r"""The corpus sweep: `jaxmc sweep` = the reference's `make test` contract
(`tlc *tla`, /root/reference/Makefile:6-7) — check every checkable
spec+cfg with its EXPECTED verdict, including the models whose defining
property is an expected violation. One manifest drives both the sweep and
the pytest pins (tests/test_corpus.py parametrizes over it).

Verdicts: "ok" (clean pass), "assumes" (ASSUME-calculator module, no
behavior spec), or "violation:<kind>" where kind is the Violation.kind the
checker must report (invariant/property/assert/deadlock).

Statuses (VERDICT r2 weak #2): every case resolves to "pass", "fail", or
"skip" — SKIP is its OWN category, never a pass. The expected jax
compile-set is pinned per case (`jax="yes"`): a model that used to
compile on the jax backend and stops compiling is a FAILURE, not a
silent skip. `jaxmc sweep --backend jax` runs each case in a fresh
subprocess with a wall-clock timeout (JAXMC_SWEEP_TIMEOUT, default 900 s)
so one pathological XLA compile cannot wedge the whole sweep.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

REFERENCE = os.environ.get("JAXMC_REFERENCE", "/root/reference")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SS = "examples/SpecifyingSystems"


@dataclass
class Case:
    spec: str                      # path, relative to root
    root: str = "ref"              # "ref" (reference) | "repo"
    cfg: Optional[str] = None      # defaults to spec with .cfg
    expect: str = "ok"             # ok | assumes | violation:<kind>
    distinct: Optional[int] = None
    generated: Optional[int] = None
    no_deadlock: bool = False
    includes: Tuple[str, ...] = ()  # extra -I dirs, relative to root kind
    slow: bool = False             # excluded from the default sweep/pins
    # the pinned jax compile-set: "yes" = must compile AND match the same
    # pins on the jax backend; "skip" = known outside the compilable
    # subset (recursion/CHOOSE-heavy — the interp remains its checker)
    jax: str = "skip"
    # the pinned EXPANSION MODE (ISSUE 5): "compiled" | "hybrid" |
    # "interp-arms" as observed in SWEEP_JAX_r05. A case that SLIDES
    # toward the interpreter (compiled -> hybrid/interp-arms, hybrid ->
    # interp-arms) FAILS the sweep — a silent demotion is a perf
    # regression, not a pass. Cases pinned "interp-arms" skip kernel
    # construction entirely (TpuExplorer pin_interp_arms): building
    # kernels the engine immediately demotes burned 245s of the r05
    # sweep (213s on MCInnerSerial alone). JAXMC_MODE_PIN=0 lifts the
    # pins for one sweep — the diagnosis mode that builds everything
    # and logs each arm's demotion reason.
    mode: Optional[str] = None
    # DERIVED mode pin (ISSUE 15): for mode="interp-arms" cases whose
    # demotions the analyze/verdicts.py taxonomy covers, the PREDICTOR
    # (not the measured pin) skips the futile kernel builds — and the
    # sweep asserts full coverage: a predictor that stops predicting
    # every arm FAILS the case loudly instead of silently re-paying
    # the builds the pin existed to kill (the MCInnerSerial 213s).
    # JAXMC_PIN_DERIVE=0 falls back to the measured pin for one sweep.
    pin_derived: bool = False
    # lane-capacity floors the default sampler under-observes for this
    # model (e.g. MCInnerSequential's opQ outgrows the sampled max):
    # passed to the device backend as Bounds(seq_cap=..., ...)
    seq_cap: Optional[int] = None
    grow_cap: Optional[int] = None
    kv_cap: Optional[int] = None
    # steady-state RESIDENT capacity buckets for this case (ISSUE 6):
    # the manifest-recorded floor for {SC, FCap, AccCap, VC} so a bench
    # or kernelbench run compiles ONCE and never grows mid-window.  The
    # persisted capacity profile (compile/cache.py) max-merges over
    # this; the manifest value is the committed, review-able record.
    res_caps: Optional[dict] = None
    # MESH capacity record (ISSUE 8): {SC, FC, TRL, GAM16} per-SHARD
    # buckets for the mesh-resident engine at the bench device counts
    # (measured at D=4 in this container; max-merged with the per-
    # (D, exchange) learned profile, so other D start close and learn
    # the rest).  jaxmc.meshbench passes it to MeshExplorer(mesh_caps=).
    # PR 10 adds the optional MSL key — the superstep controller's
    # learned levels-per-dispatch — so a cold engine skips the
    # 1 -> 2 -> 4 dispatch ramp and `mesh.host_syncs` drops below the
    # level count from the first run.
    mesh_caps: Optional[dict] = None
    # LINT surface (ISSUE 9, `make lint-corpus`): diagnostic codes this
    # pair is WAIVED for (intentional fixture constructs — each waiver
    # carries a comment at the case naming why), and, for lint-only
    # fixtures, the codes the pair MUST produce.  A lint_only case is
    # never swept/checked — it exists to exercise the linter.
    lint_waive: Tuple[str, ...] = ()
    lint_only: bool = False
    lint_expect: Tuple[str, ...] = ()

    def spec_path(self) -> str:
        base = REFERENCE if self.root == "ref" else REPO
        return os.path.join(base, self.spec)

    def cfg_path(self) -> Optional[str]:
        if self.cfg == "":
            return None
        if self.cfg is not None:
            base = REFERENCE if self.root == "ref" else REPO
            return os.path.join(base, self.cfg)
        p = self.spec_path()[:-4] + ".cfg"
        return p if os.path.exists(p) else None

    def include_dirs(self) -> List[str]:
        out = []
        for inc in self.includes:
            if inc.startswith("repo:"):
                out.append(os.path.join(REPO, inc[5:]))
            else:
                out.append(os.path.join(REFERENCE, inc))
        return out


# Every reference cfg (all 21) plus the repo's MC shims. Counts are the
# TLC-semantics pins (CONSTRAINT-violating states are discarded, matching
# the golden testout2 run; see tests/test_corpus.py).
CASES: List[Case] = [
    # -- top level + tutorial variants
    Case("pcal_intro.tla", distinct=3800, generated=5850, jax="yes",
         mode="compiled"),
    # JMC301 waived: the PlusCal translator emits Termination /
    # MoneyInvariant whether or not the (absent) cfg checks them
    Case("specs/pcal_intro_buggy.tla", root="repo", cfg="",
         expect="violation:assert", jax="yes", mode="compiled",
         lint_waive=("JMC301",)),
    Case("atomic_add.tla", cfg="", distinct=5, generated=7,
         no_deadlock=True, jax="yes", mode="compiled"),
    # -- Paxos chain
    Case("examples/Paxos/MCConsensus.tla", distinct=4, generated=7,
         no_deadlock=True, jax="yes", mode="compiled"),
    Case("examples/Paxos/MCVoting.tla", distinct=77, generated=406,
         no_deadlock=True, jax="yes", mode="compiled"),
    Case("examples/Paxos/MCPaxos.tla", distinct=25, generated=82,
         jax="yes", mode="compiled"),
    # -- Specifying Systems chapters
    Case(f"{SS}/SimpleMath/SimpleMath.tla", expect="assumes"),
    Case(f"{SS}/HourClock/HourClock.tla", distinct=12, generated=24,
         jax="yes", mode="compiled"),
    Case(f"{SS}/HourClock/HourClock2.tla", distinct=12, generated=24,
         jax="yes", mode="compiled"),
    Case(f"{SS}/AsynchronousInterface/AsynchInterface.tla",
         distinct=12, generated=30, jax="yes", mode="hybrid"),
    Case(f"{SS}/AsynchronousInterface/Channel.tla",
         distinct=12, generated=30, jax="yes", mode="compiled"),
    Case(f"{SS}/AsynchronousInterface/PrintValues.tla", expect="assumes"),
    Case(f"{SS}/FIFO/MCInnerFIFO.tla", distinct=3864, generated=9660,
         jax="yes", mode="compiled"),
    Case(f"{SS}/CachingMemory/MCInternalMemory.tla",
         distinct=4408, generated=21400, jax="yes", mode="hybrid"),
    Case(f"{SS}/CachingMemory/MCWriteThroughCache.tla",
         distinct=5196, generated=28170, jax="yes", mode="hybrid"),
    Case(f"{SS}/Liveness/LiveHourClock.tla", distinct=12, generated=24,
         jax="yes", mode="compiled"),
    Case(f"{SS}/Liveness/MCLiveInternalMemory.tla",
         distinct=4408, generated=21400, jax="yes", mode="hybrid"),
    Case(f"{SS}/Liveness/MCLiveWriteThroughCache.tla",
         distinct=5196, generated=28170, jax="yes", mode="hybrid"),
    # ErrorTemporal is EXPECTED to fail (MCRealTimeHourClock.tla:43)
    Case(f"{SS}/RealTime/MCRealTimeHourClock.tla",
         expect="violation:property", distinct=216, generated=696,
         jax="yes", mode="interp-arms"),
    Case(f"{SS}/TLC/ABCorrectness.tla", distinct=20, generated=36,
         jax="yes", mode="compiled"),
    Case(f"{SS}/TLC/MCAlternatingBit.tla", distinct=240, generated=1392,
         jax="yes", mode="compiled"),
    Case(f"{SS}/AdvancedExamples/MCInnerSequential.tla",
         distinct=3528, generated=24368, jax="yes", seq_cap=8,
         mode="compiled"),
    # the golden testout2 model (6181/195, diameter 5 — TLC 1.57: 22h).
    # testout1 (the 17h log) is a SECOND run of this SAME model: both
    # logs open "4 distinct initial states" and climb to 195 distinct at
    # diameter 5; testout1 was cut off at 6032 generated with 2 states
    # on queue (no final-totals line), consistent with this 6181 final —
    # so this pin covers BOTH golden logs
    # interp-arms PINNED (ISSUE 5): the r05 sweep burned 213s building
    # 13 kernels that all demoted (the recursion in Serializable/
    # opOrder reaches every arm through the inlined response guards).
    # The pin skips kernel construction outright; run a sweep with
    # JAXMC_MODE_PIN=0 to rebuild everything and log each arm's
    # demotion reason (the path to compiling the mechanical
    # request/response arms while recursion stays demoted)
    # pin DERIVED since ISSUE 15: the recursive-operator verdict class
    # covers every arm (opOrder reaches each through the inlined
    # response guards), so the predictor skips the builds and the
    # sweep asserts it keeps doing so (JAXMC_PIN_DERIVE=0 restores the
    # measured pin for a diagnosis sweep)
    Case(f"{SS}/AdvancedExamples/MCInnerSerial.tla",
         distinct=195, generated=6181, jax="yes", mode="interp-arms",
         pin_derived=True),
    # the shipped alternative model (Proc={p1}, DataInvariant only):
    # matches NEITHER golden log (they both record 4 init states; this
    # model has 2) — counts below are this repo's cross-backend pin,
    # closing the last unswept reference cfg (21/21)
    Case(f"{SS}/AdvancedExamples/MCInnerSerial.tla",
         cfg=f"{SS}/AdvancedExamples/MCInnerSerial.cfg.alt",
         distinct=9, generated=47, jax="yes", mode="interp-arms",
         pin_derived=True),
    # -- repo MC shims for the cfg-less reference specs
    Case("specs/transfer_scaled.tla", root="repo",
         cfg="specs/transfer_scaled.cfg",
         distinct=153701, generated=311153, slow=True, jax="yes",
         mode="compiled",
         # kernelbench rung (ISSUE 6): steady resident buckets so the
         # warm-up compile covers the whole run
         res_caps={"SC": 1 << 18, "FCap": 1 << 16, "AccCap": 1 << 17,
                   "VC": 1 << 13, "chunk": 2048},
         mesh_caps={"SC": 1 << 17, "FC": 1 << 13, "TRL": 32,
                    "GAM16": 32, "MSL": 32}),
    Case("specs/MCraftMicro.tla", root="repo",
         cfg="specs/MCraft_micro.cfg", includes=("examples",),
         distinct=694, generated=6185, jax="yes", mode="compiled",
         res_caps={"SC": 1 << 12, "FCap": 1 << 9, "AccCap": 1 << 12,
                   "VC": 1 << 11, "chunk": 256},
         mesh_caps={"SC": 1 << 12, "FC": 1 << 9, "TRL": 32,
                    "GAM16": 32, "MSL": 32}),
    # mode=compiled proven by the BENCH_r02 resident-mode completion
    # (resident refuses hybrid/interp-arms outright)
    Case("specs/MCraftMicro.tla", root="repo",
         cfg="specs/MCraft_3s_bench.cfg", includes=("examples",),
         distinct=76654, generated=1138651, slow=True, jax="yes",
         mode="compiled",
         # the bench.py full rung's steady caps (one warm-up compile
         # covers the run; the persisted profile max-merges over this)
         res_caps={"SC": 1 << 18, "FCap": 1 << 16, "AccCap": 1 << 17,
                   "VC": 1 << 13},
         # meshbench rung (ISSUE 8): per-shard mesh-resident buckets
         mesh_caps={"SC": 1 << 17, "FC": 1 << 14, "TRL": 64,
                    "GAM16": 32, "MSL": 64}),
    Case("specs/MCtextbookSI.tla", root="repo",
         cfg="specs/MCtextbookSI_small.cfg", includes=("examples",),
         distinct=569, generated=945, jax="yes", mode="interp-arms"),
    # SI is EXPECTED non-serializable (textbookSnapshotIsolation.tla:91-96)
    Case("specs/MCtextbookSI.tla", root="repo",
         cfg="specs/MCtextbookSI_skew.cfg", includes=("examples",),
         expect="violation:invariant", slow=True),
    Case("specs/MCserializableSI.tla", root="repo",
         cfg="specs/MCserializableSI_small.cfg", includes=("examples",),
         distinct=569, generated=945, jax="yes", mode="interp-arms"),
    # fast-CI seeded write-skew: SI MUST reach a non-serializable history
    # (textbookSnapshotIsolation.tla:91-96; VERDICT r2 weak #3)
    Case("specs/MCtextbookSI.tla", root="repo",
         cfg="specs/MCtextbookSI_skew_fast.cfg", includes=("examples",),
         expect="violation:invariant", jax="yes", mode="interp-arms"),
    # SSI at its documented envelope floor (2 keys x 3 txns, seeded):
    # serializability HOLDS while write skew is attempted and aborted
    Case("specs/MCserializableSI.tla", root="repo",
         cfg="specs/MCserializableSI_env.cfg", includes=("examples",),
         slow=True),
    # VIEW/CONSTRAINT parity fixtures (PR 3), now first-class manifest
    # cases: cfg VIEW compiles on the jax backend since ISSUE 6 (dedup
    # keys on the compiled view's value lanes), and both serve as
    # kernelbench rungs with committed res_caps records
    Case("specs/viewtoy.tla", root="repo", cfg="specs/viewtoy.cfg",
         distinct=5, generated=11, jax="yes", mode="compiled",
         res_caps={"SC": 256, "FCap": 64, "AccCap": 128, "VC": 64,
                   "chunk": 64}),
    # JMC301 waived: AssertBound is a deliberate spare CONSTRAINT the
    # parity tests swap in for the Assert-raising discard path
    Case("specs/constoy.tla", root="repo", cfg="specs/constoy.cfg",
         distinct=21, generated=43, jax="yes", mode="compiled",
         lint_waive=("JMC301",),
         res_caps={"SC": 256, "FCap": 64, "AccCap": 128, "VC": 64,
                   "chunk": 64}),
    # cross-model batching fixture family (ISSUE 13): one module, four
    # cfgs differing ONLY in liftable constant values — layout-
    # compatible by construction, so the serve fleet and `make
    # batch-check` can prove the vmapped multi-model engine in
    # containers without /root/reference.  batchtoy_bad's Bound sits
    # below the reachable x maximum: the mixed-batch scenario (one
    # member violates, the rest run to exhaustion).
    Case("specs/batchtoy.tla", root="repo",
         cfg="specs/batchtoy_a.cfg",
         distinct=28, generated=29, jax="yes", mode="compiled"),
    Case("specs/batchtoy.tla", root="repo",
         cfg="specs/batchtoy_b.cfg",
         distinct=40, generated=41, jax="yes", mode="compiled"),
    Case("specs/batchtoy.tla", root="repo",
         cfg="specs/batchtoy_c.cfg",
         distinct=20, generated=21, jax="yes", mode="compiled"),
    Case("specs/batchtoy.tla", root="repo",
         cfg="specs/batchtoy_d.cfg",
         distinct=32, generated=33, jax="yes", mode="compiled"),
    Case("specs/batchtoy.tla", root="repo",
         cfg="specs/batchtoy_bad.cfg",
         expect="violation:invariant", jax="yes", mode="compiled"),
    # bench-scale kernelbench rungs (ISSUE 6): wide-shallow variants of
    # the VIEW/SYMMETRY fixtures sized so states/sec measures
    # throughput; `make bench-check`'s kernel-vs-interp leg gates the
    # cpu-XLA kernel against the serial interpreter on each
    Case("specs/viewtoy_scaled.tla", root="repo",
         cfg="specs/viewtoy_scaled.cfg",
         distinct=18432, generated=239617, jax="yes", mode="compiled",
         res_caps={"SC": 1 << 15, "FCap": 1 << 12, "AccCap": 1 << 15,
                   "VC": 1 << 13, "chunk": 1024},
         # measured mesh-resident shard caps at D=4 in this container
         # (SC grew 256 -> 65536 over 9 redo recompiles without it)
         mesh_caps={"SC": 1 << 16, "FC": 1 << 11, "TRL": 32,
                    "GAM16": 32, "MSL": 32}),
    # out-of-core overflow fixture (ISSUE 12): a wide-state rung whose
    # exact dedup keys cost >7x a fingerprint; `make ooc-check` forces
    # a device seen cap at ~17% of its state count and pins the capped
    # (tier-spilling) and fingerprint-mode runs bit-identical to this
    # uncapped record.  NoMeet (the ooc_scaled_bad.cfg violation rung)
    # is deliberately unused here — JMC301 waived.
    Case("specs/ooc_scaled.tla", root="repo",
         cfg="specs/ooc_scaled.cfg",
         distinct=3072, generated=12289, jax="yes", mode="compiled",
         lint_waive=("JMC301",),
         res_caps={"SC": 1 << 13, "FCap": 256, "AccCap": 1 << 10,
                   "VC": 512, "chunk": 256}),
    Case("specs/symtoy_scaled.tla", root="repo",
         cfg="specs/symtoy_scaled.cfg", no_deadlock=True,
         distinct=10725, generated=65365, jax="yes", mode="compiled",
         res_caps={"SC": 1 << 15, "FCap": 1 << 12, "AccCap": 1 << 14,
                   "VC": 1 << 13, "chunk": 1024},
         mesh_caps={"SC": 1 << 15, "FC": 1 << 11, "TRL": 32,
                    "GAM16": 32, "MSL": 32}),
    # device SYMMETRY toys (orbit-canonical counts; deadlock expected
    # when every process exhausts its turns)
    Case("specs/symtoy.tla", root="repo", cfg="specs/symtoy.cfg",
         no_deadlock=True, distinct=22, generated=33, jax="yes",
         mode="compiled",
         res_caps={"SC": 256, "FCap": 64, "AccCap": 128, "VC": 64,
                   "chunk": 64}),
    # ISSUE 5 disclosure fixtures (repo-local, no reference needed):
    # identity-group SYMMETRY must say sym=identity, never claim an
    # UNREDUCED-FALLBACK divergence...
    Case("specs/symid.tla", root="repo", cfg="specs/symid.cfg",
         distinct=4, generated=4, jax="yes", mode="compiled"),
    # ...and an arm whose unguarded SUBSET-of-symbolic-set assignment
    # demotes AT BUILD TIME with a NAMED per-arm reason — the
    # repo-local representative of the hybrid class, pinning the
    # mode-slide failure path
    Case("specs/interparm_toy.tla", root="repo",
         cfg="specs/interparm_toy.cfg", distinct=19, generated=29,
         jax="yes", mode="hybrid"),
    # POR fixture family (ISSUE 15): independent per-element counters,
    # so the Step arms pairwise commute (analyze/independence.py) and
    # the --por persistent-set filter gets its measured reduction.
    # Unreduced counts pinned here; `make por-check` runs the reduced
    # legs and gates verdict parity + >=30% explored-state reduction.
    # JMC301 waived on all three: Bounded/NoFire are deliberate spare
    # predicates — each cfg checks the subset its rung needs
    Case("specs/portoy.tla", root="repo", cfg="specs/portoy.cfg",
         expect="violation:deadlock", distinct=80, generated=185,
         jax="yes", mode="compiled", lint_waive=("JMC301",)),
    Case("specs/portoy.tla", root="repo", cfg="specs/portoy_ok.cfg",
         no_deadlock=True, distinct=150, generated=366,
         jax="yes", mode="compiled", lint_waive=("JMC301",)),
    # jax engines report the level-batched violation (counts differ
    # from the interp's mid-level stop by design): verdict-only pin
    Case("specs/portoy.tla", root="repo", cfg="specs/portoy_bad.cfg",
         expect="violation:invariant", jax="yes", mode="compiled",
         lint_waive=("JMC301",)),
    # raft-shaped dynamic-key fixture (ISSUE 18): per-process message
    # table msgs[self] (element-commuting Send arms), a DYNAMIC \E arm
    # whose binder key resolves to a domain key set, and a CONSTANT-
    # keyed element read.  Unreduced counts pinned here; the por-check
    # device legs gate >=30% reduction with por.engine=device
    Case("specs/msgstoy.tla", root="repo", cfg="specs/msgstoy.cfg",
         no_deadlock=True, distinct=324, generated=1108,
         jax="yes", mode="compiled"),
    # DERIVED interp-arms fixture (ISSUE 15): both arms are unsized
    # dynamic \E shapes (multi-binder / nested) that the verdict
    # taxonomy predicts with ground.py's exact reason strings — the
    # repo-local pin_derived representative (no /root/reference needed)
    Case("specs/dyntoy.tla", root="repo", cfg="specs/dyntoy.cfg",
         distinct=8, generated=49, jax="yes", mode="interp-arms",
         pin_derived=True),
    # LINT-ONLY fixture (ISSUE 9): deliberately unclean — a dead
    # action, an unused CONSTANT/VARIABLE/definition, a cfg naming an
    # undefined invariant, an unassigned CONSTANT, and a CHOOSE over
    # the symmetry set.  `make lint-corpus` asserts every expected
    # diagnostic class fires; no search ever runs it.
    Case("specs/linttoy.tla", root="repo", cfg="specs/linttoy.cfg",
         lint_only=True,
         lint_expect=("JMC101", "JMC102", "JMC201", "JMC202",
                      "JMC203", "JMC301", "JMC302")),
]

# mode-slide severity order: a case may only move LEFT (toward
# "compiled") without failing its pin
_MODE_ORDER = {"compiled": 0, "hybrid": 1, "interp-arms": 2}


def mode_pins_enabled() -> bool:
    """The JAXMC_MODE_PIN=0 escape hatch: one sweep with every pin
    lifted builds every kernel again and logs per-arm demotion reasons
    — the diagnosis pass for un-demoting arms."""
    return os.environ.get("JAXMC_MODE_PIN", "1") != "0"


def case_for_cfg(cfg_basename: str) -> Optional[Case]:
    """Manifest lookup by cfg basename (bench.py uses it to assert the
    full rung's resumed counts against the pinned totals)."""
    for c in CASES:
        p = c.cfg_path()
        if p and os.path.basename(p) == cfg_basename:
            return c
    return None


def run_case(case: Case, backend: str = "interp"):
    """Returns (status, detail, result|None, mode|None); status is
    'pass' | 'fail' | 'skip'; mode (jax backend only) is the expansion
    execution mode — 'compiled' | 'hybrid' | 'interp-arms'.
    SKIP only arises on the jax backend, only for cases the
    manifest does NOT pin into the compile-set (jax='yes'): a pinned
    case that stops compiling FAILS (VERDICT r2 weak #2)."""
    from .front.cfg import ModelConfig, parse_cfg
    from .sem.modules import Loader, bind_model
    from .engine.explore import Explorer

    if case.lint_only:
        return "skip", ("lint-only fixture (make lint-corpus checks "
                        "it); not a checkable model"), None, None
    spec = case.spec_path()
    cfgp = case.cfg_path()
    if cfgp:
        with open(cfgp) as fh:
            cfg = parse_cfg(fh.read())
    else:
        cfg = ModelConfig(specification="Spec")
    if case.no_deadlock:
        cfg.check_deadlock = False
    ldr = Loader([os.path.dirname(spec)] + case.include_dirs())
    mod = ldr.load_path(spec)

    if case.expect == "assumes":
        from .sem.eval import eval_expr, _bool, Ctx
        from .sem.modules import bind_model_defs
        defs = bind_model_defs(mod, cfg)
        ctx = Ctx(defs)
        n = 0
        for a in mod.assumes:
            if not _bool(eval_expr(a.expr, ctx), "ASSUME"):
                return "fail", "ASSUME violated", None, None
            n += 1
        return "pass", f"{n} assumptions checked", None, None

    model = bind_model(mod, cfg)
    note = ""
    mode = None
    if backend == "jax":
        from .backend.bfs import TpuExplorer
        from .compile.vspec import Bounds, CompileError, ModeError
        from . import native_store
        b = Bounds()
        if case.seq_cap:
            b.seq_cap = case.seq_cap
        if case.grow_cap:
            b.grow_cap = case.grow_cap
        if case.kv_cap:
            b.kv_cap = case.kv_cap
        pin = case.mode if mode_pins_enabled() else None
        if pin is not None and pin not in _MODE_ORDER:
            # a typo'd pin must not silently disable enforcement (every
            # real mode would read as an "improvement" against it)
            return "fail", (f"manifest defect: unknown mode pin {pin!r} "
                            f"(expected one of "
                            f"{sorted(_MODE_ORDER)})"), None, None
        # DERIVED pin (ISSUE 15): the predictor, not the measured pin,
        # skips the futile builds — unless the operator lifted it
        # (JAXMC_PIN_DERIVE=0) or disabled prediction outright
        from . import analyze as _analyze
        derive = (case.pin_derived and pin == "interp-arms"
                  and os.environ.get("JAXMC_PIN_DERIVE", "1") != "0"
                  and _analyze.predict_enabled())
        try:
            # instrument compile cost (VERDICT r3 weak #3): construction
            # = grounding + kernel build + forced abstract tracing;
            # the run then adds the XLA compiles proper
            t_c0 = time.time()
            ex = TpuExplorer(model, store_trace=False, bounds=b,
                             host_seen=native_store.is_available(),
                             pin_interp_arms=(pin == "interp-arms"
                                              and not derive))
            build_s = time.time() - t_c0
            # honest per-case execution-mode disclosure (VERDICT r4
            # weak #3/#6): how much of the EXPANSION hot loop actually
            # runs compiled, and whether cfg SYMMETRY is device-reduced
            # or silently unreduced (divergence-by-design from TLC)
            n_arms = len(ex.arms)
            n_fb = len(ex.fb_arms)
            if n_fb == 0:
                mode = "compiled"
            elif ex.A > 0:
                mode = "hybrid"
            else:
                mode = "interp-arms"  # device does hashing/dedup only
            # symmetry disclosure, three-way (ISSUE 5 satellite):
            # build_canon2 returns None BY DESIGN for identity groups
            # (symmetry2.py) — no reduction exists to diverge from, so
            # sym=identity; only a genuine CompileError fallback
            # (ex._sym_fallback) claims divergence. MCPaxos's line used
            # to report a divergence that does not exist.
            sym_note = ""
            if model.symmetry is not None:
                if ex.canon_fn is not None:
                    sym_note = ", sym=device-reduced"
                elif ex._sym_fallback:
                    sym_note = (", sym=UNREDUCED-FALLBACK (counts "
                                "diverge from TLC's reduced ones)")
                else:
                    sym_note = (", sym=identity (every declared "
                                "permutation is the identity; counts "
                                "match TLC)")
            note = (f" [build {build_s:.1f}s, mode={mode}, "
                    f"A={ex.A} compiled instances, "
                    f"{n_arms - n_fb}/{n_arms} arms compiled, "
                    f"W={ex.W} lanes"
                    + (f", {n_fb} arms interp-demoted"
                       if ex.fb_arms else "")
                    + (f", {len(ex.fb_invs)} invs interp-demoted"
                       if ex.fb_invs else "") + sym_note
                    + (" [mode pinned]" if pin == "interp-arms" else "")
                    + "]")
            # per-arm demotion reason table (VERDICT r5 #4): name each
            # demoted arm and why — the evidence needed to un-demote
            # mechanical arms — instead of only a count
            if ex.fb_arms and pin != "interp-arms":
                reasons = "; ".join(
                    f"{a.label or 'Next'}: {reason[:100]}"
                    for a, reason in ex.fb_arms[:8])
                more = len(ex.fb_arms) - 8
                note += (f" [demoted arms: {reasons}"
                         + (f"; +{more} more" if more > 0 else "") + "]")
            # derived-pin coverage assertion (ISSUE 15): the measured
            # pin stays as the fallback CONTRACT — if the predictor
            # stops predicting every arm, the futile builds the pin
            # existed to kill are back, and the sweep says so loudly
            if derive:
                if len(ex.arm_verdicts) < len(ex.arms):
                    return "fail", (
                        f"PREDICTOR REGRESSION: pin_derived case "
                        f"predicted only {len(ex.arm_verdicts)}/"
                        f"{len(ex.arms)} arm demotions — the measured "
                        f"interp-arms pin would have skipped every "
                        f"build (diagnose with JAXMC_PIN_DERIVE=0)"
                        f"{note}"), None, mode
                note += " [pin derived by predictor]"
            # mode-pin enforcement BEFORE the run: a slide toward the
            # interpreter fails fast — no point paying the search for a
            # case whose compile coverage already regressed
            if pin is not None and mode != pin:
                if _MODE_ORDER.get(mode, 3) > _MODE_ORDER.get(pin, 3):
                    return "fail", (
                        f"REGRESSION: expansion mode slid from pinned "
                        f"'{pin}' to '{mode}'{note}"), None, mode
                note += (f" [mode improved vs pinned '{pin}' — update "
                         f"the manifest]")
            r = ex.run()
        except (CompileError, ModeError) as ex:
            if isinstance(ex, ModeError) and "hybrid" in str(ex) \
                    and not native_store.is_available():
                # a host capability gap, not a code regression: hybrid
                # pins need the native store's host_seen mode
                return "skip", (f"hybrid needs the native store "
                                f"(unavailable on this host): "
                                f"{ex}"), None, None
            if case.jax == "yes":
                return "fail", (f"REGRESSION: pinned into the jax "
                                f"compile-set but no longer compiles "
                                f"({ex})"), None, None
            return "skip", f"outside jax subset: {ex}", None, None
        if case.jax != "yes":
            note += " [compiles despite jax='skip' — update the manifest]"
    else:
        r = Explorer(model).run()

    if case.expect == "ok":
        if not r.ok:
            return "fail", f"unexpected {r.violation.kind} violation " \
                           f"({r.violation.name})", r, mode
    else:
        kind = case.expect.split(":", 1)[1]
        if r.ok or r.violation.kind != kind:
            return "fail", f"expected a {kind} violation, got " \
                           f"{'ok' if r.ok else r.violation.kind}", r, mode
    if case.distinct is not None and r.distinct != case.distinct:
        return "fail", f"distinct {r.distinct} != pinned " \
                       f"{case.distinct}", r, mode
    if case.generated is not None and r.generated != case.generated:
        return "fail", f"generated {r.generated} != " \
                       f"pinned {case.generated}", r, mode
    return "pass", f"{r.generated} generated / {r.distinct} distinct " \
                   f"({case.expect}){note}", r, mode


def _run_case_isolated(idx: int, backend: str, timeout_s: float):
    """One case in a fresh subprocess (CPU-pinned before first jax use)
    under a wall-clock timeout: one pathological XLA compile must not
    wedge the sweep (the round-2 jax sweep never finished on a 1-core
    box). Timeout is a FAILURE for jax='yes' cases, a skip otherwise."""
    import json
    import subprocess
    import sys
    cache_line = ""
    if backend == "jax":
        # persistent compile cache ON BY DEFAULT for sweep children
        # (ISSUE 5): repeat sweeps — and the repeat-spec pairs inside
        # one sweep (MCInternalMemory/MCLiveInternalMemory, the two
        # WriteThroughCache models) — reload their XLA programs from
        # disk instead of recompiling. enable_guarded_cache honors the
        # JAXMC_COMPILE_CACHE=off opt-out and degrades COLD on a
        # wedged/corrupt/foreign cache; the health probe is paid once
        # per cache dir per hour, not per case. The guard verdict rides
        # a JAXMC_CACHE_GUARD stdout line so a cold fallback is VISIBLE
        # in the sweep log instead of vanishing into NullTelemetry.
        cache_line = (
            "from jaxmc import obs as _obs\n"
            "from jaxmc.compile.cache import enable_guarded_cache\n"
            "_ct = _obs.Telemetry()\n"
            "enable_guarded_cache(tel=_ct)\n"
            "print('JAXMC_CACHE_GUARD ' + str(_ct.gauges.get("
            "'compile.persistent_cache_guard')))\n")
    code = (
        "import json, sys\n"
        "import jax\n"
        f"jax.config.update('jax_platforms', "
        f"{os.environ.get('JAXMC_SWEEP_PLATFORM', 'cpu')!r})\n"
        + cache_line +
        "from jaxmc.corpus import CASES, run_case\n"
        f"s, d, _, md = run_case(CASES[{idx}], backend={backend!r})\n"
        "print('JAXMC_CASE ' + json.dumps([s, d, md]))\n")
    case = CASES[idx]
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s,
                           cwd=REPO, env=dict(os.environ,
                                              PYTHONPATH=REPO))
    except subprocess.TimeoutExpired:
        if case.jax == "yes":
            return "fail", (f"REGRESSION: pinned into the jax compile-set "
                            f"but timed out after {timeout_s:.0f}s"), None
        return "skip", f"timed out after {timeout_s:.0f}s (compile?)", None
    guard_note = ""
    for line in (p.stdout or "").splitlines():
        if line.startswith("JAXMC_CACHE_GUARD ") and \
                "cold-fallback" in line:
            # a guard cold-fallback must be visible in the sweep log,
            # not silent: the whole-sweep wall-time win depends on it
            guard_note = (" [compile cache COLD: "
                          + line[len("JAXMC_CACHE_GUARD "):][:120] + "]")
    for line in (p.stdout or "").splitlines():
        if line.startswith("JAXMC_CASE "):
            s, d, md = json.loads(line[len("JAXMC_CASE "):])
            return s, d + guard_note, md
    tail = (p.stderr or "").strip().splitlines()[-1:] or ["no output"]
    return "fail", f"CRASH rc={p.returncode}: {tail[0][:160]}", None


def sweep(backend: str = "interp", include_slow: bool = False,
          log=print, isolate: Optional[bool] = None,
          metrics_out: Optional[str] = None) -> int:
    """Check the whole corpus; returns the number of failures.
    Logs explicit pass/violation/skip/fail tallies — a sweep where every
    model skips is visibly NOT a clean sweep. With metrics_out (or env
    JAXMC_SWEEP_METRICS_OUT) the per-case record — status, wall time,
    expansion mode — lands in a JSON artifact so future SWEEP logs carry
    a machine-readable phase breakdown, not only free text."""
    if isolate is None:
        isolate = backend == "jax" and \
            os.environ.get("JAXMC_SWEEP_INPROC") != "1"
    if metrics_out is None:
        metrics_out = os.environ.get("JAXMC_SWEEP_METRICS_OUT") or None
    timeout_s = float(os.environ.get("JAXMC_SWEEP_TIMEOUT", "900"))
    tallies = {"pass": 0, "fail": 0, "skip": 0}
    modes = {"compiled": 0, "hybrid": 0, "interp-arms": 0}
    expected_violations = 0
    case_records = []
    t0 = time.time()
    n = 0
    for i, case in enumerate(CASES):
        if case.slow and not include_slow:
            continue
        if case.lint_only:
            continue  # `make lint-corpus` owns these fixtures
        n += 1
        name = case.cfg or case.spec
        t1 = time.time()
        mode = None
        try:
            if isolate:
                status, detail, mode = _run_case_isolated(
                    i, backend, timeout_s)
            else:
                status, detail, _, mode = run_case(case, backend)
        except Exception as ex:  # a crash is a failure, not an abort
            status, detail = "fail", f"CRASH {type(ex).__name__}: {ex}"
        tag = {"pass": "ok  ", "fail": "FAIL", "skip": "SKIP"}[status]
        log(f"[{tag}] {name:62s} {detail} "
            f"({time.time() - t1:.1f}s)")
        tallies[status] += 1
        if status == "pass" and case.expect.startswith("violation"):
            expected_violations += 1
        if mode in modes:
            modes[mode] += 1
        case_records.append({"case": name, "status": status,
                             "expect": case.expect, "mode": mode,
                             "wall_s": round(time.time() - t1, 3),
                             "detail": detail})
    # advisor r3: disclose the platform isolated cases were pinned to —
    # `sweep --backend jax` on a TPU machine validates the CPU path
    # unless JAXMC_SWEEP_PLATFORM says otherwise, and the summary must
    # say which one actually ran
    plat_note = ""
    if isolate:
        plat_note = (", platform="
                     f"{os.environ.get('JAXMC_SWEEP_PLATFORM', 'cpu')}"
                     " [JAXMC_SWEEP_PLATFORM]")
    if backend == "jax" and not mode_pins_enabled():
        plat_note += ", MODE PINS LIFTED [JAXMC_MODE_PIN=0]"
    mode_note = ""
    if backend == "jax" and sum(modes.values()):
        # the honest coverage split (VERDICT r4 weak #3): "passes on the
        # jax backend" spans fully-compiled expansion, hybrid (some arms
        # interp-demoted), and all-interp-arms (device hashing/dedup only)
        mode_note = (f"; expansion modes: {modes['compiled']} "
                     f"fully-compiled / {modes['hybrid']} hybrid / "
                     f"{modes['interp-arms']} all-interp-arms")
    log(f"{n} corpus models: {tallies['pass']} pass "
        f"({expected_violations} expected-violation), "
        f"{tallies['skip']} SKIP (outside jax subset), "
        f"{tallies['fail']} FAIL "
        f"({time.time() - t0:.1f}s, backend={backend}{plat_note})"
        f"{mode_note}")
    if metrics_out:
        from . import obs
        art = {"schema": "jaxmc.sweep-metrics/1", "backend": backend,
               "isolated": bool(isolate),
               "platform": os.environ.get("JAXMC_SWEEP_PLATFORM", "cpu")
               if isolate else None,
               "wall_s": round(time.time() - t0, 3),
               "tallies": dict(tallies, total=n,
                               expected_violations=expected_violations),
               "modes": modes, "cases": case_records}
        obs.write_json_atomic(metrics_out, art)
        log(f"sweep metrics written to {metrics_out}")
    return tallies["fail"]
