r"""`make ooc-check` (ISSUE 12): the out-of-core seen-set gate.

Four legs over the repo-local overflow fixture (specs/ooc_scaled.tla —
wide packed rows, 3072 states, seconds-scale), one parseable
`OOC-CHECK …` line each:

  1. UNCAPPED   the exact (level-mode) run; counts must equal the
                corpus manifest pins.
  2. CAPPED     JAXMC_SEEN_CAP forces the device seen table to ~17% of
                the state count and a tiny host budget forces the disk
                tier: the run must complete EXHAUSTIVELY via tier
                spill (no truncation), with counts bit-identical to
                leg 1 and both cold tiers exercised.  The artifact
                gates against its saved baseline via `python -m
                jaxmc.obs diff --fail-on-regress` (first run snapshots
                it, like every bench-check leg).
  3. FINGERPRINT the same capped run under --seen fingerprint: counts
                must stay bit-identical, the result must report its
                collision probability, and the measured
                states-per-device-tier ratio (exact key words /
                fingerprint key words, from the artifacts' layout
                gauges) must be >= 4x — the BASELINE.md claim,
                measured every run.
  4. TRACE      the violation rung (ooc_scaled_bad.cfg) capped vs
                uncapped: the rendered counterexample must be
                byte-identical.

A container without the jax backend prints `OOC-CHECK SKIP …` and
exits 0 — parseable, never a silent pass.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SPEC = "specs/ooc_scaled.tla"
_CFG = "specs/ooc_scaled.cfg"
_CFG_BAD = "specs/ooc_scaled_bad.cfg"
#: ~17% of the rung's 3072 states (acceptance: <= 25%), still >= one
#: level's dense candidate block so the cap is never soft-breached
_SEEN_CAP = "512"
#: host-tier key budget small enough that the capped run flushes to disk
_HOST_KEYS = "1024"
_FP_WORDS = 5  # fingerprint dedup key words (4 fp words + validity)


def _run(cfg: str, metrics: Optional[str], capped: bool,
         seen: str = "auto", timeout_s: float = 600.0) -> Dict:
    cmd = [sys.executable, "-m", "jaxmc", "check",
           os.path.join(_REPO, _SPEC),
           "--cfg", os.path.join(_REPO, cfg),
           "--backend", "jax", "--platform", "cpu", "--quiet",
           "--seen", seen]
    if metrics:
        cmd += ["--metrics-out", metrics]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    if capped:
        env["JAXMC_SEEN_CAP"] = _SEEN_CAP
        env["JAXMC_TIER_HOST_KEYS"] = _HOST_KEYS
    else:
        env.pop("JAXMC_SEEN_CAP", None)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=_REPO, env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"leg timed out after {timeout_s:.0f}s"}
    out = {"rc": p.returncode, "stdout": p.stdout, "stderr": p.stderr,
           "wall_s": round(time.time() - t0, 3)}
    if "is not available" in (p.stderr or ""):
        out["skip"] = p.stderr.strip().splitlines()[-1]
        return out
    if metrics:
        try:
            with open(metrics, encoding="utf-8") as fh:
                out["summary"] = json.load(fh)
        except (OSError, ValueError) as ex:
            out["error"] = f"no metrics artifact ({ex})"
    return out


def _trace_lines(stdout: str) -> List[str]:
    """The rendered counterexample: everything from the violation
    banner on (timings stripped by taking whole lines only)."""
    lines = stdout.splitlines()
    for i, ln in enumerate(lines):
        if "is violated" in ln or "Error:" in ln:
            return lines[i:]
    return []


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.oocbench",
        description="out-of-core seen-set gate (capped exhaustive + "
                    "fingerprint parity)")
    ap.add_argument("--out-dir", default="/tmp")
    ap.add_argument("--leg-timeout", type=float, default=float(
        os.environ.get("JAXMC_OOC_CHECK_TIMEOUT", "600")))
    args = ap.parse_args(argv)

    from .corpus import case_for_cfg
    case = case_for_cfg(os.path.basename(_CFG))
    want = (case.generated, case.distinct) if case else (12289, 3072)

    # leg 1: uncapped exact
    m_exact = os.path.join(args.out_dir, "jaxmc_ooc_exact.json")
    r = _run(_CFG, m_exact, capped=False, timeout_s=args.leg_timeout)
    if r.get("skip"):
        print(f"OOC-CHECK SKIP: {r['skip']}")
        return 0
    res = (r.get("summary") or {}).get("result") or {}
    if r.get("rc") != 0 or not res.get("ok") or r.get("error"):
        print(f"OOC-CHECK FAIL uncapped: rc={r.get('rc')} "
              f"{r.get('error', '')} {(r.get('stderr') or '')[-200:]}",
              file=sys.stderr)
        return 1
    got = (res.get("generated"), res.get("distinct"))
    if got != want:
        print(f"OOC-CHECK FAIL uncapped: counts {got} != manifest "
              f"pins {want}", file=sys.stderr)
        return 1
    if res.get("seen_mode") != "exact":
        print(f"OOC-CHECK FAIL uncapped: seen_mode="
              f"{res.get('seen_mode')} (the rung must stay under "
              f"FP_THRESHOLD so exact is the auto default)",
              file=sys.stderr)
        return 1
    print(f"OOC-CHECK ok uncapped: {got[0]} gen / {got[1]} distinct "
          f"exact ({r['wall_s']}s)")

    failures = 0
    # leg 2: capped exhaustive via tier spill
    m_cap = os.path.join(args.out_dir, "jaxmc_ooc_capped.json")
    r2 = _run(_CFG, m_cap, capped=True, timeout_s=args.leg_timeout)
    res2 = (r2.get("summary") or {}).get("result") or {}
    tiers = res2.get("tiers") or {}
    if r2.get("rc") != 0 or not res2.get("ok") or \
            res2.get("truncated") or \
            (res2.get("generated"), res2.get("distinct")) != want:
        print(f"OOC-CHECK FAIL capped: rc={r2.get('rc')} "
              f"truncated={res2.get('truncated')} "
              f"reason={res2.get('trunc_reason')} counts="
              f"{(res2.get('generated'), res2.get('distinct'))} != "
              f"{want}", file=sys.stderr)
        failures += 1
    elif not tiers.get("spills") or not tiers.get("disk_keys"):
        print(f"OOC-CHECK FAIL capped: expected spill through BOTH "
              f"cold tiers, got {tiers}", file=sys.stderr)
        failures += 1
    else:
        print(f"OOC-CHECK ok capped: exhaustive at seen_cap="
              f"{_SEEN_CAP} ({tiers['spills']} spills, "
              f"host={tiers['host_keys']} disk={tiers['disk_keys']} "
              f"keys, probe={tiers['probe_wall_s']}s; {r2['wall_s']}s)")
        from .meshbench import _gate as gate
        # cold-start compile walls swing with box load; gate the
        # search/throughput surface like backend-check does
        if gate(m_cap, log=print,
                ignore_phases=("device_init", "engine_build",
                               "layout_sample", "compile_arm",
                               "tier.spill")):
            failures += 1

    # leg 3: fingerprint-mode parity + the measured per-tier ratio
    m_fp = os.path.join(args.out_dir, "jaxmc_ooc_fp.json")
    r3 = _run(_CFG, m_fp, capped=True, seen="fingerprint",
              timeout_s=args.leg_timeout)
    res3 = (r3.get("summary") or {}).get("result") or {}
    if r3.get("rc") != 0 or not res3.get("ok") or \
            (res3.get("generated"), res3.get("distinct")) != want:
        print(f"OOC-CHECK FAIL fingerprint: rc={r3.get('rc')} counts="
              f"{(res3.get('generated'), res3.get('distinct'))} != "
              f"{want}", file=sys.stderr)
        failures += 1
    elif res3.get("seen_mode") != "fingerprint" or \
            res3.get("collision_p") is None:
        print(f"OOC-CHECK FAIL fingerprint: result must report "
              f"seen_mode=fingerprint + collision_p, got "
              f"{res3.get('seen_mode')}/{res3.get('collision_p')}",
              file=sys.stderr)
        failures += 1
    else:
        # measured states-per-device-tier ratio: tier rows cost
        # (key_words)*4 bytes, so the ratio is exact key words over
        # fingerprint key words — from the artifacts' layout gauges
        pw = ((r.get("summary") or {}).get("gauges") or {}) \
            .get("layout.packed_width_lanes")
        ratio = (pw + 1) / _FP_WORDS if isinstance(pw, int) else None
        if ratio is None or ratio < 4.0:
            print(f"OOC-CHECK FAIL fingerprint: states/tier ratio "
                  f"{ratio} < 4x (packed_width={pw})", file=sys.stderr)
            failures += 1
        else:
            print(f"OOC-CHECK ok fingerprint: parity at "
                  f"{ratio:.1f}x states/device-tier, "
                  f"collision_p={res3['collision_p']:.3g} "
                  f"({r3['wall_s']}s)")

    # leg 4: violation-trace parity, capped vs uncapped
    rb0 = _run(_CFG_BAD, None, capped=False,
               timeout_s=args.leg_timeout)
    rb1 = _run(_CFG_BAD, None, capped=True, timeout_s=args.leg_timeout)
    t0_, t1_ = _trace_lines(rb0.get("stdout", "")), \
        _trace_lines(rb1.get("stdout", ""))
    if rb0.get("rc") != 1 or rb1.get("rc") != 1 or not t0_ or \
            t0_ != t1_:
        print(f"OOC-CHECK FAIL trace: capped trace differs from "
              f"uncapped (rc {rb0.get('rc')}/{rb1.get('rc')}, "
              f"{len(t0_)} vs {len(t1_)} lines)", file=sys.stderr)
        failures += 1
    else:
        print(f"OOC-CHECK ok trace: capped counterexample "
              f"byte-identical ({len(t0_)} lines)")

    print(f"ooc-check: {'FAIL' if failures else 'ok'} "
          f"({failures} failing legs)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
