r"""`make prof-check` (ISSUE 17): the profiler/ledger gate.

Per warm rung (transfer_scaled, symtoy_scaled), three legs — the same
checkpoint-then-resume recipe as the bench-check warmleg, so the timed
window is dispatch-dominated rather than compile-dominated:

  1. WARM      resident run to a truncation checkpoint (no profile);
  2. ON        `--profile` resume to the full cap, metrics artifact
               with a `prof{}` block: the per-site walls must account
               for >= JAXMC_PROF_CHECK_MIN_SHARE (default 0.90) of the
               search phase wall (obs.prof_attribution), and the HBM
               model must have registered resident buffers;
  3. OFF       the identical resume WITHOUT --profile: generated /
               distinct / diameter / ok / truncated must be
               bit-identical to leg 2 — profiling observes the search,
               it never steers it.

Both resume legs append to a TEMP ledger (JAXMC_LEDGER), which is then
gated: `obs history --fail-on-regress` over the real entries must exit
0, and the same gate over a copy with one synthesized degraded entry
(half the observed rate, later timestamp) must exit 1 — the regression
detector is proven live in the same invocation that proves the happy
path.  One parseable `PROF-CHECK …` line per assertion; a jax-less
container prints `PROF-CHECK SKIP …` and exits 0.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import shutil
import subprocess
import sys
import time
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (spec, extra check flags) — repo-local rungs with resident caps
_RUNGS = [
    ("specs/transfer_scaled.tla", []),
    ("specs/symtoy_scaled.tla", ["--no-deadlock"]),
]
_WARM_STATES = 4000
_FULL_STATES = 20000


def _min_share() -> float:
    try:
        return float(os.environ.get("JAXMC_PROF_CHECK_MIN_SHARE", ""))
    except ValueError:
        return 0.90


def _have_jax() -> bool:
    import importlib.util
    return importlib.util.find_spec("jax") is not None


def _check(spec: str, extra: List[str], metrics: Optional[str],
           ledger: Optional[str], timeout_s: float) -> Dict:
    cmd = [sys.executable, "-m", "jaxmc", "check",
           os.path.join(_REPO, spec),
           "--backend", "jax", "--platform", "cpu", "--resident",
           "--no-trace", "--quiet"] + extra
    if metrics:
        cmd += ["--metrics-out", metrics]
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    env["JAXMC_LEDGER"] = ledger if ledger else "off"
    t0 = time.time()
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=_REPO, env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"error": f"leg timed out after {timeout_s:.0f}s"}
    out = {"rc": p.returncode, "stderr": p.stderr,
           "wall_s": round(time.time() - t0, 3)}
    if metrics:
        try:
            with open(metrics, encoding="utf-8") as fh:
                out["summary"] = json.load(fh)
        except (OSError, ValueError) as ex:
            out["error"] = f"no metrics artifact ({ex})"
    return out


def _counts(summary: Dict) -> tuple:
    res = summary.get("result") or {}
    return tuple(res.get(k) for k in
                 ("ok", "generated", "distinct", "diameter",
                  "truncated"))


def _history_rc(ledger: str, extra: Optional[List[str]] = None) -> int:
    """`obs history --fail-on-regress` in-process; output swallowed."""
    from .obs.report import main as obs_main
    buf = io.StringIO()
    import contextlib
    with contextlib.redirect_stdout(buf):
        rc = obs_main(["history", "--ledger", ledger,
                       "--fail-on-regress"] + (extra or []))
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.profcheck",
        description="profiler attribution + parity + ledger gate")
    ap.add_argument("--out-dir", default="/tmp")
    ap.add_argument("--leg-timeout", type=float, default=float(
        os.environ.get("JAXMC_PROF_CHECK_TIMEOUT", "600")))
    args = ap.parse_args(argv)

    if not _have_jax():
        print("PROF-CHECK SKIP: no jax in this container")
        return 0
    os.makedirs(args.out_dir, exist_ok=True)
    ledger = os.path.join(args.out_dir, "jaxmc_prof_check_ledger.jsonl")
    if os.path.exists(ledger):
        os.unlink(ledger)  # the gate judges THIS invocation's legs
    failures = 0
    min_share = _min_share()

    from .obs.prof import attribution

    for spec, extra in _RUNGS:
        name = os.path.splitext(os.path.basename(spec))[0]
        ck = os.path.join(args.out_dir, f"jaxmc_prof_check_{name}.ck")
        m_on = os.path.join(args.out_dir,
                            f"jaxmc_prof_check_{name}_on.json")
        m_off = os.path.join(args.out_dir,
                             f"jaxmc_prof_check_{name}_off.json")
        # leg 1: warm checkpoint (excluded from the profiled window)
        r = _check(spec, extra + ["--max-states", str(_WARM_STATES),
                                  "--checkpoint", ck],
                   None, None, args.leg_timeout)
        if r.get("error") or r.get("rc") not in (0, 3):
            print(f"PROF-CHECK FAIL {name} warm leg: rc={r.get('rc')} "
                  f"{r.get('error') or (r.get('stderr') or '')[-200:]}",
                  file=sys.stderr)
            failures += 1
            continue
        # leg 2: profiled resume
        r_on = _check(spec, extra + ["--max-states", str(_FULL_STATES),
                                     "--resume", ck, "--profile"],
                      m_on, ledger, args.leg_timeout)
        # leg 3: identical resume, profile off
        r_off = _check(spec, extra + ["--max-states", str(_FULL_STATES),
                                      "--resume", ck],
                       m_off, ledger, args.leg_timeout)
        bad = [(t, r2) for t, r2 in (("on", r_on), ("off", r_off))
               if r2.get("error") or "summary" not in r2]
        if bad:
            for t, r2 in bad:
                print(f"PROF-CHECK FAIL {name} {t} leg: "
                      f"rc={r2.get('rc')} {r2.get('error') or ''} "
                      f"{(r2.get('stderr') or '')[-200:]}",
                      file=sys.stderr)
            failures += 1
            continue
        s_on, s_off = r_on["summary"], r_off["summary"]
        # parity: profiling must not perturb the search
        if _counts(s_on) != _counts(s_off):
            print(f"PROF-CHECK FAIL {name}: profile-on counts "
                  f"{_counts(s_on)} != profile-off {_counts(s_off)}",
                  file=sys.stderr)
            failures += 1
        else:
            print(f"PROF-CHECK ok {name} parity: counts "
                  f"{_counts(s_on)} bit-identical on/off")
        # attribution: the profiled sites must explain the search wall
        prof = s_on.get("prof")
        if not prof or not prof.get("sites"):
            print(f"PROF-CHECK FAIL {name}: no prof block in the "
                  f"--profile artifact", file=sys.stderr)
            failures += 1
            continue
        att = attribution(s_on)
        share = att.get("share")
        if share is None or share < min_share:
            print(f"PROF-CHECK FAIL {name}: attributed "
                  f"{att.get('attributed_wall_s')}s of "
                  f"{att.get('search_wall_s')}s search wall "
                  f"(share={share}) < {min_share:.0%}",
                  file=sys.stderr)
            failures += 1
        else:
            print(f"PROF-CHECK ok {name} attribution: "
                  f"{share:.0%} of {att['search_wall_s']:.2f}s search "
                  f"wall across {len(prof['sites'])} sites")
        hbm = (prof.get("hbm") or {})
        if not hbm.get("peak_bytes"):
            print(f"PROF-CHECK FAIL {name}: HBM model registered no "
                  f"resident buffers", file=sys.stderr)
            failures += 1
        else:
            print(f"PROF-CHECK ok {name} hbm: peak "
                  f"{hbm['peak_bytes']:,} bytes over "
                  f"{len(hbm.get('buffers') or {})} buffers")

    # ledger gate: the legs above appended; the real history must pass…
    if not os.path.exists(ledger):
        print("PROF-CHECK FAIL: no ledger entries were appended",
              file=sys.stderr)
        failures += 1
    else:
        rc = _history_rc(ledger)
        if rc != 0:
            print(f"PROF-CHECK FAIL: obs history --fail-on-regress "
                  f"rc={rc} on the fresh ledger", file=sys.stderr)
            failures += 1
        else:
            print("PROF-CHECK ok ledger: history gate green on "
                  "this invocation's entries")
        # …and a synthesized degraded latest entry must trip it
        from .obs import ledger as led
        entries = led.read_entries(ledger)
        rated = [e for e in entries
                 if isinstance(e.get("states_per_sec"), (int, float))]
        if rated:
            worst = dict(rated[-1])
            worst.pop("id", None)
            degraded = led.make_entry(
                worst["rung"], worst["states_per_sec"] * 0.5,
                (worst.get("ts") or time.time()) + 60.0,
                run="degraded", kind=worst.get("kind", "metrics"),
                platform=worst.get("platform"),
                env=worst.get("env"), source="profcheck-synthetic")
            bad_ledger = ledger.replace(".jsonl", "_degraded.jsonl")
            shutil.copyfile(ledger, bad_ledger)
            led.append_entries([degraded], bad_ledger)
            rc2 = _history_rc(bad_ledger)
            if rc2 != 1:
                print(f"PROF-CHECK FAIL: degraded ledger gate rc={rc2}"
                      f" != 1 — regression detector asleep",
                      file=sys.stderr)
                failures += 1
            else:
                print("PROF-CHECK ok ledger: synthesized 2x slowdown "
                      "trips --fail-on-regress (rc 1)")
        else:
            print("PROF-CHECK FAIL: no rated ledger entries to "
                  "synthesize a regression from", file=sys.stderr)
            failures += 1

    print(f"PROF-CHECK {'FAIL' if failures else 'ok'}: "
          f"{failures} failure(s)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
