r"""Kernel-vs-interpreter bench leg (ISSUE 6): `python -m jaxmc.kernelbench`.

The whole point of the compiled path is to outrun the exact interpreter —
BENCH_r04 measured it at 0.678x instead.  This driver turns that into a
GATE: for one spec it measures, on the same workload,

  interp  the serial exact interpreter (engine/explore.py), fresh
          Explorer per repeat, min-of-repeats wall;
  kernel  the cpu-XLA/device engine (tpu/bfs.py), built once; the FIRST
          run is the untimed warm-up (XLA compile + capacity training +
          capacity-profile persist), then min-of-repeats over fully-warm
          re-runs — the steady-state methodology PR 5 established for
          the raft bench, applied per corpus rung.

Counts must be BIT-IDENTICAL between the two engines (the packed
encoding must not change what is counted), and two metrics artifacts
(schema jaxmc.metrics/2) are written so the gate runs through the same
`python -m jaxmc.obs diff --fail-on-regress` machinery as every other
bench-check leg: artifacts are ordered [interp, kernel], so a kernel
slower than the interpreter raises the REGRESS states/sec flag and
fails the leg.

Used by `make bench-check` over the repo-local rungs (transfer_scaled,
viewtoy, symtoy — no reference corpus needed).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_model(spec: str, cfg: Optional[str], includes):
    from .front.cfg import parse_cfg, ModelConfig
    from .sem.modules import Loader, bind_model
    if cfg is None:
        guess = os.path.splitext(spec)[0] + ".cfg"
        cfg = guess if os.path.exists(guess) else None
    if cfg:
        with open(cfg, encoding="utf-8") as fh:
            mc = parse_cfg(fh.read())
    else:
        mc = ModelConfig(specification="Spec")
    from .corpus import case_for_cfg
    pin = case_for_cfg(os.path.basename(cfg)) if cfg else None
    if pin is not None and pin.no_deadlock:
        mc.check_deadlock = False
    ldr = Loader([os.path.dirname(os.path.abspath(spec))] +
                 list(includes))
    return bind_model(ldr.load_path(spec), mc), pin


def _artifact(path: str, backend: str, spec: str, platform: str,
              wall_s: float, result, repeats: int, note: str) -> None:
    from . import obs
    env = obs.environment_meta()
    env["platform"] = platform
    art = {
        "schema": "jaxmc.metrics/2",
        "started_at": time.time(),
        "wall_s": round(wall_s, 6),
        "backend": backend,
        "spec": spec,
        "phases": [{"name": "search", "wall_s": round(wall_s, 6),
                    "count": repeats}],
        "counters": {},
        "gauges": {"kernelbench.note": note},
        "levels": [],
        "env": env,
        "result": {"ok": bool(result.ok),
                   "distinct": int(result.distinct),
                   "generated": int(result.generated),
                   "diameter": int(result.diameter),
                   "truncated": bool(result.truncated),
                   "wall_s": round(wall_s, 6)},
    }
    obs.write_json_atomic(path, art)
    # ISSUE 17: each gate leg lands a trajectory point in the run ledger
    obs.append_summary(art, source=path)


def run_leg(spec: str, cfg: Optional[str], out_dir: str,
            repeats: int = 2, interp_repeats: int = 1,
            engine: str = "resident", includes=(), log=print) -> int:
    """Measure both engines, write the two artifacts, run the gate.
    Returns the gate's exit status (0 ok, 1 kernel lost)."""
    from .engine.explore import Explorer
    from .backend.bfs import TpuExplorer

    name = os.path.splitext(os.path.basename(spec))[0]

    # ---- serial interpreter: fresh engine per repeat, min wall ----
    iwalls, iref = [], None
    for _ in range(max(interp_repeats, 1)):
        model, pin = _load_model(spec, cfg, includes)
        r = Explorer(model).run()
        iwalls.append(r.wall_s)
        if iref is None:
            iref = r
        assert (r.generated, r.distinct) == (iref.generated,
                                             iref.distinct), \
            "interpreter repeats disagree (nondeterminism?)"
    interp_wall = min(iwalls)
    interp_rate = iref.generated / max(interp_wall, 1e-9)

    # ---- kernel: one engine; warm-up run (compile + caps + profile),
    # then min-of-repeats over fully warm re-runs ----
    model, pin = _load_model(spec, cfg, includes)
    kw = dict(store_trace=False)
    if engine == "resident":
        # the manifest's committed res_caps record sizes the capacity
        # buckets (small model -> small sorts); the gate measurement
        # itself stays profile-independent so it is reproducible from
        # the repo alone
        kw["resident"] = True
        kw["cap_profile"] = False
        rc = dict(pin.res_caps) if pin is not None and pin.res_caps \
            else None
        if rc:
            kw["chunk"] = int(rc.pop("chunk", 2048))
            kw["res_caps"] = rc
    ex = TpuExplorer(model, **kw)
    t0 = time.time()
    rw = ex.run()  # warm-up: XLA compile + capacity training, untimed
    warm_wall = time.time() - t0
    kwalls = []
    for _ in range(repeats):
        t0 = time.time()
        rk = ex.run()
        kwalls.append(time.time() - t0)
        assert (rk.generated, rk.distinct, rk.ok) == \
            (rw.generated, rw.distinct, rw.ok), "kernel repeats disagree"
    kernel_wall = min(kwalls)
    kernel_rate = rk.generated / max(kernel_wall, 1e-9)

    # ---- exactness gate: the packed kernel must COUNT identically ----
    assert (rk.generated, rk.distinct, rk.ok) == \
        (iref.generated, iref.distinct, iref.ok), \
        (f"{name}: kernel counts diverge from the interpreter: "
         f"kernel {rk.generated}/{rk.distinct}/ok={rk.ok} vs interp "
         f"{iref.generated}/{iref.distinct}/ok={iref.ok}")

    import jax
    platform = jax.devices()[0].platform
    os.makedirs(out_dir, exist_ok=True)
    a_interp = os.path.join(out_dir, f"jaxmc_kernelbench_{name}_interp.json")
    a_kernel = os.path.join(out_dir, f"jaxmc_kernelbench_{name}_kernel.json")
    _artifact(a_interp, "interp", spec, "interp", interp_wall, iref,
              max(interp_repeats, 1),
              f"serial exact interpreter, min of {max(interp_repeats, 1)}")
    _artifact(a_kernel, "jax", spec, platform, kernel_wall, rk, repeats,
              f"{engine} engine on {platform}, min of {repeats} after "
              f"one warm-up ({warm_wall:.2f}s compile+ramp excluded); "
              f"W={ex.W} PW={ex.PW} packed"
              f"={'no' if ex.plan.identity else 'yes'}")
    log(f"kernelbench {name}: interp {interp_rate:,.0f} st/s "
        f"({iref.generated} gen / {interp_wall:.4f}s) | kernel[{engine}/"
        f"{platform}] {kernel_rate:,.0f} st/s ({kernel_wall:.4f}s, "
        f"warm-up {warm_wall:.2f}s excluded) | "
        f"ratio {kernel_rate / max(interp_rate, 1e-9):.2f}x | "
        f"W={ex.W} PW={ex.PW}")

    # ---- the gate: same machinery as every bench-check leg ----
    from .obs.report import main as obs_main
    return obs_main(["diff", "--fail-on-regress", "--threshold", "0",
                     a_interp, a_kernel])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.kernelbench",
        description="kernel-vs-interpreter states/sec gate for one spec")
    ap.add_argument("spec")
    ap.add_argument("--cfg", default=None)
    ap.add_argument("-I", "--include", action="append", default=[])
    ap.add_argument("--out-dir", default="/tmp",
                    help="where the two metrics artifacts land")
    ap.add_argument("--repeats", type=int, default=2,
                    help="timed kernel re-runs (min wall wins)")
    ap.add_argument("--interp-repeats", type=int, default=1,
                    help="interpreter repeats (the expensive side: one "
                         "full exact search each)")
    ap.add_argument("--engine", choices=("resident", "level"),
                    default="resident")
    args = ap.parse_args(argv)
    try:
        import jax
        jax.config.update("jax_platforms",
                          os.environ.get("JAXMC_PLATFORM") or
                          os.environ.get("JAX_PLATFORMS") or "cpu")
    except ImportError:
        print("error: the jax backend is unavailable in this build",
              file=sys.stderr)
        return 2
    return run_leg(args.spec, args.cfg, args.out_dir,
                   repeats=args.repeats,
                   interp_repeats=args.interp_repeats,
                   engine=args.engine, includes=args.include)


if __name__ == "__main__":
    sys.exit(main())
