"""jaxmc — a TPU-native TLA+/PlusCal model checker.

A from-scratch, TPU-first model-checking framework with the capabilities of the
reference spec corpus's TLC harness (see /root/reference/Makefile:1-7): parse
TLA+ modules and TLC .cfg models, enumerate reachable states, check
invariants/deadlock, and report counterexample traces — with the hot BFS loop
compiled to XLA and run on a TPU mesh.

Layout (maps onto the standard models/ops/parallel/utils split):
  front/    TLA+ lexer/parser, .cfg parser, PlusCal translator   (the "models")
  sem/      value domain, evaluator, Init/Next enumeration        (semantics)
  engine/   host BFS oracle engine, traces, checkpointing
  compile/  model grounder + AST->jnp kernel compiler             (the "ops")
  tpu/      device-resident BFS, mesh sharding, collectives       ("parallel")
  utils/    shared helpers
"""

__version__ = "0.1.0"
