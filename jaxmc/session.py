r"""CheckSession: the reusable check flow as explicit, resumable stages.

ISSUE 7's forcing-function refactor: cli.py's monolithic check flow —
cfg sniffing, model load, device init, engine construction, search,
fallback — becomes one object with three named stages,

    parse    cfg + spec  ->  a bound Model (or an ASSUME-mode verdict)
    analyze  Model       ->  lint diagnostics (ISSUE 9; gated by
                             cfg.analyze off/warn/strict — strict
                             raises AnalyzeError on error diagnostics
                             before any compile cost is paid)
    compile  Model       ->  a ready engine (device init, kernel build;
                             carries the layout signature when the jax
                             backend compiled one)
    explore  engine      ->  CheckResult (re-runnable: warm re-checks
                             override resume/checkpoint per run)

so the CLI `check` command (a thin driver with byte-identical output),
the serve daemon (`python -m jaxmc.serve`, which holds sessions WARM and
answers repeat submissions from their checkpoints), and tests all drive
the same code.  A session carries exactly the state the daemon needs to
amortize: the parsed model, the built engine (whose jit caches are the
expensive warm artifact), the layout signature (the durable-artifact
key: compile cache entries and capacity profiles are keyed by
(module, layout_sig)), and the checkpoint handle.  Telemetry rides the
session: every stage reports spans into the recorder the session was
built with (obs.current() at construction unless one is passed).

Stage errors propagate as the same exceptions the CLI always mapped
(ModeError/CompileError/CkptError/ImportError/device failures) — the
DRIVER owns the policy (cli.py prints + exit codes; the serve daemon
marks the job failed; `demote_to_cpu` implements the shared device->CPU
fallback either driver can invoke).
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from . import obs
from .compile.vspec import Bounds


def read_text(path: str) -> str:
    """Read a cfg/spec file WITHOUT leaking the handle (the old
    `open(...).read()` pattern relied on refcount finalization)."""
    with open(path, encoding="utf-8", errors="replace") as fh:
        return fh.read()


def default_cfg_path(spec_path: str) -> Optional[str]:
    guess = os.path.splitext(spec_path)[0] + ".cfg"
    return guess if os.path.exists(guess) else None


def load_model(spec_path: str, cfg_path, no_deadlock: bool,
               includes=()):
    from .front.cfg import parse_cfg, ModelConfig
    from .sem.modules import Loader, bind_model

    if cfg_path is None:
        cfg_path = default_cfg_path(spec_path)
    if cfg_path:
        cfg = parse_cfg(read_text(cfg_path))
    else:
        cfg = ModelConfig(specification="Spec")
    if no_deadlock:
        cfg.check_deadlock = False
    ldr = Loader([os.path.dirname(os.path.abspath(spec_path))] +
                 list(includes))
    mod = ldr.load_path(spec_path)
    return bind_model(mod, cfg)


_SENTINEL = object()  # "keep the configured value" for explore overrides


class AnalyzeError(Exception):
    """--analyze=strict found error-severity diagnostics: the run must
    not proceed to compile/search (exit 2 on the CLI, a rejected job on
    the serve daemon).  Carries the full diagnostic list so drivers can
    render every finding, not only the first."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.severity == "error"]
        super().__init__(
            f"{len(errs)} error diagnostic"
            f"{'s' if len(errs) != 1 else ''} "
            f"({'; '.join(d.code for d in errs[:6])})")


@dataclass
class SessionConfig:
    """Everything a check run is parameterized by — field names and
    defaults mirror the `check` CLI exactly (argparse populates the same
    surface), plus the serve-only knobs at the bottom."""

    spec: str
    cfg: Optional[str] = None
    include: Tuple[str, ...] = ()
    backend: str = "interp"
    platform: Optional[str] = None
    max_states: Optional[int] = None
    workers: Optional[int] = None
    compile_cache: Optional[str] = None
    no_deadlock: bool = False
    no_device_fallback: bool = False
    progress_every: float = 30.0
    seq_cap: int = Bounds.seq_cap
    grow_cap: int = Bounds.grow_cap
    kv_cap: int = Bounds.kv_cap
    no_trace: bool = False
    host_seen: bool = False
    sample: Tuple[int, int, int] = (800, 40, 60)
    chunk: int = 2048
    resident: bool = False
    # hierarchical seen set (ISSUE 12): dedup-key mode ("auto" keeps
    # the width-based default; "fingerprint" trades exact keys for
    # 128-bit fingerprints — 4-8x the states per tier, collision
    # probability reported; "exact" refuses to fingerprint), the
    # device seen cap (key rows; overflow spills to host/disk tiers
    # instead of growing; env JAXMC_SEEN_CAP), and the disk-tier
    # spill directory (default: a temp dir)
    seen: str = "auto"
    seen_cap: Optional[int] = None
    seen_spill: Optional[str] = None
    checkpoint: Optional[str] = None
    checkpoint_every: float = 600.0
    resume: Optional[str] = None
    # static analysis (ISSUE 9): lint severity gate for the analyze
    # stage — "off" (skip), "warn" (print diagnostics, continue),
    # "strict" (error diagnostics abort with exit 2 before compile)
    analyze: str = "off"
    # partial-order reduction (ISSUE 15 interp, ISSUE 18 device;
    # opt-in): expand one globally-commuting invisible arm per state
    # instead of every enabled arm — preserves invariant/deadlock
    # verdicts, NOT raw counts.  On device backends the ample mask is
    # applied INSIDE the fused step (zero extra dispatches); configs
    # the device mask cannot serve (hybrid demotions, symmetry, ...)
    # run unreduced with a named warning, never a silent engine swap.
    por: bool = False
    # device profiling mode (ISSUE 17, obs/prof.py): None (cheap
    # counters only), "wall" or "xla".  Plumbing, not an answer-changer
    # — deliberately NOT part of job_signature_fields (profiling never
    # changes counts or traces)
    profile: Optional[str] = None
    # serve-only knobs (no CLI flags):
    final_checkpoint: bool = False  # checkpoint COMPLETED runs too —
    # the daemon's warm-resume source
    res_caps: Optional[Dict[str, int]] = None

    @classmethod
    def from_args(cls, args) -> "SessionConfig":
        """Build from an argparse Namespace (the `check` subcommand's);
        unknown session-only fields keep their defaults."""
        import dataclasses
        kw = {}
        for f in dataclasses.fields(cls):
            if hasattr(args, f.name):
                kw[f.name] = getattr(args, f.name)
        kw["include"] = tuple(getattr(args, "include", ()) or ())
        kw["sample"] = tuple(getattr(args, "sample", (800, 40, 60)))
        return cls(**kw)

    def job_signature_fields(self) -> Dict[str, Any]:
        """The option surface that makes two submissions 'the same job'
        for warm reuse: anything that changes the search's RESULT or its
        layout/kernels.  Checkpoint/resume paths, telemetry, and pacing
        knobs (progress_every, checkpoint_every) are excluded — they
        change the run's plumbing, not its answer."""
        return {
            "spec": self.spec, "cfg": self.cfg,
            "include": list(self.include), "backend": self.backend,
            "platform": self.platform, "max_states": self.max_states,
            "no_deadlock": self.no_deadlock,
            "seq_cap": self.seq_cap, "grow_cap": self.grow_cap,
            "kv_cap": self.kv_cap, "no_trace": self.no_trace,
            "host_seen": self.host_seen, "sample": list(self.sample),
            "chunk": self.chunk, "resident": self.resident,
            "seen": self.seen, "seen_cap": self.seen_cap,
            "por": self.por,
        }

    def batch_signature_fields(self) -> Dict[str, Any]:
        """job_signature_fields WITHOUT the model identity: the option
        surface every member of a cross-model vmapped batch must share
        (per-model differences ride the lifted constant lanes)."""
        f = self.job_signature_fields()
        f.pop("spec", None)
        f.pop("cfg", None)
        return f


def _stable(v) -> str:
    """Deterministic rendering of a parsed cfg constant value (repr of
    frozensets is insertion-ordered — sort them)."""
    if isinstance(v, frozenset):
        return "{" + ",".join(sorted(_stable(x) for x in v)) + "}"
    return repr(v)


@dataclass
class BatchProfile:
    """Parse-time batch compatibility verdict for one submission
    (ISSUE 13): the LAYOUT-COMPAT CLASS key plus the scheduling cost
    estimate — both derived before any engine exists."""
    bsig: str                      # equal <=> layout-compatible, i.e.
    # one vmapped engine can serve both jobs
    lift: Tuple[str, ...]          # constants that become batch lanes
    cost_estimate: Optional[int]   # analyze's state-space estimate
    # (None = analysis bailed: no fast-lane routing)


def batch_profile(cfg: SessionConfig,
                  model=None) -> Optional["BatchProfile"]:
    """Prove (at parse time) which layout-compat class this job belongs
    to.  Two submissions with equal `bsig` differ at most in LIFTABLE
    constant values — same module shape, same non-lifted constants,
    same cfg-declared predicates, same result-affecting options — so
    the serve fleet may run them through one vmapped device program
    (backend/batch.py).  Returns None for configurations the batcher
    does not cover (interp backend, resident mode, non-host_seen device
    modes, tiered seen sets) or when the model fails to load — the job
    then schedules solo, exactly as before."""
    import hashlib
    import json
    if cfg.backend == "interp" or cfg.resident or not cfg.host_seen \
            or cfg.seen_cap is not None or cfg.por:
        return None
    if model is None:
        try:
            model = load_model(cfg.spec, cfg.cfg, cfg.no_deadlock,
                               cfg.include)
        except Exception:  # noqa: BLE001 — an unloadable pair is simply
            # not batchable; the solo path reports the real error
            return None
    from .analyze.bounds import liftable_constants, state_space_estimate
    lift = liftable_constants(model)
    mc = model.cfg
    masked = {n: ("<lifted>" if n in lift else _stable(v))
              for n, v in sorted(mc.constants.items())}
    ident = {
        "module": model.module.name,
        "vars": list(model.vars),
        "spec_sha": hashlib.sha256(
            read_text(cfg.spec).encode()).hexdigest(),
        "cfg_shape": {
            "specification": mc.specification, "init": mc.init,
            "next": mc.next,
            "invariants": sorted(mc.invariants),
            "properties": sorted(mc.properties),
            "constraints": sorted(mc.constraints),
            "action_constraints": sorted(mc.action_constraints),
            "symmetry": mc.symmetry, "view": mc.view,
            "overrides": sorted(mc.overrides.items()),
            "scoped_overrides": sorted(
                (f"{k[0]}!{k[1]}", v)
                for k, v in mc.scoped_overrides.items()),
            "check_deadlock": mc.check_deadlock,
            "constants": masked,
        },
        "lift": list(lift),
        "options": cfg.batch_signature_fields(),
    }
    blob = json.dumps(ident, sort_keys=True).encode()
    bsig = "b" + hashlib.sha256(blob).hexdigest()[:15]
    try:
        est = state_space_estimate(model)
    except Exception:  # noqa: BLE001 — estimation must never block
        est = None
    return BatchProfile(bsig=bsig, lift=lift, cost_estimate=est)


class CheckSession:
    """One check as three resumable stages over one model/engine pair.

    Stage order is enforced (compile needs parse's model, explore needs
    compile's engine); each stage is idempotent — calling it again when
    already complete is a no-op, so a driver can `ensure()` its way to
    any stage.  `explore` alone is deliberately RE-runnable with
    per-run overrides: the serve daemon re-drives a warm session's
    engine with `resume_from=<previous job's final checkpoint>` and the
    search replays the stored verdict without recompiling anything."""

    def __init__(self, cfg: SessionConfig, tel=None, log=None):
        self.cfg = cfg
        self.tel = tel if tel is not None else obs.current()
        self.log = log if log is not None else obs.Logger(quiet=True)
        self.stage: Optional[str] = None  # last COMPLETED stage
        self.kind: Optional[str] = None   # "model" | "assumes"
        self.model = None
        self.engine = None
        self.cache_dir: Optional[str] = None  # persistent compile cache
        self.layout_sig: Optional[str] = None
        self.result = None
        self.explore_count = 0
        self.diagnostics = None  # analyze stage output (lint findings)

    # ---- stage: parse -------------------------------------------------
    def parse(self) -> str:
        """Load cfg+spec.  Returns the session kind: "model" (a bound
        Model ready to compile) or "assumes" (TLC's No-Behavior-Spec
        calculator mode — drive it with run_assumes())."""
        if self.stage is not None:
            return self.kind
        cfg = self.cfg
        cfgp = cfg.cfg or default_cfg_path(cfg.spec)
        self.cfg_path = cfgp
        if cfgp:
            from .front.cfg import parse_cfg
            c = parse_cfg(read_text(cfgp))
            if not c.specification and not c.init:
                self.kind = "assumes"
                self.stage = "parse"
                return self.kind
        with self.tel.span("load", spec=cfg.spec):
            self.model = load_model(cfg.spec, cfg.cfg, cfg.no_deadlock,
                                    cfg.include)
        # ISSUE 16: hang a search-progress estimator off the recorder —
        # the analyze bound (when one exists) turns every progress line,
        # heartbeat and /status poll into a fraction-explored + ETA
        obs.attach_estimator(self.tel, self.model)
        self.kind = "model"
        self.stage = "parse"
        return self.kind

    def run_assumes(self) -> int:
        """TLC's "No Behavior Spec" mode: evaluate the module's ASSUMEs
        as a calculator / unit-test harness (SimpleMath.cfg:4-11,
        PrintValues.tla — SURVEY.md §4.4).  Prints the verdict lines
        (the CLI contract); returns the exit code."""
        assert self.kind == "assumes", "run_assumes needs an assumes session"
        from .front.cfg import parse_cfg, ModelConfig
        from .sem.modules import Loader, bind_model_defs
        from .sem.eval import Ctx, eval_expr
        from .sem.values import fmt

        cfg = self.cfg
        mcfg = parse_cfg(read_text(self.cfg_path)) if self.cfg_path \
            else ModelConfig()
        ldr = Loader([os.path.dirname(os.path.abspath(cfg.spec))] +
                     list(cfg.include))
        mod = ldr.load_path(cfg.spec)
        defs = bind_model_defs(mod, mcfg)
        prints = []
        ctx = Ctx(defs, {}, None, None, (),
                  on_print=lambda v: prints.append(v))
        failed = 0
        for a in mod.assumes:
            v = eval_expr(a.expr, ctx)
            nm = a.name or "ASSUME"
            if v is not True:
                print(f"Assumption {nm} is violated (evaluated to "
                      f"{fmt(v)}).")
                failed += 1
        for v in prints:
            print(fmt(v) if not isinstance(v, str) else v)
        if failed:
            return 1
        print(f"{len(mod.assumes)} assumption"
              f"{'s' if len(mod.assumes) != 1 else ''} checked. "
              "No error has been found.")
        return 0

    # ---- stage: analyze -----------------------------------------------
    def analyze(self):
        """The static-analysis stage between parse and compile (ISSUE
        9): lint the spec/cfg pair and store the diagnostics.  Severity
        policy follows cfg.analyze — "off" skips entirely (stage chain
        passes through), "warn" records, "strict" raises AnalyzeError
        when any error-severity diagnostic exists.  Idempotent like the
        other stages — and deliberately runnable BEFORE parse: the
        linter re-loads the pair itself, so a cfg broken in a way that
        makes bind_model refuse (an undefined invariant name, an
        unassigned CONSTANT) still gets its diagnostics reported
        instead of a bare parse error.  Assumes-mode pairs (no behavior
        spec) have nothing to analyze."""
        mode = (self.cfg.analyze or "off").lower()
        if self.diagnostics is not None:
            if mode == "strict":
                errs = [d for d in self.diagnostics
                        if d.severity == "error"]
                if errs:
                    # the strict refusal must hold on EVERY call — a
                    # driver that caught the first AnalyzeError cannot
                    # compile/explore its way past it via the stage
                    # chain (compile() re-enters here)
                    raise AnalyzeError(self.diagnostics)
            return self.diagnostics
        if mode == "off":
            return []
        cfgp = self.cfg.cfg or default_cfg_path(self.cfg.spec)
        if cfgp:
            try:
                from .front.cfg import parse_cfg
                c = parse_cfg(read_text(cfgp))
                if not c.specification and not c.init:
                    return []  # assumes-mode: no model to lint
            except Exception:
                pass  # unparseable cfg: lint_pair reports it as JMC100
        from .analyze.lint import errors, lint_pair, max_severity
        with self.tel.span("analyze", mode=mode):
            diags = lint_pair(self.cfg.spec, cfgp,
                              tuple(self.cfg.include))
        self.diagnostics = diags
        if diags:
            self.tel.counter("analyze.lint_diags", len(diags))
            self.tel.gauge("analyze.lint_max_severity",
                           max_severity(diags))
            self.tel.gauge("analyze.lint_codes",
                           sorted({d.code for d in diags}))
        if self.stage == "parse":
            self.stage = "analyze"
        if mode == "strict" and errors(diags):
            raise AnalyzeError(diags)
        return diags

    # ---- stage: compile -----------------------------------------------
    def resolve_platform(self) -> Optional[str]:
        """The jax platform this session's device backend should pin
        (ISSUE 11).  `--backend cpu|gpu|tpu` names it outright;
        `--backend auto` asks the preflight oracle (jaxmc/backend/
        oracle.py — tiny compile+dispatch probe per visible platform,
        seconds, hang-proof) and records the verdict in telemetry;
        `--backend jax` keeps the historical meaning: --platform /
        JAXMC_PLATFORM if given, else whatever jax initializes."""
        b = self.cfg.backend
        if b in ("cpu", "gpu", "tpu"):
            return b
        if b == "auto":
            from .backend.oracle import preflight
            with self.tel.span("preflight_oracle"):
                v = preflight(tel=self.tel)
            if v["platform"] is None:
                errs = "; ".join(
                    f"{p}: {pr.get('error')}"
                    for p, pr in v["probes"].items())
                raise RuntimeError(
                    f"backend oracle found no live platform ({errs})")
            self.log(f"-- backend oracle: {v['platform']} "
                     f"({v['reason']}; {v['wall_s']}s)")
            return v["platform"]
        return self.cfg.platform

    def device_init(self) -> Optional[str]:
        """Device/plugin init with bounded retries + backoff
        (JAXMC_DEVICE_RETRIES, default 2): a flaky accelerator tunnel
        gets more than one chance before the run demotes to CPU.
        ImportError (jax not in the build) stays terminal — retrying
        cannot install a wheel.  Returns the persistent compile-cache
        dir (or None)."""
        from . import faults
        cfg, tel = self.cfg, self.tel
        platform = self.resolve_platform()  # oracle verdict is cached
        retries = int(os.environ.get("JAXMC_DEVICE_RETRIES", "2"))
        for attempt in range(retries + 1):
            try:
                with tel.span("device_init",
                              platform=platform or "default",
                              attempt=attempt):
                    import jax
                    faults.inject("device_init_fail")
                    if platform:
                        jax.config.update("jax_platforms", platform)
                    # persistent XLA compile cache (repeat runs skip the
                    # per-arm compiles): opt-in via --compile-cache /
                    # JAXMC_COMPILE_CACHE, but GUARDED (ISSUE 5): a
                    # wedged, corrupt or foreign-build cache degrades to
                    # cold compilation instead of hanging the run
                    from .compile.cache import (cache_dir_from_env,
                                                enable_guarded_cache)
                    _cache_req = cfg.compile_cache or cache_dir_from_env()
                    cache_dir = enable_guarded_cache(_cache_req, tel=tel) \
                        if _cache_req else None
                    if tel.enabled:
                        # force plugin/device init inside the span so a
                        # hung tunnel is attributed to device_init, not
                        # compile
                        tel.gauge("device.platform",
                                  jax.devices()[0].platform)
                        tel.gauge("device.count", len(jax.devices()))
                        # re-stamp the env fingerprint now that jax is
                        # initialized: platform/device_count become real
                        tel.set_meta(env=obs.environment_meta())
                    else:
                        jax.devices()  # init failures must surface HERE
                return cache_dir
            except (faults.FaultInjected, RuntimeError, OSError,
                    ConnectionError) as ex:
                if attempt >= retries:
                    raise
                tel.counter("device.init_retries")
                print(f"warning: device init failed ({ex}); retrying "
                      f"({attempt + 1}/{retries})", file=sys.stderr)
                time.sleep(min(0.2 * (2 ** attempt), 5.0))

    def compile(self) -> "CheckSession":
        """Build the engine for the configured backend.  For the jax
        backend this is the expensive stage (device init, layout
        sampling, per-arm kernel construction) and the one whose product
        the serve daemon keeps warm; it also stamps `layout_sig`, the
        key under which compile-cache entries and capacity profiles
        persist.  Raises what engine construction raises (ModeError /
        CompileError / device failures) — the driver owns the policy."""
        if self.stage in ("compile", "explore"):
            return self
        if self.stage is None:
            self.parse()
        if self.stage == "parse":
            self.analyze()  # no-op when cfg.analyze == "off"
        assert self.kind == "model", "assumes sessions have no engine"
        cfg = self.cfg
        if cfg.backend == "interp":
            from .engine.parallel import ParallelExplorer, default_workers
            # None or 0 = auto (JAXMC_WORKERS, else min(cpu_count, 8))
            self.workers = default_workers() if not cfg.workers \
                else max(1, cfg.workers)
            kw = dict(log=self.log, max_states=cfg.max_states,
                      progress_every=cfg.progress_every,
                      checkpoint_path=cfg.checkpoint,
                      checkpoint_every=cfg.checkpoint_every,
                      resume_from=cfg.resume,
                      final_checkpoint=cfg.final_checkpoint)
            if cfg.por:
                # the ample-set choice depends on the live seen-set, a
                # per-state sequential decision — the fork-pool's
                # chunked expansion cannot replay it; serial engine,
                # named reason
                if self.workers > 1:
                    self.tel.gauge("parallel.fallback_reason", "por")
                self.workers = 1
                from .engine.explore import Explorer
                self.engine = Explorer(self.model, por=True, **kw)
            elif self.workers > 1:
                # worker-parallel frontier expansion (crash-safe:
                # checkpoints natively, survives worker deaths); falls
                # back to the serial engine (identical results) only for
                # stepwise refinement or when the platform cannot fork
                self.engine = ParallelExplorer(self.model,
                                               workers=self.workers, **kw)
            else:
                from .engine.explore import Explorer
                self.engine = Explorer(self.model, **kw)
        else:
            self.cache_dir = self.device_init()
            from .backend.bfs import TpuExplorer
            bounds = Bounds(seq_cap=cfg.seq_cap, grow_cap=cfg.grow_cap,
                            kv_cap=cfg.kv_cap)
            with self.tel.span("engine_build"):
                self.engine = TpuExplorer(
                    self.model, log=self.log, bounds=bounds,
                    store_trace=not cfg.no_trace,
                    progress_every=cfg.progress_every,
                    host_seen=cfg.host_seen,
                    chunk=cfg.chunk,
                    resident=cfg.resident,
                    sample_cfg=tuple(cfg.sample),
                    checkpoint_path=cfg.checkpoint,
                    checkpoint_every=cfg.checkpoint_every,
                    resume_from=cfg.resume,
                    max_states=cfg.max_states,
                    por=cfg.por,
                    res_caps=cfg.res_caps,
                    final_checkpoint=cfg.final_checkpoint,
                    seen_mode=cfg.seen,
                    seen_cap=cfg.seen_cap,
                    spill_dir=cfg.seen_spill)
            self.layout_sig = self.engine._layout_sig()
        self.stage = "compile"
        return self

    # ---- stage: explore -----------------------------------------------
    def explore(self, resume_from=_SENTINEL, checkpoint_path=_SENTINEL,
                final_checkpoint=_SENTINEL):
        """Run (or RE-run) the search.  Overrides apply to this run only
        in spirit — they are set on the engine, whose run() reads them
        fresh each call — and are how a warm session answers a repeat
        submission: explore(resume_from=last_final_checkpoint) replays
        the completed search's verdict through the already-compiled
        kernels.  Returns (and stores) the CheckResult."""
        if self.stage in (None, "parse", "analyze"):
            self.compile()
        ex = self.engine
        if resume_from is not _SENTINEL:
            ex.resume_from = resume_from
        if checkpoint_path is not _SENTINEL:
            ex.checkpoint_path = checkpoint_path
        if final_checkpoint is not _SENTINEL:
            ex.final_checkpoint = final_checkpoint
        self.explore_count += 1
        if self.cfg.backend == "interp":
            with self.tel.span("search", workers=self.workers):
                self.result = ex.run()
        else:
            with self.tel.span("search"):
                self.result = ex.run()
            from .compile.cache import record_entries_end
            record_entries_end(self.cache_dir)
        self.stage = "explore"
        return self.result

    # ---- shared device->CPU fallback ----------------------------------
    def demote_to_cpu(self, err) -> Any:
        """Terminal device failure -> the parallel CPU engine, resuming
        from the device run's host snapshot (`<checkpoint>.host`,
        written at level barriers by tpu/bfs.py) when one exists.  The
        demotion is machine-readable: `device.demoted` gauge + event
        (flagged by `python -m jaxmc.obs diff`) and a result warning on
        stdout."""
        from .engine.parallel import ParallelExplorer, default_workers
        cfg, tel = self.cfg, self.tel
        reason = f"{type(err).__name__}: {err}"
        print(f"warning: device backend failed terminally ({reason}); "
              f"falling back to the parallel CPU engine", file=sys.stderr)
        tel.event("device.demoted", reason=reason)
        tel.gauge("device.demoted", reason[:200])
        tel.counter("device.demotions")
        snap = (cfg.checkpoint + ".host") if cfg.checkpoint else None
        resume = snap if snap and os.path.exists(snap) else None
        if snap and not resume:
            print("warning: no host snapshot exists yet - the CPU engine "
                  "restarts from scratch", file=sys.stderr)
        if resume:
            print(f"resuming from host snapshot {resume}", file=sys.stderr)
        workers = default_workers() if not cfg.workers \
            else max(1, cfg.workers)
        with tel.span("search_fallback", workers=workers):
            res = ParallelExplorer(
                self.model, workers=workers, log=self.log,
                max_states=cfg.max_states,
                progress_every=cfg.progress_every,
                checkpoint_path=snap,
                checkpoint_every=cfg.checkpoint_every,
                resume_from=resume,
                final_checkpoint=cfg.final_checkpoint).run()
        res.warnings.append(
            f"device backend failed ({reason}); the run completed on the "
            f"parallel CPU engine"
            + (", resumed from the last host snapshot" if resume
               else ", restarted from scratch"))
        self.result = res
        return res

    # ---- introspection -------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        """The session's resumable identity (serve status endpoint)."""
        return {
            "stage": self.stage,
            "kind": self.kind,
            "backend": self.cfg.backend,
            "spec": self.cfg.spec,
            "module": self.model.module.name if self.model is not None
            else None,
            "layout_sig": self.layout_sig,
            "checkpoint": self.cfg.checkpoint,
            "explore_count": self.explore_count,
            "analyze_diags": len(self.diagnostics)
            if self.diagnostics is not None else None,
        }
