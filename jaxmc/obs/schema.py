r"""The `--metrics-out` / `--trace` event schema, as data.

One place pins what every artifact must carry so the CLI, bench.py, the
sweep driver, and tests/test_obs.py agree. `validate_summary` raises
ValueError with the missing/ill-typed field names — it is deliberately
structural (required keys + types + level-index monotonicity), not
exhaustive: engines are free to add fields.

Trace JSONL event grammar (one JSON object per line, `ev` discriminates;
since jaxmc.metrics/3 every event also carries `tid` — the fleet-wide
trace id, obs/context.py):

  proc_meta  {t, mono, pid, argv, psid, parent_span, env}
                                           -- per-file header (first
                                              line): process identity +
                                              span lineage + monotonic
                                              clock anchor
  run_start  {t, meta}
  span_open  {name, t, parent, attrs}      -- partial-span forensics
  span       {name, t0, wall_s, attrs[, error]}
  level      {level, t, frontier?, generated?, new?, distinct?, ...}
  heartbeat  {t, wall_s, rss_bytes, open_spans, last_level,
              progress_seq}                -- periodic watchdog beat
  stall      {t, stalled_for_s, threshold_s, open_spans, last_level,
              median_level_s}              -- watchdog: no span/level
                                              progress for too long
  counter/gauge changes are rolled up in the summary only
  log        {t, msg}                      -- mirror of the stdout line
  run_end    {t}

Summary (metrics-out) required surface: see REQUIRED_KEYS below; each
phases[i] carries {name, wall_s, count} (+optional open=True for spans
still running at rollup — the deadline-blowout record); each levels[i]
carries at least {level} with non-decreasing level indices.

Schema history (additive — every jaxmc.metrics/1 artifact is a valid
jaxmc.metrics/2 artifact minus the new optional surface, so readers and
`validate_summary` accept both):

  jaxmc.metrics/1  (PR 1) the surface above minus heartbeat/stall.
  jaxmc.metrics/2  (PR 2) adds, all optional:
    - meta block `env` = {jax_version, platform, device_count}: the
      environment fingerprint `python -m jaxmc.obs diff` uses to
      attribute regressions to environment changes;
    - trace events `heartbeat` / `stall` (jaxmc/obs/watchdog.py);
    - compile-introspection gauges: `compile.arm_cost` ({arm label ->
      {jaxpr_eqns, hlo_flops?, hlo_bytes?}}), counters
      `compile.jaxpr_eqns_total`, `compile.hlo_flops_total`,
      `compile.hlo_bytes_total`, and jit-cache effectiveness counters
      `compile.cache_hits` / `compile.cache_misses`;
    - watchdog counters `watchdog.heartbeats` / `watchdog.stalls` and
      the `watchdog.max_stall_s` high-water gauge.

  (PR 3, still jaxmc.metrics/2 — all additive/optional:)
    - parallel exact engine (engine/parallel.py): level records may
      carry `workers`, `chunk_wall_s` (summed worker expansion wall for
      the level) and `merge_wall_s` (parent-side merge wall); gauges
      `parallel.workers` / `parallel.fallback_reason`, counter
      `parallel.chunks`, trace event `parallel.fallback {reason}`;
    - persistent XLA compile cache (compile/cache.py): counters
      `compile.persistent_cache_hits` (and any other
      /jax/compilation_cache/* monitoring events, same naming), gauges
      `compile.persistent_cache_dir`,
      `compile.persistent_cache_entries_start` / `_end`,
      `compile.persistent_cache_active`;
    - checkpoint cost: phase `checkpoint.write` (span attrs: states,
      queue) — checkpoint wall no longer hides inside `search`.

  (PR 4, still jaxmc.metrics/2 — all additive/optional; the
   fault-tolerance surface:)
    - crash-safe parallel engine (engine/parallel.py): counters
      `parallel.worker_deaths` / `parallel.respawns` /
      `parallel.requeues` / `parallel.chunk_retries` /
      `parallel.degradations`, gauges `parallel.degraded` (the reason
      string — present ONLY when the run fell back to serial expansion
      after exhausting its retry budget) and `parallel.pool_size`
      (post-shrink worker count), trace events `parallel.worker_death
      {level, pids, lost_chunks}` / `parallel.chunk_error {level,
      chunk, error, retry}` / `parallel.degraded {reason}`;
    - device retry/demotion (cli.py): counters `device.init_retries` /
      `device.demotions` / `compile.retries`, gauge `device.demoted`
      (the terminal failure reason — `python -m jaxmc.obs diff` raises
      a REGRESS flag when it appears between runs), trace event
      `device.demoted {reason}`, phase `search_fallback`;
    - checkpoint integrity (engine/ckpt.py): phase
      `checkpoint.host_snapshot` + counter `checkpoint.host_snapshots`
      (the device path's CPU-resumable `<checkpoint>.host` snapshot);
    - fault harness (jaxmc/faults.py): counter `faults.injected`,
      trace event `fault.injected {site, ...ctx}` — present only when
      JAXMC_FAULTS is set (chaos runs / `make chaos`).

  (PR 5, still jaxmc.metrics/2 — all additive/optional; the
   compile-amortization surface:)
    - guarded persistent compile cache (compile/cache.py): gauge
      `compile.persistent_cache_guard` — "ok" / "ok (<notes>)" when the
      cache enabled (notes name quarantined entries / a fresh probe),
      "cold-fallback:<reason>" when the guard degraded the run to cold
      compilation (wedged probe, corrupt dir, lock contention, foreign
      build), "disabled:..." on explicit opt-out; counters
      `compile.persistent_cache_fallbacks` and
      `compile.persistent_cache_quarantines`.  The existing
      `compile.persistent_cache_hits` counter is the CROSS-PROCESS
      proof: >0 means this process reloaded a program some other
      process compiled.
    - steady-state bench window (bench.py full rung): the emitted line
      gains a `steady_state` block {source, path, resumed_generated,
      resumed_distinct, resumed_depth, window_generated, window_wall_s,
      window_recompiles}; the parent's orchestration block gains
      `compile_excluded_from_window` {phases: {name -> wall_s},
      total_s} — the one-time compile bill, separated from the
      steady-state states/sec claim.  New child phase spans
      `warmup_run {warm_source}` and `warm_ckpt_build {warm_states}`;
      bench-warm runs emit `warmgen_bench` / `warmgen_3s` spans.
    - expansion-mode pins (corpus.py): sweep case records/details note
      `[mode pinned]` for manifest-pinned interp-arms cases (kernel
      construction skipped) and carry a per-arm demotion reason table
      (`[demoted arms: <label>: <reason>; ...]`) whenever arms demote
      unpinned; a pinned case that slides toward the interpreter is a
      FAIL with detail "REGRESSION: expansion mode slid ...".
    - symmetry disclosure is three-way: `sym=device-reduced`,
      `sym=identity` (identity permutation group — no divergence), or
      `sym=UNREDUCED-FALLBACK (...)` (a genuine CompileError fallback;
      the only case where counts diverge from TLC's reduced ones).

  (PR 6, still jaxmc.metrics/2 — all additive/optional; the
   state-encoding surface:)
    - bit-packed lane plans (compile/pack.py): gauges
      `layout.packed_width_lanes` (packed row width, vs the existing
      `layout.width_lanes`), `layout.bits_per_state`,
      `layout.pack_ratio` (packed/unpacked width),
      `layout.pack_guarded_lanes` (observed-range int lanes with a
      runtime guard), and `dedup.mode` — "exact" | "fp128" with a
      "-packed" suffix when the key basis is the packed row or
      "-view" when cfg VIEW keys the dedup;
    - buffer donation (tpu/bfs.py): gauge `device.donation` (bool —
      seen/frontier donated into the jitted steps; off on XLA:CPU by
      default, JAXMC_DONATE forces);
    - capacity profiles (compile/cache.py): gauge `profile.status` —
      "loaded" / "saved" / "absent" / "disabled:..." /
      "degraded:<named reason>" (stale layout signature, foreign
      schema, module mismatch, unreadable, malformed caps — a degraded
      profile falls back to the overflow-growth path, never a crash);
      counters `profile.hits` / `profile.saves` / `profile.degrades`;
    - kernelbench artifacts (jaxmc/kernelbench.py): ordinary
      jaxmc.metrics/2 summaries whose `result.wall_s` is the
      min-of-repeats steady wall (warm-up excluded), gauge
      `kernelbench.note` carries the measurement methodology; the
      kernel-vs-interp leg feeds them to `obs diff --fail-on-regress`.

  (PR 7, still jaxmc.metrics/2 — all additive/optional; the
   checking-as-a-service surface:)
    - cooperative drain (jaxmc/drain.py): `result.drained` = true when
      a SIGTERM/daemon drain stopped the search at a safe boundary
      (implies `result.truncated`; the run checkpointed and is
      resumable); trace event `drain {reason, engine}`.
    - serve fleet telemetry (jaxmc/serve/daemon.py, the daemon's own
      Telemetry): per-job `job` phase spans (attrs: id, sig, spec,
      backend, batched), gauges `serve.queue_depth` / `serve.running` /
      `serve.warm_sessions` / `serve.workers` / `serve.draining`,
      counters `serve.jobs_submitted` / `serve.jobs_done` /
      `serve.jobs_failed` / `serve.jobs_drained` / `serve.warm_hits`
      (a repeat submission answered by a warm session's checkpoint
      replay) / `serve.cold_runs` / `serve.ckpt_resumes` (cold engine,
      but resumed a previous daemon life's checkpoint) /
      `serve.batched_jobs` (queued identical jobs coalesced into one
      dispatch) / `serve.requeued_on_start`; trace events
      `serve.drain {reason}` / `serve.job_failed {id, error}`.
    - serve per-job artifacts (`<spool>/results/<id>.json`): ordinary
      jaxmc.metrics/2 summaries (meta `command` = "serve.job") plus a
      top-level `serve` block {sig, warm_engine,
      resumed_from_checkpoint, window_recompiles (count of
      `fresh_compile` level records — 0 on a warm hit), profile_hits,
      persistent_cache_hits, batched_with, job_wall_s}; violating jobs
      add `result.trace` (the rendered counterexample).
    - session stage spans (jaxmc/session.py): the `check` flow's
      existing `load` / `device_init` / `engine_build` / `search` /
      `search_fallback` phases are now emitted by CheckSession — same
      names, same meaning, whether the CLI or the serve daemon drives.
    - fused arm groups (tpu/bfs.py): gauge `expand.fused_groups` — the
      number of fused expansion jits when a many-instance model splits
      per arm-group (JAXMC_FUSED_MAX_INSTANCES instances per group)
      instead of per action.

  (PR 8, still jaxmc.metrics/2 — all additive/optional; the mesh-
   resident multi-chip surface, tpu/mesh.py + jaxmc/meshbench.py:)
    - exchange strategy: gauges `mesh.exchange` ("a2a" | "gather"),
      `mesh.devices`; the strategy + gamma are also logged once per
      run.
    - resident-loop host traffic: counter `mesh.host_syncs` — one per
      level, counting the SINGLE replicated scalar-vector read the
      resident loop performs (on a clean run it EQUALS the level-record
      count: no row traffic crosses to the host between levels);
      counter `mesh.row_syncs` — whole-ring row pulls (violation trace
      assembly, checkpoints) — the only other device->host transfers.
    - exchange volume: counter `mesh.exchange_bytes` — whole-mesh bytes
      moved by the level exchanges (a2a: D^2*(B+SB)*(K+PW+1)*4 per
      level incl. the spill pass; gather: D^2*C*(K+PW)*4), computed
      from the static shapes.
    - a2a routing: gauges `mesh.a2a_gamma` (final bucket capacity
      factor; grows to the observed per-peer need on overflow),
      `mesh.a2a_spill` (total rows drained through the second
      all_to_all spill pass instead of rerunning the level),
      `mesh.a2a_max_bucket` (peak per-destination bucket occupancy).
    - shard health: gauge `mesh.shard_balance` — max/mean seen-shard
      occupancy (1.0 = perfectly balanced hash partition).
    - mesh level records add `devices`, `fc` (frontier capacity),
      `spill`, `max_bucket`, and the existing `fresh_compile` flag
      (so `window_recompiles` computes for mesh runs exactly like
      serve jobs).
    - multichip artifacts: MULTICHIP_r*.json (schema
      jaxmc.multichip/1, jaxmc/meshbench.py) — per-rung scaling curves
      [{devices, exchange, states_per_sec, states_per_sec_per_chip,
      window_recompiles, host_syncs, levels, exchange_bytes_per_level,
      shard_balance, a2a_*}]; per-leg jaxmc.metrics/2 artifacts carry
      the same numbers in a top-level `multichip` block and gate via
      `obs diff --fail-on-regress`.

  (PR 9, still jaxmc.metrics/2 — all additive/optional; the static-
   analysis surface, jaxmc/analyze/*:)
    - session stage span `analyze` (attrs: mode) between `load` and
      `engine_build` when `check --analyze != off`; engine-side spans
      `analyze_bounds` (the interval fixpoint) and `analyze_arms` (the
      per-arm demotion scan) inside the jax engine build.
    - bounds inference: gauge `analyze.proven_lanes` — int lanes whose
      packed width is a STATICALLY PROVEN interval (no sampling
      margin; the runtime OV_PACK check remains as a soundness net) —
      disjoint from `layout.pack_guarded_lanes`, which now counts ONLY
      observed-range lanes; gauge `analyze.bounds_converged` (bool).
      `obs report` renders the proven/(proven+guarded) ratio as a
      highlight line.
    - demotion prediction: counter `analyze.predicted_demotions` and
      gauge `analyze.arm_verdicts` ({arm label -> predicted reason});
      a predicted arm's reason string is IDENTICAL to the build-time
      demotion wording (kernel2's shared message constants), so the
      per-arm demotion table reads the same on either path.
    - linter: counter `analyze.lint_diags`, gauges
      `analyze.lint_max_severity` ("error"|"warning"|"info") and
      `analyze.lint_codes` (sorted JMC* code list).  Serve adds
      counter `serve.jobs_rejected` + trace event `serve.job_rejected
      {spec, codes}` for submissions refused by the submit-time lint
      gate.

  (PR 10, still jaxmc.metrics/2 — all additive/optional; the mesh
   rank-merge + superstep surface, tpu/mesh.py + jaxmc/meshbench.py:)
    - merge strategy: gauge `mesh.merge` ("rank" | "fullsort") — the
      shard-local dedup-merge that actually ran (rank is the default;
      JAXMC_MESH_RANKMERGE=0 forces the PR-8 fullsort); the mesh
      engine now also re-stamps `dedup.mode` at run start (the PR-6
      gauge was stamped before the mesh subclass forced fp128 keys,
      so multichip artifacts carried a stale value).
    - supersteps: `mesh.host_syncs` now counts SUPERSTEPS — one
      scalar-RING read per dispatch, each dispatch fusing up to
      JAXMC_MESH_SUPERSTEP levels in a device-side lax.while_loop —
      so host_syncs <= level-record count and < on any multi-level
      run; gauges `mesh.supersteps` (== host_syncs for the run) and
      `mesh.superstep_levels` (deepest fused dispatch).  Mesh level
      records gain `superstep` (which dispatch the level rode) and
      their `wall_s` is the dispatch wall amortized over its levels.
    - phase walls (jaxmc.meshbench bench legs, MeshExplorer
      .probe_phase_walls): gauges `mesh.phase_levels`,
      `mesh.phase_expand_s`, `mesh.phase_exchange_s`,
      `mesh.phase_merge_s`, `mesh.phase_merge_rank_s`,
      `mesh.phase_merge_fullsort_s` — a measured expand / exchange /
      merge wall breakdown at the run's learned capacities (both
      merge strategies timed on identical inputs, so the rank win is
      in the artifact); per-probed-level trace event
      `mesh.phase_walls {level, expand_s, exchange_s, merge_rank_s,
      merge_fullsort_s}`.
    - multichip artifacts add per-point `merge`, `supersteps`,
      `superstep_levels` and `phase_walls`; `python -m jaxmc.obs
      diff` accepts two+ jaxmc.multichip/1 artifacts directly and
      gates per-(rung, D) states/sec/chip with REGRESS flags.
    - serve warm-registry eviction (ROADMAP item 3): counter
      `serve.evictions` + trace event `serve.evicted {sig}` when the
      bounded LRU (JAXMC_SERVE_WARM_MAX, default 32) drops the
      least-recently-used idle session; evicted signatures fall back
      to the final-checkpoint resume path (`serve.ckpt_resumes`).
    - mesh capacity profiles (compile/cache.py variant
      mesh-d<D>-<exchange>) gain the MSL key — the superstep
      controller's learned levels-per-dispatch — alongside
      SC/FC/TRL/GAM16.

  (PR 12, still jaxmc.metrics/2 — all additive/optional; the
   out-of-core hierarchical seen set, backend/tiers.py + ISSUE 12:)
    - seen-key mode: gauge `seen.mode` ("exact" | "fingerprint") — the
      dedup-key mode that actually ran (--seen forces it; auto keeps
      the width-based default); gauge `fingerprint.collision_p` — the
      reported n^2 * 2^-129 bound over every admitted key (device +
      cold tiers).  `result` gains `seen_mode` and (fingerprint runs)
      `collision_p`.
    - tier hierarchy: gauge `tier.occupancy` ({device, host, disk}
      keys), gauge `tier.probe_wall_s` (cumulative cold-probe wall),
      gauge `tier.device_cap` (the configured device cap, rows),
      counters `tier.spills` / `tier.spilled_keys` /
      `tier.compactions`; phase span `tier.spill {keys[, shards]}`
      per device-prefix spill; `result.tiers` carries the final
      stats() summary {host_keys, disk_keys, host_runs, disk_runs,
      spills, compactions, probe_wall_s[, io_degraded]}.
    - tier fault containment: trace event + gauge `tier.io_degraded
      {error}` when a disk-tier write fails (ENOSPC, the
      tier_io_error fault site) and the store degrades to
      host-tier-only — counts stay exact; `obs diff` treats its
      appearance like `device.demoted` (a named degradation).
    - truncation attribution: gauge `truncation.reason` and
      `result.trunc_reason` — the EXHAUSTED resource by name
      ("max_states: distinct N >= limit M", "drain", a tier/cap with
      the observed need) so capacity regressions are attributable;
      a bare `truncated` flag no longer ships alone.
    - capacity profiles: resident runs that spilled persist the
      optional TIERK key (cold-tier key total, pow2) alongside
      SC/FCap/AccCap/VC; a capped run that loads one stamps gauge
      `tier.predicted_keys` (the expected out-of-core magnitude)
      before the first spill.

  (PR 13, still jaxmc.metrics/2 — all additive/optional; cross-model
   vmapped batching, backend/batch.py + serve fleet wiring + ISSUE 13:)
    - batch scheduling (fleet telemetry): gauge `serve.batch_sigs`
      (distinct layout-compat classes seen this life), gauge
      `serve.batch_occupancy` (member width of the last vmapped
      cohort), gauge `serve.batch_compiles` (engine builds per cohort
      — 1 by construction), counters `serve.vbatch_jobs` /
      `serve.fastlane_jobs` (analyze-cost-routed queue jumps) /
      `serve.batch_incompatible` (parse-time-compatible cohorts the
      build refused; members requeued solo) / `serve.owner_respawns`
      + trace event `serve.owner_died {error}` (device-owner process
      death; jobs requeued, never lost).
    - batch engine (run-scope telemetry): gauge `batch.width` (member
      lanes in the last vmapped dispatch), counter `batch.dispatches`,
      gauges `batch.members` / `batch.occupancy` /
      `batch.dispatch_count` / `batch.lifted_consts` (the CONSTANT
      names riding the batch axis) / `batch.plan` (the shared
      pack-plan descriptor: width/packed_width/bits_per_state/...).
    - serve job artifacts: the `serve` block gains optional `bsig`
      (the layout-compat class), `cost_estimate` (analyze's
      state-space estimate consumed by the fast lane — null when the
      fixpoint bailed), `batch_occupancy`, `batch_dispatches`,
      `lifted_consts`, and `device_owner` (job ran in the owner
      process); job records carry `bsig`/`cost_estimate`/`fast_lane`.

  (PR 15, still jaxmc.metrics/2 — all additive/optional;
   independence-driven hot path, ISSUE 15:)
    - independence analysis: gauge `analyze.independence_pairs`
      (commuting arm pairs proven by the element-atom footprints),
      gauge `analyze.independence_safe` (arms eligible as singleton
      ample sets), gauge `expand.regrouped` (1 when the fused-group
      plan departed from the legacy contiguous one — counts/traces
      stay byte-identical; `expand.fused_groups` /
      `mesh.grouped_expand` may SHRINK under the new plan).
    - partial-order reduction (opt-in --por): gauge `por.enabled`
      (false + gauge `por.disabled_reason` when the model's
      constructs refuse the reduction), counters `por.ample_states` /
      `por.full_states` (states expanded through a singleton ample
      set vs fully), gauge `por.ample_ratio` (ample / total expanded),
      gauge `por.reduced_states` (the REDUCED run's distinct count —
      compare against an unreduced baseline's result.distinct; raw
      counts shrink BY DESIGN under --por), gauge `por.engine`
      ("interp" on the exact interpreter; "device" since PR 18, when
      the ample mask runs inside the fused device step — the PR 15
      demotion of device --por requests to the interpreter is gone).
    - bounds-sized engines: `profile.status` gains the value
      "predicted" (capacity ladder rung below `learned`: no saved
      profile, but a converged bounds fixpoint proved a state-count
      ceiling), gauges `profile.predicted_states` (the proven
      ceiling) and `profile.predicted_caps` (the buckets sized from
      it — a cold run then pays zero growth-retry recompiles).

  jaxmc.metrics/3  (PR 16) adds, all optional — the fleet-wide
   distributed-tracing + live-exposition surface; every /2 artifact
   remains valid (readers accept both):
    - trace-context propagation (obs/context.py): every trace event
      carries `tid` (16-hex fleet-wide trace id); every trace FILE
      opens with a `proc_meta` header {t, mono, pid, argv, psid,
      parent_span, env} — `psid` is this process's span id,
      `parent_span` the span of whoever spawned it (inherited over
      the JAXMC_TRACE_CTX env var as "<trace_id>:<parent_span_id>";
      absent -> this process is a trace root and `parent_span` is
      null).  Fork-pool workers (engine/parallel.py) write no files;
      the parent emits one `parallel.worker_span {pid, span, parent,
      level}` event per worker instead.  `python -m jaxmc.obs
      timeline` reconstructs the process tree from exactly these two
      shapes and flags orphan spans (a `parent_span` resolving to no
      known `psid`/worker span — a broken propagation hop).
    - search-progress estimation (obs/progress.py): trace event
      `progress_estimate {estimate, source}` when analyze's bounds
      fixpoint proved a state-space ceiling; gauge
      `search.progress_est` (fraction of the estimate explored, live
      during the run); heartbeat events gain `progress_fraction` /
      `progress_eta_s` / `progress_verdict` ("est" | "unbounded" —
      unbounded when no estimate exists or the observed distinct
      count exceeded it); `--progress-every` stdout lines (and their
      `log` mirrors) gain the same "~N% of est. M states, ETA Ks"
      suffix, including the immediate first line.
    - live exposition (serve/daemon.py): `GET /metrics` renders the
      daemon's counters/gauges plus per-job series in Prometheus
      text format 0.0.4.  Name grammar: `jaxmc_` + the internal
      dotted name with every character outside [a-zA-Z0-9_] mapped
      to `_` (obs.prom_name — e.g. `serve.queue_depth` ->
      `jaxmc_serve_queue_depth`, `search.progress_est` ->
      `jaxmc_search_progress_est`); per-job samples carry a
      `{job="<id>"}` label; derived per-job series:
      `jaxmc_job_running`, `jaxmc_job_levels`,
      `jaxmc_job_states_per_sec`, `jaxmc_job_progress_distinct`,
      `jaxmc_job_progress_eta_s`.  `GET /jobs/<id>/events` serves
      the job's bounded in-memory event ring (JAXMC_TRACE_RING,
      default 256 events) readable MID-RUN; `GET /status` gains a
      `progress` block {job id -> progress snapshot}.  Scrapes never
      block job threads (bounded ring + lock-copy snapshots).
    - per-job watchdogs (serve fleet): each in-daemon job and each
      owner-side solo job runs its own obs.Watchdog over the job's
      Telemetry, so one slow tenant cannot mask another job's stall;
      job heartbeat/stall events land in the per-job trace
      (`<spool>/results/<id>.trace.jsonl`) and ring.

  jaxmc.metrics/4  (PR 17) adds, all optional — the device profiler +
   HBM accounting + run-ledger surface; every /3 artifact remains
   valid (readers accept both):
    - the `prof{}` block (obs/prof.py): stamped by any run whose
      profiler recorded something (always under --profile; under the
      always-on cheap mode only when a dispatch site fired).  Grammar:
        prof: {
          mode: "cheap" | "wall" | "xla",
          sites: { <site>: {              # e.g. "bfs.resident_run",
            dispatches: int,              #   "mesh.superstep",
            recompiles: int,              #   "batch.vstep", ...
            wall_s?: float,               # block-until-ready wall
            analysis_wall_s?: float,      # one-shot lowering retrace
            arg_bytes?: int,              # cumulative argument bytes
            res_bytes?: int,              # cumulative result bytes
            cost?: {flops?: int,          # one-shot AOT
                    bytes_accessed?: int} # lowering cost_analysis
          }, ... },
          hbm: {
            buffers: { <name>: bytes },   # the device-memory MODEL:
                                          # resident.seen/.frontier/
                                          # .accumulator/.candidates,
                                          # mesh.seen_shards/.frontier/
                                          # .trace_ring/.a2a_buckets,
                                          # level.seen/.frontier, ...
            peak_bytes: int,              # model high-water
            measured_peak_bytes?: int     # cross-check: sum of
                                          # device memory_stats()
                                          # peak_bytes_in_use, when
                                          # the backend exposes it
          },
          xla_trace_dir?: str             # --profile=xla capture dir
        }
      Cheap mode records counts/recompiles only; wall/xla add the
      sync + byte surfaces.  Profiling NEVER changes results: counts
      and traces stay bit-identical profile-on vs profile-off
      (pinned by tests and `make prof-check`).
    - watchdog heartbeat events gain optional `device_mem_bytes` (the
      HBM model's current total) next to `rss_bytes`; stall events
      gain an optional dominant-site suffix in `msg` ("; 92% in
      mesh.superstep") naming where the wall concentrated at stall
      time.
    - live exposition (serve/daemon.py): per-job `/metrics` series
      gain `jaxmc_prof_site_dispatches` / `jaxmc_prof_site_wall_s`
      (labels `{job,site}`) and `jaxmc_hbm_peak_bytes` {job}.
      Completed jobs' `{job=...}` series persist for
      JAXMC_METRICS_JOB_TTL seconds (default 600) after completion —
      `jaxmc_job_running 0` plus the final gauges — then drop, so
      fleet lifetime no longer grows scrape cardinality without
      bound.
    - the run ledger (obs/ledger.py) is a SIBLING artifact, not part
      of the metrics schema: an append-only JSONL (default
      ~/.cache/jaxmc/ledger.jsonl; JAXMC_LEDGER=path overrides,
      =off disables) of one-line trajectory points
        {v:1, id, ts, rung, run, kind, states_per_sec, platform,
         env, source, sig?}
      content-addressed by `id` = sha1(rung, ts, rate, sig, env,
      source)[:16] — flock-appended, torn-line tolerant, idempotent
      to re-import.  `python -m jaxmc.obs history` renders/gates it.

  (PR 18, still jaxmc.metrics/4 — all additive/optional; device-side
   POR + dynamic element keys + structural batch-bound merge:)
    - device POR (--por on the jax/mesh backends): gauge `por.engine`
      gains the value "device" (ample mask applied INSIDE the fused
      step — level, resident, host_seen, and mesh supersteps; zero
      extra dispatches), gauge `por.device_masked_arms` (candidate
      rows the device mask dropped before dedup/exchange — the raw
      arm-level reduction the por.ample_states/full_states counters
      summarise per state), and the existing `por.ample_ratio` /
      `por.reduced_states` gauges are now also emitted by the device
      engines with IDENTICAL semantics (counts are bit-identical
      across engine shapes, including mesh data-parallel runs, where
      the ample probe is psum-distributed over the pre-level seen
      snapshot).  `por.disabled_reason` gains the mesh host-loop
      refusal (JAXMC_MESH_RESIDENT=0 escape hatch).
    - independence analysis: the arm-footprint report adds per-arm
      dynamic-key classes (element-commuting / whole-var writes /
      full-footprint bail) surfaced by `jaxmc info --cfg`; no new
      metrics keys.
    - batch engine: `batch.plan` (the shared pack-plan descriptor)
      now reflects the STRUCTURAL per-element bound merge — the donor
      packs container elements at the interval-union of every
      member's proven element bounds instead of falling back to
      whole-variable summaries; `bits_per_state` never exceeds the
      worst solo member's.

  (PR 19, still jaxmc.metrics/4 — all additive/optional; fleet-grade
   serving: leases + takeover, admission control, quarantine:)
    - serve fleet gauges: `serve.fleet_daemons` (live daemon-registry
      records within the lease TTL), `serve.leases_held` (jobs this
      daemon currently holds a lease on).
    - serve fleet counters: `serve.takeovers` (expired leases this
      daemon stole), `serve.jobs_adopted` (spool jobs pulled into the
      local queue by the fleet scanner), `serve.jobs_deferred`
      (submissions accepted but left unclaimed for a warmer peer),
      `serve.affinity_adoptions` (adoptions won on sig/bsig warmth),
      `serve.lease_lost` / `serve.lease_lost_drops` (renewals lost to
      a thief / results discarded because the lease was lost),
      `serve.lease_stalls` (injected fleet-tick stalls),
      `serve.quarantined` (jobs moved to spool/quarantine after the
      cross-daemon retry budget), `serve.admission_rejected` (429s),
      `serve.spool_retries` / `serve.spool_degraded` (transient spool
      write retries / writes that exhausted them).  `obs diff` flags
      the APPEARANCE of admission_rejected and spool_degraded like
      the tier degradation gauge (REGRESS lines).
    - job records (serve artifacts / GET /jobs): optional `daemon`
      (the fleet member that ran the job), `tenant` (admission
      accounting principal), `stolen_by` + `requeue_note` (lease-
      expiry takeover provenance); job status gains "quarantined".
    - batch counters: `batch.resume_refused` (a cohort member's
      checkpoint could not seed the merged layout; it ran fresh).
"""

from __future__ import annotations

from typing import Any, Dict

SCHEMA = "jaxmc.metrics/4"

# every schema revision an artifact may carry and a reader must accept
# (additive history: a v1 artifact simply lacks the v2 optional surface)
SCHEMAS = ("jaxmc.metrics/1", "jaxmc.metrics/2", "jaxmc.metrics/3",
           "jaxmc.metrics/4")

# top-level summary keys every artifact must carry
REQUIRED_KEYS = ("schema", "started_at", "wall_s", "phases", "counters",
                 "gauges", "levels")

# keys a `check` run's artifact adds
CHECK_KEYS = ("backend", "spec", "result")

# required fields of summary["result"] for a check run
RESULT_KEYS = ("ok", "distinct", "generated", "diameter", "truncated")

PHASE_KEYS = ("name", "wall_s", "count")

# required fields of the watchdog trace events (jaxmc/obs/watchdog.py)
HEARTBEAT_KEYS = ("ev", "t", "wall_s", "open_spans", "last_level",
                  "progress_seq")
STALL_KEYS = ("ev", "t", "stalled_for_s", "threshold_s", "open_spans",
              "last_level")


def validate_summary(s: Dict[str, Any], check_run: bool = False) -> None:
    """Structural validation; raises ValueError naming the defect."""
    if not isinstance(s, dict):
        raise ValueError(f"summary is {type(s).__name__}, not a dict")
    missing = [k for k in REQUIRED_KEYS if k not in s]
    if check_run:
        missing += [k for k in CHECK_KEYS if k not in s]
    if missing:
        raise ValueError(f"summary missing keys: {missing}")
    if s["schema"] not in SCHEMAS:
        raise ValueError(f"schema {s['schema']!r} not in {SCHEMAS!r}")
    if not isinstance(s["phases"], list):
        raise ValueError("phases is not a list")
    for ph in s["phases"]:
        miss = [k for k in PHASE_KEYS if k not in ph]
        if miss:
            raise ValueError(f"phase {ph!r} missing {miss}")
        if ph["wall_s"] < 0:
            raise ValueError(f"phase {ph['name']} has negative wall_s")
    if not isinstance(s["counters"], dict) or \
            not isinstance(s["gauges"], dict):
        raise ValueError("counters/gauges must be dicts")
    if not isinstance(s["levels"], list):
        raise ValueError("levels is not a list")
    prev = None
    for rec in s["levels"]:
        if "level" not in rec:
            raise ValueError(f"level record {rec!r} missing 'level'")
        if prev is not None and rec["level"] < prev:
            raise ValueError(
                f"level indices not monotone: {rec['level']} after {prev}")
        prev = rec["level"]
    if check_run:
        res = s["result"]
        miss = [k for k in RESULT_KEYS if k not in res]
        if miss:
            raise ValueError(f"result missing keys: {miss}")


def validate_trace_event(e: Dict[str, Any]) -> None:
    """Structural validation of one trace JSONL event. Only the watchdog
    events carry enough required structure to pin; other event kinds
    need just the `ev`/`t` envelope."""
    if not isinstance(e, dict):
        raise ValueError(f"event is {type(e).__name__}, not a dict")
    if "ev" not in e:
        raise ValueError("event missing 'ev'")
    # every event is timestamped: `t` everywhere except span-close,
    # which carries its open time as `t0` (see the grammar above)
    tkey = "t0" if e["ev"] == "span" else "t"
    if tkey not in e:
        raise ValueError(f"event {e['ev']!r} missing {tkey!r}")
    required = {"heartbeat": HEARTBEAT_KEYS, "stall": STALL_KEYS}.get(
        e["ev"])
    if required is None:
        return
    miss = [k for k in required if k not in e]
    if miss:
        raise ValueError(f"{e['ev']} event missing {miss}")
    if not isinstance(e["open_spans"], list):
        raise ValueError(f"{e['ev']}.open_spans is not a list")
    if e["ev"] == "heartbeat" and e["wall_s"] < 0:
        raise ValueError("heartbeat has negative wall_s")
    if e["ev"] == "stall" and e["stalled_for_s"] < 0:
        raise ValueError("stall has negative stalled_for_s")
