r"""The `--metrics-out` / `--trace` event schema, as data.

One place pins what every artifact must carry so the CLI, bench.py, the
sweep driver, and tests/test_obs.py agree. `validate_summary` raises
ValueError with the missing/ill-typed field names — it is deliberately
structural (required keys + types + level-index monotonicity), not
exhaustive: engines are free to add fields.

Trace JSONL event grammar (one JSON object per line, `ev` discriminates):

  run_start  {t, meta}
  span_open  {name, t, parent, attrs}      -- partial-span forensics
  span       {name, t0, wall_s, attrs[, error]}
  level      {level, t, frontier?, generated?, new?, distinct?, ...}
  counter/gauge changes are rolled up in the summary only
  log        {t, msg}                      -- mirror of the stdout line
  run_end    {t}

Summary (metrics-out) required surface: see REQUIRED_KEYS below; each
phases[i] carries {name, wall_s, count} (+optional open=True for spans
still running at rollup — the deadline-blowout record); each levels[i]
carries at least {level} with non-decreasing level indices.
"""

from __future__ import annotations

from typing import Any, Dict

SCHEMA = "jaxmc.metrics/1"

# top-level summary keys every artifact must carry
REQUIRED_KEYS = ("schema", "started_at", "wall_s", "phases", "counters",
                 "gauges", "levels")

# keys a `check` run's artifact adds
CHECK_KEYS = ("backend", "spec", "result")

# required fields of summary["result"] for a check run
RESULT_KEYS = ("ok", "distinct", "generated", "diameter", "truncated")

PHASE_KEYS = ("name", "wall_s", "count")


def validate_summary(s: Dict[str, Any], check_run: bool = False) -> None:
    """Structural validation; raises ValueError naming the defect."""
    if not isinstance(s, dict):
        raise ValueError(f"summary is {type(s).__name__}, not a dict")
    missing = [k for k in REQUIRED_KEYS if k not in s]
    if check_run:
        missing += [k for k in CHECK_KEYS if k not in s]
    if missing:
        raise ValueError(f"summary missing keys: {missing}")
    if s["schema"] != SCHEMA:
        raise ValueError(f"schema {s['schema']!r} != {SCHEMA!r}")
    if not isinstance(s["phases"], list):
        raise ValueError("phases is not a list")
    for ph in s["phases"]:
        miss = [k for k in PHASE_KEYS if k not in ph]
        if miss:
            raise ValueError(f"phase {ph!r} missing {miss}")
        if ph["wall_s"] < 0:
            raise ValueError(f"phase {ph['name']} has negative wall_s")
    if not isinstance(s["counters"], dict) or \
            not isinstance(s["gauges"], dict):
        raise ValueError("counters/gauges must be dicts")
    if not isinstance(s["levels"], list):
        raise ValueError("levels is not a list")
    prev = None
    for rec in s["levels"]:
        if "level" not in rec:
            raise ValueError(f"level record {rec!r} missing 'level'")
        if prev is not None and rec["level"] < prev:
            raise ValueError(
                f"level indices not monotone: {rec['level']} after {prev}")
        prev = rec["level"]
    if check_run:
        res = s["result"]
        miss = [k for k in RESULT_KEYS if k not in res]
        if miss:
            raise ValueError(f"result missing keys: {miss}")
