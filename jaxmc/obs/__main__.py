"""`python -m jaxmc.obs` — the metrics report/diff CLI (obs/report.py).

Deliberately free of jax imports: the report path must work (and is
smoke-tested) in environments where only the interpreter backend runs.
"""

import sys

from .report import main

sys.exit(main())
