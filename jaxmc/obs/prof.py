r"""Device profiler (ISSUE 17): per-dispatch attribution + HBM accounting.

PR 11 left one perf target unmet — merge wall <30% of step wall — partly
because nothing below the PHASE level said where device time went:
`phase_walls` names "the fused step is slow", not which dispatch site,
buffer traffic, or recompile paid for it.  This module is the missing
layer:

  sites     every jitted entry point in the engines registers a NAMED
            dispatch site via `wrap("bfs.level_step", jitted)`; the
            wrapper resolves the active recorder's Profiler at CALL
            time (so the serve daemon's per-thread recorders work
            unchanged) and records per-site stats.
  cheap     the always-on mode: dispatch counts + recompile attribution
            only (a `_cache_size()` delta around the call) — no sync,
            no byte walks, so profile-off runs stay byte-identical and
            effectively free.
  wall      `--profile`: additionally blocks until the output pytree is
            ready and charges the wall to the site, sums argument /
            result bytes per dispatch, and asks the AOT lowering's
            cost_analysis once per site for flops / bytes-accessed.
            Synchronization cannot change counts or traces — profile-on
            vs profile-off stays bit-identical (pinned by tests and
            `make prof-check`).
  xla       wall + the CLI wraps the run in a jax.profiler.trace
            capture to a named artifact dir.
  hbm       a device-memory MODEL from the capacity profile / LanePlan:
            engines register named buffers (seen shards, frontier,
            trace ring, a2a buckets, tier tables) as byte sizes the
            moment their capacities are known; the running sum's
            high-water is `prof.hbm_peak_bytes`, cross-checked against
            `jax.local_devices()[0].memory_stats()` where the backend
            exposes it.

The rollup lands in the metrics artifact as the `prof{}` block (schema
jaxmc.metrics/4, obs/schema.py) and renders via `python -m jaxmc.obs
top` — the table that answers where the 44–77% goes.  This module is
import-clean of jax (the report CLI must run in interp-only
environments); jax is imported lazily inside the wall-mode paths only.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# resolved lazily to avoid a telemetry<->prof import cycle (telemetry
# imports Profiler at module load; we only need current() at call time)
_current = None


def _cur():
    global _current
    if _current is None:
        from .telemetry import current as _current
    return _current()


def _nbytes(x) -> int:
    """Best-effort byte count of a pytree-ish value without importing
    jax: arrays expose .nbytes; containers recurse; scalars are 0."""
    nb = getattr(x, "nbytes", None)
    if isinstance(nb, int):
        return nb
    if isinstance(x, dict):
        return sum(_nbytes(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return sum(_nbytes(v) for v in x)
    return 0


class SiteStats:
    """Per-site accumulators.  Mutated under the owning Profiler's
    lock; read via Profiler.snapshot()."""

    __slots__ = ("name", "dispatches", "wall_s", "analysis_wall_s",
                 "arg_bytes", "res_bytes", "recompiles", "cost",
                 "_analyzed")

    def __init__(self, name: str):
        self.name = name
        self.dispatches = 0
        self.wall_s = 0.0
        self.analysis_wall_s = 0.0
        self.arg_bytes = 0
        self.res_bytes = 0
        self.recompiles = 0
        self.cost: Optional[Dict[str, Any]] = None
        self._analyzed = False

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"dispatches": self.dispatches,
                             "recompiles": self.recompiles}
        if self.wall_s:
            d["wall_s"] = round(self.wall_s, 6)
        if self.analysis_wall_s:
            d["analysis_wall_s"] = round(self.analysis_wall_s, 6)
        if self.arg_bytes or self.res_bytes:
            d["arg_bytes"] = self.arg_bytes
            d["res_bytes"] = self.res_bytes
        if self.cost:
            d["cost"] = dict(self.cost)
        return d


class Profiler:
    """One per live Telemetry (NullTelemetry carries `prof = None`, so
    the un-instrumented hot path costs one getattr + a None test)."""

    CHEAP, WALL, XLA = "cheap", "wall", "xla"

    def __init__(self, mode: str = "cheap",
                 clock=time.perf_counter):
        self.mode = mode
        self._clock = clock
        self._lock = threading.Lock()
        self.sites: Dict[str, SiteStats] = {}
        self._buffers: Dict[str, int] = {}
        self.hbm_peak_bytes = 0
        self.xla_trace_dir: Optional[str] = None

    # ---- dispatch sites ------------------------------------------------
    def _site(self, name: str) -> SiteStats:
        st = self.sites.get(name)
        if st is None:
            with self._lock:
                st = self.sites.setdefault(name, SiteStats(name))
        return st

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        cs = getattr(fn, "_cache_size", None)
        if not callable(cs):
            return None
        try:
            return int(cs())
        except Exception:  # noqa: BLE001 — profiling never breaks a run
            return None

    def record(self, name: str, fn, args, kwargs):
        """One profiled dispatch.  Cheap mode: count + recompile delta
        only.  Wall mode: + block-until-ready wall and arg/result
        bytes, + a one-time AOT cost_analysis per site."""
        st = self._site(name)
        cs0 = self._cache_size(fn)
        if self.mode == self.CHEAP:
            out = fn(*args, **kwargs)
            cs1 = self._cache_size(fn)
            with self._lock:
                st.dispatches += 1
                if cs0 is not None and cs1 is not None and cs1 > cs0:
                    st.recompiles += cs1 - cs0
            return out
        t0 = self._clock()
        out = fn(*args, **kwargs)
        out = self._block(out)
        dt = self._clock() - t0
        cs1 = self._cache_size(fn)
        ab = _nbytes(args) + _nbytes(kwargs)
        rb = _nbytes(out)
        with self._lock:
            st.dispatches += 1
            st.wall_s += dt
            st.arg_bytes += ab
            st.res_bytes += rb
            if cs0 is not None and cs1 is not None and cs1 > cs0:
                st.recompiles += cs1 - cs0
            analyze = not st._analyzed
            if analyze:
                st._analyzed = True
        if analyze:
            # the one-shot lowering retrace is PROFILER-caused wall
            # inside the search phase; charge it to the site (its own
            # column, not wall_s) so the attribution metric stays honest
            ta = self._clock()
            self._analyze(st, fn, args, kwargs)
            with self._lock:
                st.analysis_wall_s += self._clock() - ta
        return out

    @staticmethod
    def _block(out):
        """Synchronize on the output pytree so the recorded wall covers
        the device work, not just the async dispatch.  A sync cannot
        change values — counts/traces stay bit-identical."""
        try:
            import jax
            return jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-jax outputs pass through
            return out

    def _analyze(self, st: SiteStats, fn, args, kwargs) -> None:
        """One-shot AOT cost analysis for the site (wall mode only;
        JAXMC_PROF_COST=0 disables — the lowering retrace costs a few
        hundred ms on big programs)."""
        if os.environ.get("JAXMC_PROF_COST", "").strip() == "0":
            return
        try:
            lowered = fn.lower(*args, **kwargs)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            cost = {}
            for key, out_key in (("flops", "flops"),
                                 ("bytes accessed", "bytes_accessed")):
                v = ca.get(key) if isinstance(ca, dict) else None
                if isinstance(v, (int, float)):
                    cost[out_key] = int(v)
            if cost:
                with self._lock:
                    st.cost = cost
        except Exception:  # noqa: BLE001 — cost analysis is best-effort
            pass

    def dominant_site(self) -> Optional[Tuple[str, float]]:
        """(site name, share) of the site holding the largest wall
        share (wall mode) or dispatch share (cheap mode); None when no
        dispatches were recorded yet.  The watchdog's stall suffix."""
        with self._lock:
            if not self.sites:
                return None
            walls = {n: s.wall_s for n, s in self.sites.items()}
            total = sum(walls.values())
            if total > 0:
                name = max(walls, key=walls.get)
                return name, walls[name] / total
            disp = {n: s.dispatches for n, s in self.sites.items()}
            total = sum(disp.values())
            if total > 0:
                name = max(disp, key=disp.get)
                return name, disp[name] / total
            return None

    # ---- HBM accounting ------------------------------------------------
    def note_buffer(self, name: str, nbytes) -> None:
        """Register (or resize) one named device buffer in the memory
        model; the running total's high-water is hbm_peak_bytes."""
        try:
            nb = int(nbytes)
        except (TypeError, ValueError):
            return
        with self._lock:
            self._buffers[name] = nb
            cur = sum(self._buffers.values())
            if cur > self.hbm_peak_bytes:
                self.hbm_peak_bytes = cur

    def drop_buffer(self, name: str) -> None:
        with self._lock:
            self._buffers.pop(name, None)

    def hbm_current_bytes(self) -> int:
        with self._lock:
            return sum(self._buffers.values())

    def hbm_buffers(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._buffers)

    # ---- rollup --------------------------------------------------------
    def snapshot(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """The `prof{}` artifact block (schema notes in obs/schema.py).
        None when nothing was recorded and the mode is cheap (so
        un-instrumented artifacts carry no empty noise block) unless
        `force`."""
        with self._lock:
            sites = {n: s.as_dict() for n, s in self.sites.items()}
            buffers = dict(self._buffers)
            peak = self.hbm_peak_bytes
        if not force and not sites and not buffers \
                and self.mode == self.CHEAP:
            return None
        out: Dict[str, Any] = {"mode": self.mode, "sites": sites}
        hbm: Dict[str, Any] = {"buffers": buffers, "peak_bytes": peak}
        measured = _measured_peak()
        if measured is not None:
            hbm["measured_peak_bytes"] = measured
        out["hbm"] = hbm
        if self.xla_trace_dir:
            out["xla_trace_dir"] = self.xla_trace_dir
        return out


def _measured_peak() -> Optional[int]:
    from .telemetry import device_mem_high_water
    return device_mem_high_water()


def wrap(name: str, fn):
    """Register `fn` (typically a jitted callable) as the named
    dispatch site.  The active recorder's Profiler is resolved at CALL
    time; with no live recorder (NullTelemetry.prof is None) the
    wrapper is one getattr + a None test."""
    def profiled(*args, **kwargs):
        prof = getattr(_cur(), "prof", None)
        if prof is None:
            return fn(*args, **kwargs)
        return prof.record(name, fn, args, kwargs)

    profiled.__wrapped__ = fn
    profiled.__name__ = getattr(fn, "__name__", name)
    profiled.profiler_site = name
    return profiled


def note_buffer(name: str, nbytes) -> None:
    """Module-level HBM-model convenience for engine code: a no-op
    unless a live recorder (with a Profiler) is installed."""
    prof = getattr(_cur(), "prof", None)
    if prof is not None:
        prof.note_buffer(name, nbytes)


# ------------------------------------------------------- rollup helpers

def attribution(summary: Dict[str, Any]) -> Dict[str, Any]:
    """How much of the measured search wall the named sites explain —
    the `make prof-check` acceptance metric.  Pure dict math (no jax):
    works on any jaxmc.metrics/4 artifact."""
    prof = summary.get("prof") or {}
    sites = prof.get("sites") or {}
    attributed = sum((s.get("wall_s") or 0.0)
                     + (s.get("analysis_wall_s") or 0.0)
                     for s in sites.values())
    search = None
    for ph in summary.get("phases", []) or []:
        if ph.get("name") == "search":
            search = ph.get("wall_s")
            break
    share = (attributed / search) if search else None
    return {"attributed_wall_s": round(attributed, 6),
            "search_wall_s": search,
            "share": None if share is None else round(share, 4)}


# package-namespace aliases (obs.prof_wrap / obs.prof_attribution):
# "wrap" and "attribution" are too generic at the obs level
prof_wrap = wrap
prof_attribution = attribution


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:,.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:,.1f}TB"


def cmd_top(args, out=None) -> int:
    """`python -m jaxmc.obs top FILE` — the per-site table: wall,
    share of the search wall, dispatches, bytes per dispatch,
    recompiles; plus the HBM model.  Exit 2 when the artifact carries
    no prof block (pre-/4 artifact, or an un-instrumented run)."""
    import json
    import sys
    out = out if out is not None else sys.stdout
    with open(args.file, encoding="utf-8") as fh:
        summary = json.load(fh)
    prof = summary.get("prof")
    if not isinstance(prof, dict) or not (prof.get("sites")
                                          or prof.get("hbm")):
        print(f"error: {args.file}: no prof block (run with --profile, "
              f"or any telemetry-enabled run on jaxmc.metrics/4+)",
              file=sys.stderr)
        return 2
    sites: Dict[str, Dict[str, Any]] = prof.get("sites") or {}
    att = attribution(summary)
    search = att["search_wall_s"]
    print(f"== prof top: {args.file} (mode={prof.get('mode')})",
          file=out)
    rows: List[Tuple[str, Dict[str, Any]]] = sorted(
        sites.items(),
        key=lambda kv: (-(kv[1].get("wall_s") or 0.0),
                        -kv[1].get("dispatches", 0)))
    if rows:
        w = max(len(n) for n, _ in rows)
        print(f"  {'site':<{w}}  {'wall':>9}  {'share':>6}  "
              f"{'disp':>6}  {'arg/disp':>10}  {'res/disp':>10}  "
              f"{'recomp':>6}", file=out)
        for name, s in rows:
            wall = s.get("wall_s")
            share = (wall / search * 100.0) if wall and search else None
            d = max(s.get("dispatches", 0), 1)
            print(
                f"  {name:<{w}}  "
                f"{'-' if wall is None else f'{wall:9.3f}s'[:10]:>9}  "
                f"{'-' if share is None else f'{share:5.1f}%':>6}  "
                f"{s.get('dispatches', 0):>6}  "
                f"{_fmt_bytes(s.get('arg_bytes', 0) / d if s.get('arg_bytes') else None):>10}  "
                f"{_fmt_bytes(s.get('res_bytes', 0) / d if s.get('res_bytes') else None):>10}  "
                f"{s.get('recompiles', 0):>6}", file=out)
    else:
        print("  (no dispatch sites recorded)", file=out)
    if att["share"] is not None:
        print(f"attributed {att['share'] * 100.0:.1f}% of the search "
              f"wall ({att['attributed_wall_s']:.3f}s of "
              f"{search:.3f}s)", file=out)
    hbm = prof.get("hbm") or {}
    bufs = hbm.get("buffers") or {}
    if bufs or hbm.get("peak_bytes"):
        meas = hbm.get("measured_peak_bytes")
        print(f"hbm model: peak {_fmt_bytes(hbm.get('peak_bytes'))}"
              + (f" (measured {_fmt_bytes(meas)})"
                 if meas is not None else ""), file=out)
        for bname in sorted(bufs, key=lambda b: -bufs[b]):
            print(f"  {bname:<28} {_fmt_bytes(bufs[bname]):>12}",
                  file=out)
    return 0
