r"""`python -m jaxmc.obs timeline <artifacts...>` — merge multi-process
JSONL traces into one causally-ordered per-process-lane view.

Every trace file opens with a `proc_meta` header (obs/telemetry.py):
pid, argv, env fingerprint, a monotonic-clock anchor, the process's
span id (`psid`) and the span of whoever spawned it (`parent_span`,
carried over the JAXMC_TRACE_CTX env var — obs/context.py).  Fork-pool
workers write no files of their own; the parent's trace carries one
`parallel.worker_span` event per worker pid instead.  From those two
sources the renderer reconstructs the process tree, assigns every file
a LANE, and prints all events merged in time order with lane tags.

Diagnostics:
  orphan spans   a lane whose parent_span resolves to no known process
                 span — a broken propagation hop (the chaos suite pins
                 zero orphans across worker SIGKILL + respawn);
  gaps           a silent stretch inside one lane longer than
                 --gap-threshold while the run was live — where to look
                 when a fleet wedged;
  heartbeat/stall events render with their stalled_for/threshold fields
                 (the PR-2 grammar), so a stalled lane is visible inline.

The last line is machine-parseable (the trace-check gate asserts on
it):

    summary: files=N processes=N lanes=N events=N orphans=N gaps=N

Stdlib-only, like the rest of the report path: timeline must work where
only the interpreter backend runs.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def _load_events(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path, encoding="utf-8") as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                ev = json.loads(ln)
            except ValueError:
                continue  # torn final line of a killed writer
            if isinstance(ev, dict):
                out.append(ev)
    return out


def _ev_time(ev: Dict[str, Any]) -> Optional[float]:
    t = ev.get("t0") if ev.get("ev") == "span" else ev.get("t")
    return t if isinstance(t, (int, float)) else None


class _Lane:
    __slots__ = ("key", "label", "pid", "span", "parent", "events",
                 "source", "command")

    def __init__(self, key, pid, span, parent, source, command=None):
        self.key = key
        self.label = ""
        self.pid = pid
        self.span = span
        self.parent = parent
        self.source = source
        self.command = command
        self.events: List[Dict[str, Any]] = []


def _describe(ev: Dict[str, Any]) -> str:
    kind = ev.get("ev")
    if kind == "proc_meta":
        return f"proc_meta pid={ev.get('pid')}"
    if kind == "run_start":
        cmd = (ev.get("meta") or {}).get("command")
        return f"run_start {cmd or ''}".rstrip()
    if kind == "span_open":
        return f"span_open {ev.get('name')}"
    if kind == "span":
        err = f" ERROR={ev['error']}" if ev.get("error") else ""
        return f"span {ev.get('name')} ({ev.get('wall_s')}s){err}"
    if kind == "level":
        return (f"level {ev.get('level')} "
                f"distinct={ev.get('distinct')} "
                f"queue={ev.get('queue')}")
    if kind == "heartbeat":
        extra = ""
        if ev.get("progress_verdict") is not None:
            extra = f" progress={ev.get('progress_fraction')}" \
                    f" verdict={ev['progress_verdict']}"
        return (f"heartbeat stalled_for={ev.get('stalled_for_s')}s "
                f"level={ev.get('last_level')}{extra}")
    if kind == "stall":
        return (f"STALL {ev.get('stalled_for_s')}s "
                f"(threshold {ev.get('threshold_s')}s) "
                f"open={'>'.join(ev.get('open_spans') or [])}")
    if kind == "log":
        msg = str(ev.get("msg") or "")
        return f"log {msg[:90]}"
    if kind == "parallel.worker_span":
        return (f"worker_span pid={ev.get('pid')} "
                f"span={str(ev.get('span'))[:8]}")
    return str(kind)


def cmd_timeline(args, out) -> int:
    lanes: List[_Lane] = []
    psids: Dict[str, _Lane] = {}  # process span id -> its file lane
    trace_ids: set = set()
    files_loaded = 0
    for path in args.files:
        evs = _load_events(path)
        files_loaded += 1
        meta = next((e for e in evs if e.get("ev") == "proc_meta"), None)
        run0 = next((e for e in evs if e.get("ev") == "run_start"), None)
        cmd = (run0 or {}).get("meta", {}).get("command") \
            if run0 else None
        if meta is not None:
            lane = _Lane(path, meta.get("pid"), meta.get("psid"),
                         meta.get("parent_span"), path, cmd)
            if meta.get("psid"):
                # several recorders in one process (a daemon's fleet +
                # in-process job tels) share one psid; the first file
                # seen resolves it
                psids.setdefault(meta["psid"], lane)
        else:  # pre-PR-16 artifact: still render, just unparented
            lane = _Lane(path, None, None, None, path, cmd)
        for e in evs:
            if e.get("tid"):
                trace_ids.add(e["tid"])
        lane.events = evs
        lanes.append(lane)

    # fork-pool workers: lanes synthesized from the parents' events
    worker_lanes: List[_Lane] = []
    for lane in list(lanes):
        for e in lane.events:
            if e.get("ev") == "parallel.worker_span":
                wl = _Lane(f"worker:{e.get('span')}", e.get("pid"),
                           e.get("span"), e.get("parent"),
                           lane.source, "worker")
                wl.events = [e]
                worker_lanes.append(wl)
    lanes.extend(worker_lanes)

    # ---- process tree + orphan detection ----
    orphans = []
    for lane in lanes:
        if lane.parent is not None and lane.parent not in psids:
            orphans.append(lane)

    pids = {ln.pid for ln in lanes if ln.pid is not None}
    for i, lane in enumerate(sorted(
            lanes, key=lambda ln: (_ev_time(ln.events[0])
                                   if ln.events and
                                   _ev_time(ln.events[0]) is not None
                                   else 0.0))):
        lane.label = f"P{i}"

    tid_txt = ",".join(sorted(trace_ids)) or "none"
    print(f"timeline: {files_loaded} file"
          f"{'s' if files_loaded != 1 else ''}, "
          f"{len(pids)} process{'es' if len(pids) != 1 else ''}, "
          f"trace {tid_txt}", file=out)
    for lane in sorted(lanes, key=lambda ln: ln.label):
        par = psids.get(lane.parent)
        ptxt = "(root)" if lane.parent is None else \
            (f"parent={par.label}" if par is not None
             else f"parent=ORPHAN({str(lane.parent)[:8]})")
        span8 = str(lane.span)[:8] if lane.span else "-"
        print(f"  {lane.label:<4} pid={lane.pid or '?':<8} "
              f"{(lane.command or '?'):<16} {ptxt:<22} "
              f"span={span8} events={len(lane.events)}", file=out)

    # ---- merged, time-ordered event listing ----
    tagged = []
    for lane in lanes:
        if lane.command == "worker":
            continue  # worker lanes' one event renders via the parent
        for e in lane.events:
            t = _ev_time(e)
            if t is not None:
                tagged.append((t, lane.label, e))
    tagged.sort(key=lambda x: (x[0], x[1]))
    t0 = tagged[0][0] if tagged else 0.0

    gaps = 0
    last_per_lane: Dict[str, float] = {}
    limit = args.limit if args.limit and args.limit > 0 else len(tagged)
    shown = 0
    for t, label, e in tagged:
        prev = last_per_lane.get(label)
        last_per_lane[label] = t
        if prev is not None and t - prev > args.gap_threshold:
            gaps += 1
            print(f"  ........ {label} silent for {t - prev:.1f}s "
                  f"(gap threshold {args.gap_threshold:.0f}s)",
                  file=out)
        if shown < limit:
            print(f"  +{t - t0:9.3f}s {label:<4} {_describe(e)}",
                  file=out)
            shown += 1
    if shown < len(tagged):
        print(f"  ... {len(tagged) - shown} more events "
              f"(--limit {args.limit})", file=out)

    for lane in orphans:
        print(f"  ORPHAN: {lane.label} ({lane.source}) parent span "
              f"{lane.parent} not found in any artifact — broken "
              f"trace-context hop or missing file", file=out)
    print(f"summary: files={files_loaded} processes={len(pids)} "
          f"lanes={len(lanes)} events={len(tagged)} "
          f"orphans={len(orphans)} gaps={gaps}", file=out)
    if args.fail_on_orphans and orphans:
        return 1
    return 0
