r"""Watchdog: a daemon heartbeat thread that names a stall WHILE it is
happening.

Motivation (ISSUE 2 / BENCH_r05): the device bench degraded to the
interpreter because device init wedged inside the 480 s deadline, and
nothing in-flight said so — the post-mortem rollup named the culprit
only after the budget was gone. The watchdog turns the telemetry the
engines already emit into a live signal:

  - every `interval` seconds it emits a `heartbeat` trace event carrying
    wall time, RSS, the open-span stack (outermost first) and the last
    completed BFS level — a killed run's trace ends with a beat that
    says exactly where it was;
  - when no span opens/closes and no level record lands for longer than
    `max(min_stall_s, stall_factor * median(level wall))` it emits ONE
    `stall` trace event per episode (plus a stderr line via `on_stall`),
    naming the open spans — a wedged device init or a pathological BFS
    level is reported before any deadline fires, not after.

The liveness signal is `Telemetry.progress_seq`, bumped on every span
open/close and level record, so the watchdog needs no cooperation from
the engines. Everything is best-effort: a watchdog failure must never
break a run (the tick body is exception-proofed), and the thread is a
daemon so it can never hold a process open.

Knobs (env, all optional):
  JAXMC_HEARTBEAT_EVERY  seconds between beats        (default 10)
  JAXMC_STALL_FACTOR     multiple of the median level (default 5)
  JAXMC_STALL_MIN_S      stall floor in seconds       (default 30)
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

from .telemetry import rss_bytes


def _median(xs):
    if not xs:
        return None
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _default_on_stall(msg: str) -> None:
    print(f"jaxmc: WATCHDOG: {msg}", file=sys.stderr, flush=True)


class Watchdog:
    """Heartbeat/stall monitor over one Telemetry instance.

    `start()` launches the daemon thread; `stop()` joins it. `_tick()`
    is the whole per-beat body and takes the current time explicitly, so
    tests drive it deterministically without threads or sleeps."""

    def __init__(self, tel, interval: Optional[float] = None,
                 stall_factor: Optional[float] = None,
                 min_stall_s: Optional[float] = None,
                 on_stall: Callable[[str], None] = _default_on_stall,
                 clock=time.time):
        def _env(name, default):
            try:
                return float(os.environ.get(name, ""))
            except ValueError:
                return default

        self.tel = tel
        self.interval = interval if interval is not None \
            else _env("JAXMC_HEARTBEAT_EVERY", 10.0)
        self.stall_factor = stall_factor if stall_factor is not None \
            else _env("JAXMC_STALL_FACTOR", 5.0)
        self.min_stall_s = min_stall_s if min_stall_s is not None \
            else _env("JAXMC_STALL_MIN_S", 30.0)
        self.on_stall = on_stall
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        now = clock()
        self._last_seq = -1
        self._last_change_t = now
        self._stalled = False  # one stall event per episode

    # ---- lifecycle ----
    def start(self) -> "Watchdog":
        if not getattr(self.tel, "enabled", False):
            return self  # a NullTelemetry never progresses: nothing to watch
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="jaxmc-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick(self._clock())
            except Exception:  # noqa: BLE001 — never break the run
                pass

    # ---- one beat (deterministic; tests call this directly) ----
    def stall_threshold_s(self, level_walls) -> float:
        """max(floor, factor * median level wall): early phases (device
        init, compile) have no levels yet, so the floor governs; once
        the BFS is producing level records the threshold tracks the
        model's own rhythm — a level 5x slower than the median is news
        even when it is fast in absolute terms."""
        med = _median(level_walls)
        if med is None:
            return self.min_stall_s
        return max(self.min_stall_s, self.stall_factor * med)

    def _tick(self, now: float) -> None:
        tel = self.tel
        snap = tel.watch_snapshot()
        if snap["progress_seq"] != self._last_seq:
            self._last_seq = snap["progress_seq"]
            self._last_change_t = now
            self._stalled = False
        stalled_for = now - self._last_change_t
        tel.counter("watchdog.heartbeats")
        beat = dict(
            wall_s=round(max(now - tel.t_start, 0.0), 3),
            rss_bytes=rss_bytes(),
            open_spans=snap["open_spans"],
            last_level=snap["last_level"],
            progress_seq=snap["progress_seq"],
            stalled_for_s=round(stalled_for, 3))
        prof = getattr(tel, "prof", None)
        if prof is not None:
            # ISSUE 17: device memory (the HBM model's current total)
            # rides next to RSS — a beat that shows host memory flat
            # while device buffers grew names the right suspect
            dm = prof.hbm_current_bytes()
            if dm:
                beat["device_mem_bytes"] = dm
        pe = getattr(tel, "progress_est", None)
        if pe is not None:  # ISSUE 16: the beat carries the live ETA
            ps = pe.snapshot()
            beat.update(progress_fraction=ps["fraction"],
                        progress_eta_s=ps["eta_s"],
                        progress_verdict=ps["verdict"])
        tel.event("heartbeat", **beat)
        threshold = self.stall_threshold_s(snap["level_walls"])
        if stalled_for >= threshold and not self._stalled:
            self._stalled = True
            tel.counter("watchdog.stalls")
            tel.high_water("watchdog.max_stall_s", round(stalled_for, 3))
            med = _median(snap["level_walls"])
            tel.event("stall",
                      stalled_for_s=round(stalled_for, 3),
                      threshold_s=round(threshold, 3),
                      open_spans=snap["open_spans"],
                      last_level=snap["last_level"],
                      median_level_s=None if med is None
                      else round(med, 6))
            where = " > ".join(snap["open_spans"]) or "no open span"
            lvl = snap["last_level"]
            # ISSUE 17: name the dominant profiler site, turning "no
            # progress" into "no progress, 92% in mesh.superstep"
            dom = ""
            if prof is not None:
                ds = prof.dominant_site()
                if ds is not None:
                    dom = f"; {ds[1]:.0%} in {ds[0]}"
            try:
                self.on_stall(
                    f"no span/level progress for {stalled_for:.0f}s "
                    f"(threshold {threshold:.0f}s); open: {where}; "
                    f"last completed level: "
                    f"{'none' if lvl is None else lvl}{dom}")
            except Exception:  # noqa: BLE001
                pass
        elif self._stalled:
            # episode continues: keep the high-water moving so the
            # summary records how long the worst wedge lasted
            tel.high_water("watchdog.max_stall_s", round(stalled_for, 3))
