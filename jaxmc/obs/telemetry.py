r"""Run telemetry: spans, counters, per-level BFS records (no third-party
deps).

Motivation (ISSUE 1 / BENCH_r05): the device bench blew its deadline and
degraded to the interpreter with no record of WHERE the budget went —
device init, kernel compilation, or the BFS itself. Every engine phase now
reports into one `Telemetry` object: phases as spans (wall time, nesting),
scalar counters/gauges (expansion-mode tallies, memo-cache hits,
fingerprint occupancy, device-memory high-water), and one record per BFS
level (frontier/generated/distinct). Events stream as JSONL (`--trace
FILE`) while the run is live — a killed process leaves `span_open` events
naming the phase it died in — and roll up into an end-of-run summary
(`--metrics-out FILE`, schema in obs/schema.py).

Telemetry is a PARALLEL channel: TLC-style stdout stays byte-identical.
Engines reach the active recorder through `current()` (a NullTelemetry by
default, every method a no-op), so deep code needs no constructor
plumbing; the CLI installs a real recorder with `use(...)` only when the
user asked for an artifact.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from . import context as trace_context
from .prof import Profiler  # per-dispatch attribution + HBM model
from .schema import SCHEMA  # one source of truth for the artifact schema

# every live recorder keeps the last N trace events in memory (the
# serve daemon's GET /jobs/<id>/events reads them mid-run); bounded so
# a long search cannot grow the daemon without limit
_RING_MAX = int(os.environ.get("JAXMC_TRACE_RING", "256") or "256")


def write_json_atomic(path: str, obj) -> None:
    """Dump `obj` as JSON via a sibling tmp file + os.replace, so a
    crash mid-write never leaves a truncated artifact.  Creates the
    parent directory: a bench leg must not burn minutes of measurement
    and then die because --out-dir didn't exist yet."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, indent=1, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)


def _jsonable(v):
    """Best-effort plain-JSON coercion for attribute values."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:  # numpy scalars
        return v.item()
    except Exception:  # non-scalar array, no .item(): never break a run
        return str(v)


class _SpanHandle:
    """Context manager for one phase span. Re-entrant use is not needed:
    each `span()` call makes a fresh handle."""

    __slots__ = ("tel", "name", "attrs", "t0", "_done")

    def __init__(self, tel: "Telemetry", name: str, attrs: Dict[str, Any]):
        self.tel = tel
        self.name = name
        self.attrs = attrs
        self.t0 = None
        self._done = False

    def __enter__(self):
        self.t0 = self.tel._clock()
        self.tel._span_open(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.done(error=exc_type.__name__ if exc_type else None)
        return False

    def done(self, error: Optional[str] = None):
        if self._done:
            return
        self._done = True
        self.tel._span_close(self, error)


class NullTelemetry:
    """The default recorder: every method a no-op, so instrumented hot
    paths cost one attribute lookup and a truth test when telemetry is
    off."""

    enabled = False
    progress_seq = 0  # never advances: a watchdog on a null recorder
    # would see an eternal stall, so Watchdog refuses to start on one
    progress_est = None  # a ProgressEstimator when one is attached
    # (obs/progress.py); engines read it via getattr, so the null
    # recorder's class attribute keeps the hot path allocation-free
    prof = None  # a Profiler on live recorders (obs/prof.py); the
    # class-level None keeps prof.wrap's per-dispatch check to one
    # getattr + a None test when telemetry is off

    def recent_events(self) -> List[Dict[str, Any]]:
        return []

    def span(self, name: str, **attrs):
        return _NULL_SPAN

    def counter(self, name: str, inc: int = 1) -> None:
        pass

    def gauge(self, name: str, value) -> None:
        pass

    def high_water(self, name: str, value) -> None:
        pass

    def level(self, index: int, **fields) -> None:
        pass

    def reset_levels(self, reason: str = "") -> None:
        pass

    def event(self, name: str, **fields) -> None:
        pass

    def log_line(self, msg: str) -> None:
        pass

    def set_meta(self, **fields) -> None:
        pass

    def close(self) -> None:
        pass


class _NullSpan:
    __slots__ = ()

    @property
    def attrs(self):
        # a fresh throwaway dict per access: callers may annotate
        # (`span.attrs["outcome"] = ...`) without caring whether
        # telemetry is live
        return {}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def done(self, error=None):
        pass


_NULL_SPAN = _NullSpan()


class Telemetry(NullTelemetry):
    """A run recorder. Thread-safe: bench workers and engine threads may
    report into one instance (spans nest per-thread via a thread-local
    stack; counters/levels share one lock)."""

    enabled = True

    def __init__(self, trace_path: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None,
                 clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._local = threading.local()
        self.t_start = clock()
        # bumped on every span open/close and level record — the
        # watchdog's liveness signal: a run whose progress_seq stops
        # moving is wedged inside whatever span is still open
        self.progress_seq = 0
        self.meta: Dict[str, Any] = dict(meta or {})
        # phases aggregate spans by name, in first-start order
        self._phases: Dict[str, Dict[str, Any]] = {}
        self._open_spans: List[_SpanHandle] = []
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, Any] = {}
        self.levels: List[Dict[str, Any]] = []
        self.progress_est = None  # attached by obs.progress when the
        # model binds and analyze offers a state-space estimate
        # always-on cheap profiler (dispatch counts + recompiles only);
        # the CLI flips mode to wall/xla under --profile
        self.prof = Profiler()
        self._ring: collections.deque = collections.deque(maxlen=_RING_MAX)
        # the trace context is derived once per process; every event
        # this recorder emits is stamped with its trace_id so fleet
        # artifacts merge into one causally-ordered timeline
        self.ctx = trace_context.get()
        self._trace_fh = None
        if trace_path:
            self._trace_fh = open(trace_path, "w", encoding="utf-8")
        # the per-file meta header (ISSUE 16): pid/argv/env fingerprint
        # plus a monotonic-clock anchor, so `obs timeline` can place
        # this file's process in the trace tree and skew-align its
        # wall-clock timestamps against the other processes'
        self._emit({"ev": "proc_meta", "t": self.t_start,
                    "mono": time.monotonic(), "pid": os.getpid(),
                    "argv": list(sys.argv), "psid": self.ctx.span_id,
                    "parent_span": self.ctx.parent_span_id,
                    "env": environment_meta()})
        self._emit({"ev": "run_start", "t": self.t_start,
                    "meta": _jsonable(self.meta)})

    # ---- trace stream ----
    def _emit(self, obj: Dict[str, Any]) -> None:
        obj.setdefault("tid", self.ctx.trace_id)
        with self._lock:
            # the in-memory ring is fed even with no trace file: the
            # serve daemon reads it live for /jobs/<id>/events
            self._ring.append(obj)
            fh = self._trace_fh
            if fh is None:
                return
            try:
                fh.write(json.dumps(obj) + "\n")
                fh.flush()
            except ValueError:  # closed file: late event after close()
                pass

    def recent_events(self) -> List[Dict[str, Any]]:
        """A snapshot of the last ~_RING_MAX trace events (newest last).
        Short critical section only — safe to call from a scrape thread
        while engine threads emit."""
        with self._lock:
            return list(self._ring)

    # ---- spans ----
    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def span(self, name: str, **attrs):
        return _SpanHandle(self, name, {k: _jsonable(v)
                                        for k, v in attrs.items()})

    def _span_open(self, h: _SpanHandle) -> None:
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(h.name)
        with self._lock:
            self.progress_seq += 1
            self._open_spans.append(h)
            ph = self._phases.setdefault(
                h.name, {"name": h.name, "wall_s": 0.0, "count": 0,
                         "open": 0})
            ph["open"] += 1
        self._emit({"ev": "span_open", "name": h.name, "t": h.t0,
                    "parent": parent, "attrs": h.attrs})

    def _span_close(self, h: _SpanHandle, error: Optional[str]) -> None:
        t1 = self._clock()
        stack = self._stack()
        if stack and stack[-1] == h.name:
            stack.pop()
        with self._lock:
            self.progress_seq += 1
            if h in self._open_spans:
                self._open_spans.remove(h)
            ph = self._phases[h.name]
            ph["wall_s"] += t1 - h.t0
            ph["count"] += 1
            ph["open"] -= 1
        ev = {"ev": "span", "name": h.name, "t0": h.t0,
              "wall_s": round(t1 - h.t0, 6), "attrs": h.attrs}
        if error:
            ev["error"] = error
        self._emit(ev)

    # ---- scalars ----
    def counter(self, name: str, inc: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self.gauges[name] = _jsonable(value)

    def high_water(self, name: str, value) -> None:
        if value is None:
            return
        value = _jsonable(value)
        with self._lock:
            old = self.gauges.get(name)
            if old is None or value > old:
                self.gauges[name] = value

    # ---- per-level BFS records ----
    def level(self, index: int, **fields) -> None:
        rec = {"level": int(index)}
        rec.update({k: _jsonable(v) for k, v in fields.items()})
        with self._lock:
            self.progress_seq += 1
            self.levels.append(rec)
        self._emit(dict(rec, ev="level", t=self._clock()))
        pe = self.progress_est
        if pe is not None:  # feed the ETA estimator per level, so the
            # `search.progress_est` gauge moves with the frontier even
            # between --progress-every lines
            if rec.get("distinct") is not None:
                fr = pe.observe(distinct=rec["distinct"])
            elif rec.get("new") is not None:
                fr = pe.observe(new=rec["new"])
            else:
                fr = None
            if fr is not None:
                self.gauge("search.progress_est", fr)

    def reset_levels(self, reason: str = "") -> None:
        """A search RESTART (hybrid demotion, adaptive relayout) replays
        from level 0: drop the stale records so the summary's level list
        describes the search that produced the final counts. The trace
        stream keeps everything, separated by this restart event."""
        with self._lock:
            n = len(self.levels)
            self.levels = []
        self.counter("search.restarts")
        self._emit({"ev": "search_restart", "t": self._clock(),
                    "reason": reason, "levels_dropped": n})

    # ---- free-form events / log mirror ----
    def event(self, name: str, **fields) -> None:
        self._emit(dict({k: _jsonable(v) for k, v in fields.items()},
                        ev=name, t=self._clock()))

    def log_line(self, msg: str) -> None:
        self._emit({"ev": "log", "t": self._clock(), "msg": msg})

    def set_meta(self, **fields) -> None:
        with self._lock:
            self.meta.update({k: _jsonable(v) for k, v in fields.items()})

    def watch_snapshot(self) -> Dict[str, Any]:
        """One consistent liveness snapshot for the watchdog: the
        progress sequence number, the open-span names (outermost first),
        the last completed BFS level, and the per-level wall times (for
        the stall threshold's median)."""
        with self._lock:
            last = self.levels[-1] if self.levels else None
            return {
                "progress_seq": self.progress_seq,
                "open_spans": [h.name for h in self._open_spans],
                "last_level": None if last is None else last.get("level"),
                "level_walls": [r["wall_s"] for r in self.levels
                                if isinstance(r.get("wall_s"),
                                              (int, float))],
            }

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Consistent copies of the scalar surfaces for a live scrape
        (the serve daemon's /metrics) — short critical section, never
        blocks the emitting threads for long."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "levels": list(self.levels)}

    # ---- rollup ----
    def phase_list(self) -> List[Dict[str, Any]]:
        """Phases in first-start order; spans still open contribute their
        elapsed-so-far with open=True (the deadline-blowout forensics:
        a partial span names its culprit)."""
        now = self._clock()
        with self._lock:
            out = []
            open_extra: Dict[str, float] = {}
            for h in self._open_spans:
                open_extra[h.name] = open_extra.get(h.name, 0.0) \
                    + (now - h.t0)
            for ph in self._phases.values():
                d = {"name": ph["name"],
                     "wall_s": round(ph["wall_s"]
                                     + open_extra.get(ph["name"], 0.0), 6),
                     "count": ph["count"] + ph["open"]}
                if ph["open"]:
                    d["open"] = True
                out.append(d)
            return out

    def summary(self, result: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            levels = list(self.levels)
            meta = dict(self.meta)
        out = {
            "schema": SCHEMA,
            "started_at": self.t_start,
            "wall_s": round(self._clock() - self.t_start, 6),
            "phases": self.phase_list(),
            "counters": counters,
            "gauges": gauges,
            "levels": levels,
        }
        out.update(meta)
        prof = self.prof
        if prof is not None:
            pb = prof.snapshot()
            if pb is not None:
                out["prof"] = pb  # additive /4 block (obs/schema.py)
        if result is not None:
            out["result"] = _jsonable(result)
        return out

    def write_metrics(self, path: str,
                      result: Optional[Dict[str, Any]] = None) -> None:
        s = self.summary(result)
        write_json_atomic(path, s)
        # every artifact-writing run is a trajectory point: record it in
        # the persistent ledger (no-op when JAXMC_LEDGER=off, never
        # raises — the ledger must not break a run)
        try:
            from .ledger import append_summary
            append_summary(s, source=path)
        except Exception:  # noqa: BLE001
            pass

    def close(self) -> None:
        self._emit({"ev": "run_end", "t": self._clock()})
        fh = self._trace_fh
        self._trace_fh = None
        if fh is not None:
            fh.close()


# ---- the process-wide current recorder ----

_CURRENT: NullTelemetry = NullTelemetry()

# per-thread override (ISSUE 7): the serve daemon runs several check
# jobs concurrently in worker threads, each with its OWN recorder —
# a single process-global slot would interleave their spans/levels.
# current() consults the thread-local first, so engine code needs no
# plumbing changes; the main-thread CLI keeps using the global `use`.
_TLS = threading.local()


def current() -> NullTelemetry:
    """The active recorder: this thread's `use_local` override if one is
    installed, else the process-wide one (a shared no-op unless the
    CLI/bench installed a real recorder)."""
    tel = getattr(_TLS, "tel", None)
    return tel if tel is not None else _CURRENT


class use:
    """Install `tel` as the process-wide recorder for a with-block."""

    def __init__(self, tel: NullTelemetry):
        self.tel = tel
        self._prev = None

    def __enter__(self):
        global _CURRENT
        self._prev = _CURRENT
        _CURRENT = self.tel
        return self.tel

    def __exit__(self, *a):
        global _CURRENT
        _CURRENT = self._prev
        return False


class use_local:
    """Install `tel` as THIS THREAD's recorder for a with-block (wins
    over the process-wide one inside the block).  The serve daemon's
    per-job telemetry channel: each worker thread records its job's
    spans/levels/counters into a private recorder while the daemon's
    fleet recorder keeps the global view."""

    def __init__(self, tel: NullTelemetry):
        self.tel = tel
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "tel", None)
        _TLS.tel = self.tel
        return self.tel

    def __exit__(self, *a):
        _TLS.tel = self._prev
        return False


class Logger:
    """The ONE engine log sink: prints the TLC-style line (unless quiet)
    and mirrors it into the telemetry trace. Replaces the ad-hoc
    `(lambda s: None) if quiet else print` plumbing in cli.py — every
    engine's `log:` callback funnels through here so stdout and the
    trace always carry the same strings."""

    __slots__ = ("tel", "quiet", "sink")

    def __init__(self, tel: Optional[NullTelemetry] = None,
                 quiet: bool = False, sink=print):
        self.tel = tel
        self.quiet = quiet
        self.sink = sink

    def __call__(self, msg: str) -> None:
        if not self.quiet:
            self.sink(msg)
        tel = self.tel if self.tel is not None else current()
        tel.log_line(msg)


def prom_name(name: str) -> str:
    """Map an internal dotted metric name onto the Prometheus exposition
    grammar (documented in obs/schema.py): `jaxmc_` prefix, every char
    outside [a-zA-Z0-9_] replaced by `_`.  `serve.warm_hits` ->
    `jaxmc_serve_warm_hits`."""
    return "jaxmc_" + "".join(
        c if (c.isascii() and (c.isalnum() or c == "_")) else "_"
        for c in name)


def rss_bytes() -> Optional[int]:
    """This process's resident set size, or None when the platform has
    no cheap way to ask. /proc is the normal path (linux containers);
    the getrusage fallback reports the PEAK rss, which is still the
    useful number for a watchdog heartbeat."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss_kb) * 1024
    except Exception:  # noqa: BLE001 — diagnostics must not mask
        return None


def environment_meta() -> Dict[str, Any]:
    """The environment fingerprint recorded in the metrics `meta` block
    (and the bench JSON line) so `python -m jaxmc.obs diff` can
    attribute a regression to an environment change instead of a code
    change. Deliberately does NOT import jax: an interp run must not pay
    (or hang on) device-plugin init for telemetry's sake — platform and
    device count appear only when the caller already initialized jax."""
    out: Dict[str, Any] = {"python": sys.version.split()[0],
                           "jax_version": None, "platform": None,
                           "device_count": None}
    jax = sys.modules.get("jax")
    if jax is not None:
        out["jax_version"] = getattr(jax, "__version__", None)
        try:
            devs = jax.devices()
            out["platform"] = devs[0].platform
            out["device_count"] = len(devs)
        except Exception:  # noqa: BLE001 — backend init may be broken
            pass
    else:
        try:  # metadata read only — no import, no device init
            from importlib.metadata import version
            out["jax_version"] = version("jax")
        except Exception:  # noqa: BLE001
            pass
    return out


def device_mem_high_water() -> Optional[int]:
    """Sum of per-device peak allocation bytes, when the jax backend
    exposes memory_stats (TPU/GPU; CPU usually returns None). Never
    raises — telemetry must not break a run."""
    try:
        import jax
        total = 0
        seen = False
        for d in jax.devices():
            ms = getattr(d, "memory_stats", None)
            st = ms() if callable(ms) else None
            if not st:
                continue
            peak = st.get("peak_bytes_in_use", st.get("bytes_in_use"))
            if peak is not None:
                total += int(peak)
                seen = True
        return total if seen else None
    except Exception:  # noqa: BLE001 — diagnostics must not mask
        return None
