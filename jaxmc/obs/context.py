r"""Dapper-style trace context, carried across every process boundary.

One CHECK — wherever it fans out — shares one ``trace_id``.  Each
process gets its own process-span id, parented on the span of whoever
spawned it, so artifacts from a whole fleet (serve daemon ->
device-owner -> job sessions, bench parent -> children, fork-pool
workers, oracle / cache-guard probes) can be merged back into a single
causally-ordered timeline (``python -m jaxmc.obs timeline``).

The wire format is deliberately tiny — one env var:

    JAXMC_TRACE_CTX = "<trace_id>:<parent_span_id>"

Both ids are 16 lowercase hex chars.  A process that finds the var in
its environment INHERITS the trace; one that does not MINTS a fresh
trace_id and becomes a root.  ``fork`` children (the parallel engine's
worker pool) inherit the parent's in-memory context; the pid check in
``get()`` re-derives their own process span lazily, parented on the
forking process — no env round-trip needed, and a respawned worker
keeps the original trace_id by construction (the chaos suite pins
this).

Everything here is stdlib-only and import-light: obs must stay safe to
import before jax and inside every subprocess.
"""

from __future__ import annotations

import contextlib
import os
import threading
import uuid
from typing import Dict, Optional

ENV_VAR = "JAXMC_TRACE_CTX"

_lock = threading.Lock()
_ctx: Optional["TraceContext"] = None


def new_span_id() -> str:
    """A fresh 16-hex span/trace id (uuid4-derived, no coordination)."""
    return uuid.uuid4().hex[:16]


class TraceContext:
    """This process's position in the trace tree."""

    __slots__ = ("trace_id", "parent_span_id", "span_id", "pid")

    def __init__(self, trace_id: str, parent_span_id: Optional[str],
                 span_id: str, pid: int):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.span_id = span_id
        self.pid = pid

    def header(self) -> str:
        """The env-var value a CHILD of this process should inherit."""
        return f"{self.trace_id}:{self.span_id}"

    def lineage(self) -> Dict[str, Optional[str]]:
        """The ids worth carrying in an IPC message (fork-pool worker
        start/done/fail frames): enough for the receiver to emit a
        trace event that places this process in the tree."""
        return {"tid": self.trace_id, "span": self.span_id,
                "parent": self.parent_span_id}


def _parse_header(raw: str) -> Optional[tuple]:
    parts = raw.strip().split(":")
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]


def _derive(parent: Optional["TraceContext"]) -> "TraceContext":
    """Build this process's context: from a forked parent's in-memory
    context when given, else from the env header, else a fresh root."""
    if parent is not None:
        return TraceContext(parent.trace_id, parent.span_id,
                            new_span_id(), os.getpid())
    hdr = _parse_header(os.environ.get(ENV_VAR, "") or "")
    if hdr is not None:
        return TraceContext(hdr[0], hdr[1], new_span_id(), os.getpid())
    return TraceContext(new_span_id(), None, new_span_id(), os.getpid())


def get() -> TraceContext:
    """The current process's trace context (lazily derived; fork-safe:
    a context cached by a parent is re-derived in the child, keeping
    the trace_id and parenting the child span on the parent's)."""
    global _ctx
    with _lock:
        if _ctx is None:
            _ctx = _derive(None)
        elif _ctx.pid != os.getpid():  # we are a fork child
            _ctx = _derive(_ctx)
        return _ctx


def reset() -> None:
    """Drop the cached context (tests)."""
    global _ctx
    with _lock:
        _ctx = None


def child_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of `env` (default: os.environ) with the trace header a
    spawned child should inherit.  Use on every subprocess env dict."""
    out = dict(os.environ if env is None else env)
    out[ENV_VAR] = get().header()
    return out


@contextlib.contextmanager
def exported():
    """Temporarily export the child header into os.environ — for spawn
    APIs that snapshot the parent environment and take no env argument
    (multiprocessing's spawn context, the device owner)."""
    prev = os.environ.get(ENV_VAR)
    os.environ[ENV_VAR] = get().header()
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = prev
