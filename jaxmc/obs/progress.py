r"""Search-progress / ETA estimation from the analyze-layer prediction.

``analyze.state_space_estimate`` (ISSUE 15) proves an upper bound on
the reachable distinct-state count for many specs.  Against that bound
and the observed frontier-growth curve this module derives, live:

  fraction   distinct / estimate, clamped to [0, 1]
  eta_s      remaining / recent discovery rate (a trailing window over
             the last observations, so it tracks the curve's knee
             instead of averaging the whole run)
  verdict    "est" while the bound holds; "unbounded" when no estimate
             exists OR the search has already exceeded it (the bound
             was an upper bound on the wrong model of the search — be
             honest rather than show >100%)

The estimator is attached to a live Telemetry as ``tel.progress_est``
(``attach_estimator``, called from CheckSession.parse once the model
is bound).  Consumers:

  - engine progress lines append ``eta_suffix(distinct)`` — empty
    string when no estimator is attached, so default (NullTelemetry)
    runs keep byte-identical stdout;
  - the watchdog stamps snapshot fields into heartbeats;
  - the serve daemon's /status and /metrics surface the
    ``search.progress_est`` gauge the estimator maintains.

Thread-safe: observations arrive from engine threads, snapshots from
the watchdog and the daemon's HTTP threads.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional

#: sliding window of (t, distinct) samples the rate is fitted over
_WINDOW = 32


class ProgressEstimator:
    def __init__(self, estimate: Optional[int],
                 clock=time.time):
        try:
            self.estimate = int(estimate) if estimate is not None else None
        except (TypeError, ValueError):
            self.estimate = None
        self.clock = clock
        self._lock = threading.Lock()
        self._samples = collections.deque(maxlen=_WINDOW)
        self._distinct = 0

    # ---- feeding ------------------------------------------------------
    def observe(self, distinct: Optional[int] = None,
                new: Optional[int] = None) -> Optional[float]:
        """Record a progress observation (cumulative `distinct` wins;
        `new` increments when that's all the caller has).  Returns the
        current fraction-explored, or None when unbounded."""
        with self._lock:
            if distinct is not None:
                try:
                    self._distinct = max(self._distinct, int(distinct))
                except (TypeError, ValueError):
                    pass
            elif new is not None:
                self._distinct += int(new)
            self._samples.append((self.clock(), self._distinct))
            return self._fraction_locked()

    # ---- deriving -----------------------------------------------------
    def _fraction_locked(self) -> Optional[float]:
        if self.estimate is None or self.estimate <= 0 \
                or self._distinct > self.estimate:
            return None
        return min(1.0, self._distinct / self.estimate)

    def _rate_locked(self) -> Optional[float]:
        if len(self._samples) < 2:
            return None
        t0, n0 = self._samples[0]
        t1, n1 = self._samples[-1]
        if t1 <= t0 or n1 <= n0:
            return None
        return (n1 - n0) / (t1 - t0)

    def snapshot(self) -> Dict[str, Any]:
        """The fields stamped into heartbeats / /status / timeline."""
        with self._lock:
            fr = self._fraction_locked()
            rate = self._rate_locked()
            eta = None
            if fr is not None and rate is not None and rate > 0:
                eta = max(0.0, (self.estimate - self._distinct) / rate)
            return {
                "estimate": self.estimate,
                "distinct": self._distinct,
                "fraction": round(fr, 6) if fr is not None else None,
                "rate_states_s": round(rate, 3) if rate else None,
                "eta_s": round(eta, 3) if eta is not None else None,
                "verdict": "est" if fr is not None else "unbounded",
            }

    def suffix(self) -> str:
        """Human tail for a Progress(...) line, e.g.
        " (~41% of est. 20001 states, ETA 12s)"."""
        s = self.snapshot()
        if s["verdict"] == "unbounded":
            return " (est. unbounded)"
        pct = 100.0 * s["fraction"]
        tail = f" (~{pct:.0f}% of est. {s['estimate']} states"
        if s["eta_s"] is not None:
            tail += f", ETA {_fmt_s(s['eta_s'])}"
        return tail + ")"


def _fmt_s(sec: float) -> str:
    if sec >= 3600:
        return f"{sec / 3600:.1f}h"
    if sec >= 60:
        return f"{sec / 60:.1f}m"
    return f"{sec:.0f}s"


def attach_estimator(tel, model) -> Optional[ProgressEstimator]:
    """Attach a ProgressEstimator for `model` to `tel` (no-op on
    disabled telemetry).  The analyze fixpoint must never break a
    check, so every failure degrades to an unbounded estimator."""
    if not getattr(tel, "enabled", False):
        return None
    est = None
    try:
        from ..analyze.bounds import state_space_estimate
        est = state_space_estimate(model)
    except Exception:  # noqa: BLE001 — estimation is best-effort
        est = None
    pe = ProgressEstimator(est)
    tel.progress_est = pe
    if est is not None:
        tel.event("progress_estimate", estimate=int(est))
    return pe


def eta_suffix(distinct: Optional[int] = None, tel=None) -> str:
    """The progress-line tail for the current telemetry's estimator —
    "" when none is attached (default runs keep their exact output).
    Feeds the observation in and refreshes the `search.progress_est`
    gauge as a side effect, so the first progress line (emitted before
    level 1 completes) already carries an estimate."""
    if tel is None:
        from .telemetry import current
        tel = current()
    pe = getattr(tel, "progress_est", None)
    if pe is None:
        return ""
    fr = pe.observe(distinct=distinct) if distinct is not None \
        else pe.snapshot().get("fraction")
    if fr is not None:
        tel.gauge("search.progress_est", fr)
    return pe.suffix()
