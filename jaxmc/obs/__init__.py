r"""jaxmc.obs — run telemetry (phase spans, counters, per-level BFS
metrics) with JSONL trace streaming and a JSON summary artifact.

    from jaxmc import obs

    tel = obs.Telemetry(trace_path="run.jsonl", meta={"backend": "jax"})
    with obs.use(tel):                       # engines see it via current()
        with tel.span("load"):
            ...
    tel.write_metrics("m.json", result={...})

Engines report through `obs.current()` — a no-op NullTelemetry unless a
real recorder is installed — so instrumentation costs nothing when no
artifact was requested. See obs/telemetry.py for the model and
obs/schema.py for the artifact schema.
"""

from .telemetry import (Logger, NullTelemetry, Telemetry, current,
                        device_mem_high_water, use, write_json_atomic)
from .schema import (CHECK_KEYS, REQUIRED_KEYS, RESULT_KEYS, SCHEMA,
                     validate_summary)

__all__ = ["Logger", "NullTelemetry", "Telemetry", "current",
           "device_mem_high_water", "use", "write_json_atomic", "SCHEMA",
           "REQUIRED_KEYS", "CHECK_KEYS", "RESULT_KEYS",
           "validate_summary"]
