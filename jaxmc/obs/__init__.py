r"""jaxmc.obs — run telemetry (phase spans, counters, per-level BFS
metrics) with JSONL trace streaming, a JSON summary artifact, a
watchdog heartbeat/stall monitor, distributed trace context, a
search-progress/ETA estimator, and a cross-run report CLI.

    from jaxmc import obs

    tel = obs.Telemetry(trace_path="run.jsonl", meta={"backend": "jax"})
    wd = obs.Watchdog(tel).start()           # heartbeat + stall events
    with obs.use(tel):                       # engines see it via current()
        with tel.span("load"):
            ...
    wd.stop()
    tel.write_metrics("m.json", result={...})

Engines report through `obs.current()` — a no-op NullTelemetry unless a
real recorder is installed — so instrumentation costs nothing when no
artifact was requested. See obs/telemetry.py for the model,
obs/schema.py for the artifact schema (jaxmc.metrics/4),
obs/context.py for the JAXMC_TRACE_CTX propagation contract,
obs/progress.py for the ETA estimator, obs/watchdog.py for live stall
diagnosis, obs/prof.py for the per-dispatch device profiler + HBM
model, obs/ledger.py for the persistent run ledger, and obs/report.py
for `python -m jaxmc.obs report|diff|timeline|top|history` over
artifacts.
"""

from . import context
from .telemetry import (Logger, NullTelemetry, Telemetry, current,
                        device_mem_high_water, environment_meta,
                        prom_name, rss_bytes, use, use_local,
                        write_json_atomic)
from .context import TraceContext, child_env
from .ledger import append_summary, ledger_path
from .prof import Profiler, note_buffer, prof_attribution, prof_wrap
from .progress import ProgressEstimator, attach_estimator, eta_suffix
from .schema import (CHECK_KEYS, HEARTBEAT_KEYS, REQUIRED_KEYS,
                     RESULT_KEYS, SCHEMA, SCHEMAS, STALL_KEYS,
                     validate_summary, validate_trace_event)
from .watchdog import Watchdog

__all__ = ["Logger", "NullTelemetry", "Profiler", "Telemetry",
           "Watchdog", "TraceContext", "ProgressEstimator",
           "append_summary", "attach_estimator", "child_env", "context",
           "current", "device_mem_high_water", "environment_meta",
           "eta_suffix", "ledger_path", "note_buffer",
           "prof_attribution", "prof_wrap", "prom_name", "rss_bytes",
           "use", "use_local", "write_json_atomic", "SCHEMA", "SCHEMAS",
           "REQUIRED_KEYS", "CHECK_KEYS", "RESULT_KEYS",
           "HEARTBEAT_KEYS", "STALL_KEYS", "validate_summary",
           "validate_trace_event"]
