r"""jaxmc.obs — run telemetry (phase spans, counters, per-level BFS
metrics) with JSONL trace streaming, a JSON summary artifact, a
watchdog heartbeat/stall monitor, and a cross-run report CLI.

    from jaxmc import obs

    tel = obs.Telemetry(trace_path="run.jsonl", meta={"backend": "jax"})
    wd = obs.Watchdog(tel).start()           # heartbeat + stall events
    with obs.use(tel):                       # engines see it via current()
        with tel.span("load"):
            ...
    wd.stop()
    tel.write_metrics("m.json", result={...})

Engines report through `obs.current()` — a no-op NullTelemetry unless a
real recorder is installed — so instrumentation costs nothing when no
artifact was requested. See obs/telemetry.py for the model,
obs/schema.py for the artifact schema (jaxmc.metrics/2),
obs/watchdog.py for live stall diagnosis, and obs/report.py for
`python -m jaxmc.obs report|diff` over artifacts.
"""

from .telemetry import (Logger, NullTelemetry, Telemetry, current,
                        device_mem_high_water, environment_meta,
                        rss_bytes, use, use_local, write_json_atomic)
from .schema import (CHECK_KEYS, HEARTBEAT_KEYS, REQUIRED_KEYS,
                     RESULT_KEYS, SCHEMA, SCHEMAS, STALL_KEYS,
                     validate_summary, validate_trace_event)
from .watchdog import Watchdog

__all__ = ["Logger", "NullTelemetry", "Telemetry", "Watchdog", "current",
           "device_mem_high_water", "environment_meta", "rss_bytes",
           "use", "use_local", "write_json_atomic", "SCHEMA", "SCHEMAS",
           "REQUIRED_KEYS", "CHECK_KEYS", "RESULT_KEYS",
           "HEARTBEAT_KEYS", "STALL_KEYS", "validate_summary",
           "validate_trace_event"]
