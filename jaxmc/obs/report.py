r"""Cross-run metrics reporting: `python -m jaxmc.obs
{report,diff,timeline,top,history}`.

PR 1 made one run legible (`--metrics-out` / `--trace`); this closes the
loop ACROSS runs. Two subcommands, both pure stdlib (no jax import — the
entrypoint must work in an interp-only environment and is smoke-tested
against import rot):

  report FILE            render one artifact as a human phase/level
                         breakdown (phases table, level rollup,
                         throughput, compile/watchdog highlights)
  diff FILE FILE [...]   ingest 2+ artifacts — `--metrics-out` JSONs
                         and/or the BENCH_r*.json family — and emit a
                         trajectory table with regression flags:
                         states/sec drops, phase wall blowups, backend
                         demotions (tpu -> cpu -> interp). With
                         --fail-on-regress the exit status is 1 when
                         any flag fired, so the bench driver can gate.
  timeline FILE [...]    merge multi-process trace JSONLs (daemon +
                         device owner + per-job recorders) into one
                         causally-ordered per-process-lane view;
                         orphan spans and silent gaps are flagged and
                         counted on a machine-parseable summary line
                         (obs/timeline.py; --fail-on-orphans gates).
  top FILE               per-dispatch-site device profile of one
                         --profile artifact: wall, share of the
                         search wall, dispatches, bytes, recompiles,
                         plus the HBM buffer model (obs/prof.py).
  history [...]          per-rung states/sec trajectory across ALL
                         ledger-recorded runs, latest-vs-best-of-
                         window regression flags with env attribution
                         (obs/ledger.py; --fail-on-regress gates,
                         --import backfills committed artifacts).

Both input shapes normalize into one record (`load_record`):
  - a metrics artifact (schema jaxmc.metrics/1 or /2, obs/schema.py);
  - a bench rollup {n, cmd, rc, tail, parsed:{metric, value, ...}} or a
    bare bench line {metric, value, unit, vs_baseline, orchestration?}
    as printed by bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

# platform rank for demotion flags: higher is better; a later run with a
# lower rank means the bench/check fell off its accelerator
_RANK = {"interp": 0, "cpu": 1, "gpu": 2, "tpu": 3}


def _fmt_s(x) -> str:
    if x is None:
        return "-"
    if x >= 100:
        return f"{x:.0f}s"
    return f"{x:.2f}s"


def _fmt_rate(x) -> str:
    return "-" if x is None else f"{x:,.1f}"


def _pct(new, old) -> Optional[float]:
    if new is None or old is None or old == 0:
        return None
    return (new - old) / old * 100.0


# --------------------------------------------------------------- loading

def load_record(path: str) -> Dict[str, Any]:
    """Normalize one artifact file into the common record the table and
    the regression rules consume. Raises ValueError on unrecognized
    shapes (naming the path)."""
    with open(path, encoding="utf-8") as fh:
        obj = json.load(fh)
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: not a JSON object")
    label = os.path.basename(path)
    for ext in (".json", ".jsonl"):
        if label.endswith(ext):
            label = label[:-len(ext)]
    if str(obj.get("schema", "")).startswith("jaxmc.multichip/"):
        return _from_multichip(obj, path, label)
    if "schema" in obj and "phases" in obj:
        return _from_metrics(obj, path, label)
    if "parsed" in obj and isinstance(obj["parsed"], dict):
        rec = _from_bench(obj["parsed"], path, label)
        if obj.get("n") is not None:
            rec["label"] = f"r{int(obj['n']):02d}"
        return rec
    if "metric" in obj and "value" in obj:
        return _from_bench(obj, path, label)
    raise ValueError(
        f"{path}: neither a jaxmc.metrics artifact nor a bench JSON "
        f"(keys: {sorted(obj)[:8]})")


def _from_metrics(s: Dict[str, Any], path: str, label: str
                  ) -> Dict[str, Any]:
    res = s.get("result") or {}
    wall = res.get("wall_s") or s.get("wall_s")
    gen = res.get("generated")
    rate = (gen / wall) if gen and wall else None
    env = s.get("env") or {}
    platform = env.get("platform") or s.get("gauges", {}).get(
        "device.platform")
    backend = s.get("backend")
    if backend == "interp" or (backend is None and platform is None):
        plat_key = "interp"
    else:
        plat_key = platform or "cpu"
    return {
        "path": path, "label": label, "kind": "metrics",
        "states_per_sec": rate,
        "backend": backend or "?",
        "platform": plat_key,
        "rank": _RANK.get(plat_key, 1),
        # a terminal device failure that completed on the CPU fallback
        # (session.demote_to_cpu); find_regressions flags its appearance
        "demoted": s.get("gauges", {}).get("device.demoted"),
        # a disk-tier write failure that degraded the seen-set
        # hierarchy to host-tier-only (ISSUE 12): counts stayed exact,
        # but the out-of-core ceiling shrank — flagged like a demotion
        "io_degraded": s.get("gauges", {}).get("tier.io_degraded"),
        # fleet-serving reliability signals (ISSUE 19): rejections or
        # spool write degradation appearing where a previous run had
        # none is a serving regression even when every accepted job
        # still completed — flagged like the tier degradation above
        "admission_rejected": s.get("counters", {}).get(
            "serve.admission_rejected"),
        "spool_degraded": s.get("counters", {}).get(
            "serve.spool_degraded"),
        "mode": s.get("gauges", {}).get("expand.mode"),
        "wall_s": s.get("wall_s"),
        "phases": {p["name"]: p["wall_s"] for p in s.get("phases", [])},
        "env": env,
        "result": res,
        "summary": s,
    }


def _from_multichip(s: Dict[str, Any], path: str, label: str
                    ) -> Dict[str, Any]:
    """A MULTICHIP_r*.json scaling artifact (jaxmc.multichip/1,
    jaxmc/meshbench.py): one record whose `curve` maps each
    (rung, devices) point to its per-chip rate, so `obs diff` can gate
    r07-vs-r06 states/sec/chip per rung (ISSUE 10 CI satellite)."""
    curve: Dict[str, Dict[str, Any]] = {}
    for rung in s.get("rungs", []):
        for pt in rung.get("curve", []) or []:
            if "error" in pt:
                continue
            curve[f"{rung['rung']}@D{pt['devices']}"] = pt
    return {
        "path": path, "label": label, "kind": "multichip",
        "states_per_sec": None,
        "backend": "mesh", "platform": s.get("platform", "cpu"),
        "rank": _RANK.get(s.get("platform", "cpu"), 1),
        "mode": s.get("mode"), "wall_s": None,
        "phases": {}, "env": s.get("env") or {},
        "result": {"ok": s.get("ok")},
        "curve": curve, "summary": s,
    }


def _from_bench(b: Dict[str, Any], path: str, label: str
                ) -> Dict[str, Any]:
    metric = str(b.get("metric") or "")
    if "EXACT PYTHON INTERPRETER" in metric:
        plat_key = "interp"
    else:
        m = re.search(r"platform=(\w+)", metric)
        plat_key = m.group(1) if m else "interp"
    phases: Dict[str, float] = {}
    for src in (b.get("phases"),
                (b.get("orchestration") or {}).get("phases")):
        for p in src or []:
            phases[p["name"]] = phases.get(p["name"], 0.0) + p["wall_s"]
    orch = b.get("orchestration") or {}
    return {
        "path": path, "label": label, "kind": "bench",
        "states_per_sec": b.get("value"),
        "backend": "bench",
        "platform": plat_key,
        "rank": _RANK.get(plat_key, 1),
        "mode": None,
        "wall_s": orch.get("spent_s"),
        "phases": phases,
        "env": b.get("env") or {},
        "result": {"vs_baseline": b.get("vs_baseline"),
                   "vs_tlc_estimate": b.get("vs_tlc_estimate")},
        "metric": metric,
    }


# ---------------------------------------------------------------- report

def _phase_table(phases: List[Dict[str, Any]], out) -> int:
    """Render a summary's phase list; returns the number of rows."""
    if not phases:
        print("  (no phases recorded)", file=out)
        return 0
    w = max(len(p["name"]) for p in phases)
    total = sum(p["wall_s"] for p in phases)
    for p in phases:
        share = (p["wall_s"] / total * 100.0) if total else 0.0
        flags = "  OPEN" if p.get("open") else ""
        print(f"  {p['name']:<{w}}  {p['wall_s']:>9.3f}s  "
              f"x{p['count']:<4d} {share:5.1f}%{flags}", file=out)
    return len(phases)


def cmd_report(args, out=sys.stdout) -> int:
    rec = load_record(args.file)
    print(f"== {rec['label']} ({rec['kind']}: {args.file})", file=out)
    if rec["kind"] == "multichip":
        print(f"  platform={rec['platform']}  mode={rec['mode']}  "
              f"ok={rec['result'].get('ok')}", file=out)
        for key, pt in rec["curve"].items():
            bits = [f"{pt.get('states_per_sec_per_chip', 0):,.0f} "
                    f"st/s/chip",
                    f"syncs={pt.get('host_syncs')}/"
                    f"{pt.get('levels')} lvls"]
            if pt.get("merge"):
                bits.append(f"merge={pt['merge']}")
            pw = pt.get("phase_walls")
            if isinstance(pw, dict):
                # tolerate missing-phase rows: a probe that hit its cap
                # early (or an older artifact) reports what it measured
                bits.append(
                    f"walls expand={pw.get('expand_s', '-')}s "
                    f"exchange={pw.get('exchange_s', '-')}s "
                    f"merge(rank)={pw.get('merge_rank_s', '-')}s "
                    f"merge(fullsort)="
                    f"{pw.get('merge_fullsort_s', '-')}s")
                # ISSUE 11 acceptance metric: (expand+merge)/step — the
                # fused one-level step timed by the same probe
                if isinstance(pw.get("hot_share"), (int, float)):
                    bits.append(
                        f"hot_share={pw['hot_share']:.0%} of "
                        f"step={pw.get('step_s', '-')}s")
            elif pw is not None:
                # a malformed row is a fact about the artifact, not a
                # rendering crash
                bits.append(f"walls=(malformed: {type(pw).__name__})")
            print(f"  {key:<28} " + "  ".join(bits), file=out)
        return 0
    env = rec["env"]
    bits = [f"backend={rec['backend']}", f"platform={rec['platform']}"]
    if rec["mode"]:
        bits.append(f"mode={rec['mode']}")
    if env.get("jax_version"):
        bits.append(f"jax={env['jax_version']}")
    if env.get("device_count"):
        bits.append(f"devices={env['device_count']}")
    print("  " + "  ".join(bits), file=out)
    if rec["kind"] == "bench":
        print(f"  states/sec: {_fmt_rate(rec['states_per_sec'])}  "
              f"vs_baseline={rec['result'].get('vs_baseline')}  "
              f"vs_tlc_estimate={rec['result'].get('vs_tlc_estimate')}",
              file=out)
        print("phases (child + orchestration):", file=out)
        _phase_table(
            [{"name": k, "wall_s": v, "count": 1}
             for k, v in rec["phases"].items()], out)
        # pre-PR1 bench lines carry no phases — that is a fact about the
        # artifact, not a rendering failure
        return 0
    s = rec["summary"]
    res = rec["result"]
    if res:
        print(f"  result: ok={res.get('ok')}  "
              f"distinct={res.get('distinct')}  "
              f"generated={res.get('generated')}  "
              f"diameter={res.get('diameter')}  "
              f"truncated={res.get('truncated')}", file=out)
        print(f"  throughput: {_fmt_rate(rec['states_per_sec'])} "
              f"states/sec over {_fmt_s(res.get('wall_s'))} search "
              f"({_fmt_s(s.get('wall_s'))} total)", file=out)
    print("phases:", file=out)
    rows = _phase_table(s.get("phases", []), out)
    levels = s.get("levels", [])
    if levels:
        gen = sum(r.get("generated", 0) for r in levels)
        walls = [r["wall_s"] for r in levels
                 if isinstance(r.get("wall_s"), (int, float))]
        print(f"levels: {len(levels)} records to depth "
              f"{levels[-1]['level']}; {gen} generated; "
              f"slowest level {_fmt_s(max(walls) if walls else None)}",
              file=out)
    hl = []
    c, g = s.get("counters", {}), s.get("gauges", {})
    for k in ("compile.kernels_built", "compile.cache_hits",
              "compile.cache_misses", "compile.jaxpr_eqns_total",
              "compile.hlo_flops_total", "watchdog.stalls",
              "mesh.host_syncs", "mesh.row_syncs",
              "mesh.exchange_bytes", "analyze.predicted_demotions",
              "analyze.lint_diags", "tier.spills",
              "tier.spilled_keys", "tier.compactions"):
        if k in c:
            hl.append(f"{k}={c[k]}")
    # out-of-core highlight row (ISSUE 12): one cell naming each tier's
    # key occupancy, so a spilling run's artifact reads
    # tier[device=… host=… disk=…] at a glance
    occ = g.get("tier.occupancy")
    if isinstance(occ, dict):
        hl.append("tier[" + " ".join(
            f"{t}={occ.get(t, 0)}" for t in ("device", "host", "disk"))
            + "]")
    # cross-model batching highlight row (ISSUE 13): cohort width,
    # dispatch count and the constants riding the batch axis — a
    # batched fleet artifact reads batch[occupancy=4 dispatches=40
    # lifted=Bound,Limit] at a glance
    bocc = g.get("batch.occupancy", g.get("serve.batch_occupancy"))
    if isinstance(bocc, int) and bocc:
        cells = [f"occupancy={bocc}"]
        bd = g.get("batch.dispatch_count")
        if isinstance(bd, int):
            cells.append(f"dispatches={bd}")
        lifted = g.get("batch.lifted_consts")
        if isinstance(lifted, list) and lifted:
            cells.append("lifted=" + ",".join(str(x) for x in lifted))
        fl = c.get("serve.fastlane_jobs")
        if fl:
            cells.append(f"fastlane={fl}")
        hl.append("batch[" + " ".join(cells) + "]")
    # proven-lane ratio (ISSUE 9): how much of the int-lane surface the
    # static analyzer proved vs what stayed sampled+guarded
    pv, gd = g.get("analyze.proven_lanes"), \
        g.get("layout.pack_guarded_lanes")
    if isinstance(pv, int) and isinstance(gd, int) and (pv or gd):
        hl.append(f"analyze.proven_lanes={pv}/{pv + gd} "
                  f"({100.0 * pv / (pv + gd):.0f}% of int lanes "
                  f"proven)")
    for k in ("expand.mode", "dedup.mode", "seen.mode",
              "tier.device_cap", "tier.probe_wall_s",
              "tier.io_degraded", "truncation.reason",
              "fingerprint.collision_p",
              "layout.width_lanes",
              "layout.packed_width_lanes", "layout.bits_per_state",
              "device.donation", "profile.status",
              "fingerprint.occupancy", "mesh.exchange", "mesh.devices",
              "mesh.merge", "mesh.supersteps", "mesh.superstep_levels",
              "mesh.a2a_gamma", "mesh.a2a_spill", "mesh.a2a_max_bucket",
              "mesh.shard_balance",
              "mesh.phase_expand_s", "mesh.phase_exchange_s",
              "mesh.phase_merge_s", "mesh.phase_merge_rank_s",
              "mesh.phase_merge_fullsort_s",
              "mesh.phase_step_s", "mesh.phase_hot_share",
              "backend.oracle_choice", "backend.oracle_wall_s",
              "device.mem_high_water_bytes", "watchdog.max_stall_s"):
        if k in g:
            hl.append(f"{k}={g[k]}")
    # preflight oracle probes (ISSUE 11 satellite): one cell per
    # candidate platform — live probes show their dispatch wall, dead
    # ones the first words of why
    op = g.get("backend.oracle_probe")
    if isinstance(op, dict):
        cells = []
        for plat, pr in op.items():
            if isinstance(pr, dict) and pr.get("live"):
                cells.append(f"{plat}={pr.get('dispatch_s')}s")
            else:
                why = (pr or {}).get("error", "?") \
                    if isinstance(pr, dict) else "?"
                cells.append(f"{plat}=dead({str(why)[:40]})")
        hl.append("backend.oracle_probe[" + " ".join(cells) + "]")
    # fleet-serve highlight row (PR 16): how the daemon ran this job —
    # serve[warm=yes resumed=yes recompiles=0 batched_with=2] at a
    # glance, same keys cmd_smoke asserts on
    sv = s.get("serve")
    if isinstance(sv, dict) and sv:
        cells = []
        if "warm_engine" in sv:
            cells.append(f"warm={'yes' if sv['warm_engine'] else 'no'}")
        if "resumed_from_checkpoint" in sv:
            cells.append("resumed=" + (
                "yes" if sv["resumed_from_checkpoint"] else "no"))
        if "window_recompiles" in sv:
            cells.append(f"recompiles={sv['window_recompiles']}")
        bw = sv.get("batched_with")
        if isinstance(bw, list) and bw:
            cells.append(f"batched_with={len(bw)}")
        if sv.get("cost_estimate") is not None:
            cells.append(f"est={sv['cost_estimate']}")
        if sv.get("job_wall_s") is not None:
            cells.append(f"wall={_fmt_s(sv['job_wall_s'])}")
        if cells:
            hl.append("serve[" + " ".join(cells) + "]")
    if hl:
        print("highlights: " + "  ".join(hl), file=out)
    return 0 if rows else 1


# ------------------------------------------------------------------ diff

def _effective_env(rec: Dict[str, Any]) -> Dict[str, Any]:
    """The record's env dict with platform/device_count backfilled from
    the record itself (ISSUE 11 satellite): metrics artifacts written
    by interp runs (and multichip artifacts, which carry the platform
    top-level) leave env.platform None, so a backend swap between two
    artifacts used to surface as an unexplained REGRESS instead of an
    attributed environment change."""
    env = dict(rec.get("env") or {})
    if env.get("platform") is None and rec.get("platform"):
        env["platform"] = rec["platform"]
    if env.get("device_count") is None:
        g = (rec.get("summary") or {}).get("gauges") or {}
        dc = g.get("mesh.devices") or g.get("device.count")
        if dc is not None:
            env["device_count"] = dc
    return env


def _env_changes(a: Dict[str, Any], b: Dict[str, Any]) -> List[str]:
    out = []
    for k in ("jax_version", "platform", "device_count", "python"):
        va, vb = a.get(k), b.get(k)
        if va is not None and vb is not None and va != vb:
            out.append(f"{k}: {va} -> {vb}")
    return out


def find_regressions(prev: Dict[str, Any], cur: Dict[str, Any],
                     threshold_pct: float,
                     ignore_phases: frozenset = frozenset()
                     ) -> List[str]:
    """Regression flags between two consecutive records. Environment
    changes are reported alongside each flag so a demotion caused by a
    jax upgrade (or a dead tunnel) reads as such.  `ignore_phases`
    names phases excluded from the per-phase wall gate (cold-start
    one-shot walls like compile_arm are load-sensitive in a way the
    measured search window is not — the backend-check gate skips
    them); the states/sec and demotion gates always apply."""
    flags = []
    step = f"{prev['label']} -> {cur['label']}"
    d = _pct(cur["states_per_sec"], prev["states_per_sec"])
    if d is not None and d < -threshold_pct:
        flags.append(
            f"REGRESS states/sec {step}: "
            f"{_fmt_rate(prev['states_per_sec'])} -> "
            f"{_fmt_rate(cur['states_per_sec'])} ({d:+.1f}%)")
    if cur["rank"] < prev["rank"]:
        flags.append(
            f"REGRESS backend demotion {step}: {prev['platform']} -> "
            f"{cur['platform']}")
    if cur.get("demoted") and not prev.get("demoted"):
        # the run finished (counts are exact via the CPU fallback) but
        # the device path died mid-run — a reliability regression even
        # when the rates happen to survive
        flags.append(
            f"REGRESS device demotion {step}: device backend failed "
            f"terminally, run completed on the CPU fallback "
            f"({cur['demoted']})")
    if cur.get("io_degraded") and not prev.get("io_degraded"):
        # counts stayed exact (the store fell back to host-tier-only)
        # but the disk tier died mid-run — the out-of-core capacity
        # ceiling regressed even though the search survived
        flags.append(
            f"REGRESS tier io degradation {step}: disk-tier write "
            f"failed, seen-set hierarchy ran host-tier-only "
            f"({cur['io_degraded']})")
    if cur.get("admission_rejected") and \
            not prev.get("admission_rejected"):
        # accepted jobs completed, but the fleet turned clients away —
        # capacity (or a tenant budget) regressed vs the previous run
        flags.append(
            f"REGRESS serve admission rejections {step}: "
            f"{cur['admission_rejected']} submissions refused with 429 "
            f"where the previous run refused none")
    if cur.get("spool_degraded") and not prev.get("spool_degraded"):
        # the durable spool exhausted its write retries: results kept
        # flowing over HTTP but durability (restart recovery, takeover)
        # regressed for the affected records
        flags.append(
            f"REGRESS serve spool degradation {step}: spool writes "
            f"exhausted their retries ({cur['spool_degraded']} "
            f"degradation events)")
    for name in sorted(set(prev["phases"]) & set(cur["phases"])):
        if name in ignore_phases:
            continue
        pw, cw = prev["phases"][name], cur["phases"][name]
        pd = _pct(cw, pw)
        # absolute floor: a 3 ms parse doubling is noise, not a flag
        if pd is not None and pd > threshold_pct and cw - pw > 1.0:
            flags.append(
                f"REGRESS phase {name} {step}: {_fmt_s(pw)} -> "
                f"{_fmt_s(cw)} ({pd:+.1f}%)")
    if flags:
        env = _env_changes(_effective_env(prev), _effective_env(cur))
        if env:
            flags.append(f"  note {step}: environment changed "
                         f"({'; '.join(env)})")
    return flags


def _diff_multichip(recs: List[Dict[str, Any]], threshold: float,
                    fail_on_regress: bool, out) -> int:
    """Scaling-artifact trajectory (ISSUE 10 CI satellite): per
    (rung, D) states/sec/chip across MULTICHIP_r* artifacts, a REGRESS
    flag when a later artifact's per-chip rate drops past the
    threshold on any shared point."""
    keys: List[str] = []
    for r in recs:
        for k in r["curve"]:
            if k not in keys:
                keys.append(k)
    lw = max([5] + [len(r["label"]) for r in recs])
    kw = max([10] + [len(k) for k in keys])
    print(f"{'point':<{kw}}  "
          + "  ".join(f"{r['label']:>{max(lw, 12)}}" for r in recs),
          file=out)
    for k in keys:
        cells = []
        for r in recs:
            pt = r["curve"].get(k)
            cells.append(_fmt_rate(pt.get("states_per_sec_per_chip")
                                   if pt else None))
        print(f"{k:<{kw}}  "
              + "  ".join(f"{c:>{max(lw, 12)}}" for c in cells),
              file=out)
    flags: List[str] = []
    for prev, cur in zip(recs, recs[1:]):
        step = f"{prev['label']} -> {cur['label']}"
        step_flagged = False
        for k in keys:
            a, b = prev["curve"].get(k), cur["curve"].get(k)
            if not a or not b:
                continue
            d = _pct(b.get("states_per_sec_per_chip"),
                     a.get("states_per_sec_per_chip"))
            if d is not None and d < -threshold:
                step_flagged = True
                flags.append(
                    f"REGRESS states/sec/chip {k} {step}: "
                    f"{_fmt_rate(a['states_per_sec_per_chip'])} -> "
                    f"{_fmt_rate(b['states_per_sec_per_chip'])} "
                    f"({d:+.1f}%)")
        if step_flagged:
            # attribute a platform/device swap (ISSUE 11 satellite): a
            # cpu-virtual-device baseline diffed against a real-chip
            # artifact is an environment change, not a bare REGRESS
            env = _env_changes(_effective_env(prev),
                               _effective_env(cur))
            if env:
                flags.append(f"  note {step}: environment changed "
                             f"({'; '.join(env)})")
    print("", file=out)
    if flags:
        print("regressions:", file=out)
        for f in flags:
            print(f"  {f}", file=out)
    else:
        print(f"no regressions flagged (threshold {threshold:.0f}%).",
              file=out)
    return 1 if (flags and fail_on_regress) else 0


def _record_ts(rec: Dict[str, Any]) -> float:
    """The record's recorded timestamp for trajectory ordering:
    metrics artifacts carry started_at, multichip artifacts
    generated_at (ISO string); bench rollups carry neither, so the
    file mtime stands in."""
    s = rec.get("summary") or {}
    ts = s.get("started_at")
    if isinstance(ts, (int, float)):
        return float(ts)
    gen = s.get("generated_at")
    if isinstance(gen, str):
        import datetime
        try:
            return datetime.datetime.fromisoformat(
                gen.replace("Z", "+00:00")).timestamp()
        except ValueError:
            pass
    try:
        return os.path.getmtime(rec["path"])
    except OSError:
        return 0.0


def expand_artifact_args(paths: List[str]) -> List[str]:
    """`obs diff` input expansion (ISSUE 17 satellite): each argument
    may be a file, a glob, or a directory (-> its *.json files).  When
    ANY argument expanded, the caller re-orders the whole set by
    recorded timestamp — a shell-quoted "BENCH_r*.json" must diff in
    run order, not lexical luck."""
    out: List[str] = []
    expanded = False
    for p in paths:
        if os.path.isdir(p):
            import glob as _glob
            out.extend(sorted(_glob.glob(os.path.join(p, "*.json"))))
            expanded = True
        elif any(ch in p for ch in "*?["):
            import glob as _glob
            hits = sorted(_glob.glob(p))
            if not hits:
                raise ValueError(f"{p}: glob matched no files")
            out.extend(hits)
            expanded = True
        else:
            out.append(p)
    if not expanded:
        return paths  # explicit files pass through — `diff A A` is legal
    # dedup while preserving order (a dir + an explicit member)
    seen = set()
    return [p for p in out if not (p in seen or seen.add(p))]


def cmd_diff(args, out=sys.stdout) -> int:
    files = expand_artifact_args(args.files)
    recs = [load_record(p) for p in files]
    if files != args.files:
        # expansion happened: order the trajectory by recorded
        # timestamp instead of trusting the shell's lexical order
        recs.sort(key=_record_ts)
    if len(recs) < 2:
        print("error: diff needs at least two artifacts",
              file=sys.stderr)
        return 2
    if all(r["kind"] == "multichip" for r in recs):
        return _diff_multichip(recs, args.threshold,
                               args.fail_on_regress, out)
    # trajectory table: one row per run, the shared top phases as columns
    phase_tot: Dict[str, float] = {}
    for r in recs:
        for k, v in r["phases"].items():
            phase_tot[k] = phase_tot.get(k, 0.0) + v
    cols = [k for k, _ in sorted(phase_tot.items(),
                                 key=lambda kv: -kv[1])[:5]]
    lw = max([5] + [len(r["label"]) for r in recs])
    head = (f"{'run':<{lw}}  {'states/sec':>12}  {'platform':>8}  "
            + "  ".join(f"{c:>14}" for c in cols))
    print(head, file=out)
    print("-" * len(head), file=out)
    for r in recs:
        cells = "  ".join(
            f"{_fmt_s(r['phases'].get(c)):>14}" for c in cols)
        print(f"{r['label']:<{lw}}  "
              f"{_fmt_rate(r['states_per_sec']):>12}  "
              f"{r['platform']:>8}  {cells}", file=out)
    ignore = frozenset(
        p for p in (args.ignore_phases or "").split(",") if p)
    flags: List[str] = []
    for prev, cur in zip(recs, recs[1:]):
        flags.extend(find_regressions(prev, cur, args.threshold,
                                      ignore_phases=ignore))
    print("", file=out)
    if flags:
        print("regressions:", file=out)
        for f in flags:
            print(f"  {f}", file=out)
    else:
        print("no regressions flagged "
              f"(threshold {args.threshold:.0f}%).", file=out)
    real = [f for f in flags if f.lstrip().startswith("REGRESS")]
    if real and args.fail_on_regress:
        return 1
    return 0


# ------------------------------------------------------------------ main

def main(argv: Optional[List[str]] = None, out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.obs",
        description="render and compare jaxmc metrics artifacts")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("report", help="render one metrics/bench artifact")
    r.add_argument("file")
    d = sub.add_parser("diff",
                       help="trajectory table + regression flags over "
                            "2+ metrics/bench artifacts (files, "
                            "quoted globs, or directories — expanded "
                            "and ordered by recorded timestamp)")
    d.add_argument("files", nargs="+")
    d.add_argument("--threshold", type=float, default=10.0,
                   metavar="PCT",
                   help="relative change that counts as a regression "
                        "(default 10%%; phase flags also need >1s "
                        "absolute growth)")
    d.add_argument("--fail-on-regress", action="store_true",
                   help="exit 1 when any REGRESS flag fired (bench/CI "
                        "gate)")
    d.add_argument("--ignore-phases", default="", metavar="P1,P2",
                   help="comma-separated phase names excluded from "
                        "the per-phase wall gate (cold-start compile "
                        "walls flap with box load; states/sec and "
                        "demotion gates always apply)")
    t = sub.add_parser(
        "timeline",
        help="merge multi-process trace JSONLs into one causally "
             "ordered per-process-lane view (orphan spans + silent "
             "gaps flagged)")
    t.add_argument("files", nargs="+")
    t.add_argument("--limit", type=int, default=200,
                   help="max merged events to print (0 = all; the "
                        "summary line always counts all)")
    t.add_argument("--gap-threshold", type=float, default=30.0,
                   metavar="SECONDS",
                   help="flag a lane silent for longer than this "
                        "(default 30s)")
    t.add_argument("--fail-on-orphans", action="store_true",
                   help="exit 1 when any lane's parent span resolves "
                        "to no known process (trace-check gate)")
    tp = sub.add_parser(
        "top",
        help="per-dispatch-site profile table (wall, share, "
             "dispatches, bytes, recompiles) + the HBM model from one "
             "--profile metrics artifact (jaxmc.metrics/4 prof{})")
    tp.add_argument("file")
    h = sub.add_parser(
        "history",
        help="per-rung states/sec trajectory across ALL ledger-"
             "recorded runs; flags the latest run per rung against "
             "the rolling best-of-window")
    h.add_argument("--ledger", default=None, metavar="FILE",
                   help="ledger JSONL (default: JAXMC_LEDGER or "
                        "~/.cache/jaxmc/ledger.jsonl)")
    h.add_argument("--rung", default=None,
                   help="restrict to one rung (e.g. transfer_scaled, "
                        "or a multichip point like philtoy@D8)")
    h.add_argument("--import", dest="import_files", nargs="+",
                   default=None, metavar="ARTIFACT",
                   help="backfill committed artifacts (BENCH_r*.json, "
                        "MULTICHIP_r*.json, --metrics-out JSONs; "
                        "globs ok) into the ledger first — "
                        "content-addressed, so re-importing is "
                        "idempotent")
    h.add_argument("--threshold", type=float, default=25.0,
                   metavar="PCT",
                   help="relative drop vs best-of-window that counts "
                        "as a regression (default 25%%; ledger points "
                        "span machines and months, so the bar is "
                        "looser than diff's pairwise 10%%)")
    h.add_argument("--window", type=int, default=5,
                   help="how many preceding runs form the rolling "
                        "best-of reference (default 5)")
    h.add_argument("--fail-on-regress", action="store_true",
                   help="exit 1 when the latest run of any rendered "
                        "rung regressed (prof-check gate)")
    args = ap.parse_args(argv)
    try:
        if args.cmd == "report":
            return cmd_report(args, out)
        if args.cmd == "timeline":
            from .timeline import cmd_timeline
            return cmd_timeline(args, out)
        if args.cmd == "top":
            from .prof import cmd_top
            return cmd_top(args, out)
        if args.cmd == "history":
            from .ledger import cmd_history
            return cmd_history(args, out)
        return cmd_diff(args, out)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
