r"""Persistent run ledger (ISSUE 17): the perf trajectory as a
first-class, queryable, self-gating artifact.

Before this, the states/sec trajectory lived in loose `BENCH_r*.json` /
`MULTICHIP_r*.json` files compared pairwise by hand-picked `obs diff`
invocations — a regression between gate runs was invisible unless
someone happened to diff the right pair.  The ledger is the cross-run
memory:

  append    every bench child, `make *-check` gate leg and serve job
            appends one compact line (rung, states/sec, platform, env
            fingerprint, source, job signature) to an append-only JSONL
            (default ~/.cache/jaxmc/ledger.jsonl; JAXMC_LEDGER overrides
            the path, JAXMC_LEDGER=off disables).  Appends are
            flock-serialized and content-addressed — the entry id is a
            hash over (rung, ts, rate, sig, env, source), so re-importing
            the same artifact is idempotent and concurrent writers
            cannot corrupt or duplicate.
  history   `python -m jaxmc.obs history [--rung R] [--fail-on-regress]`
            renders the per-rung trajectory across ALL recorded runs
            (not just adjacent pairs) and flags the LATEST entry per
            rung against the best of the preceding window (rolling
            best-of-`--window`), with env-change attribution reused
            from `obs diff` (report._env_changes) so a drop caused by a
            jax upgrade or a device-count change reads as such.
  --import  backfills committed artifacts (BENCH_r01..r05,
            MULTICHIP_r01..r08, any --metrics-out JSON) through
            report.load_record so the trajectory starts at r01.

Pure stdlib (no jax): the CLI must work in interp-only environments.
Writers call `append_summary` which NEVER raises — a full disk or a
read-only cache dir degrades the ledger, not the run.
"""

from __future__ import annotations

import datetime
import glob as _glob
import hashlib
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from . import report

DEFAULT_PATH = os.path.join("~", ".cache", "jaxmc", "ledger.jsonl")
_OFF = frozenset(("off", "0", "no", "none", "disabled"))


def ledger_path(path: Optional[str] = None) -> Optional[str]:
    """Resolve the ledger file: explicit arg wins; else JAXMC_LEDGER
    (a path, or off/0/no/none to disable -> None); else the default
    under ~/.cache."""
    if path:
        return os.path.expanduser(path)
    env = os.environ.get("JAXMC_LEDGER")
    if env is not None:
        env = env.strip()
        if env.lower() in _OFF or not env:
            return None
        return os.path.expanduser(env)
    return os.path.expanduser(DEFAULT_PATH)


def _entry_id(e: Dict[str, Any]) -> str:
    """Content address: stable over the fields that make two records
    "the same run", so concurrent appends and repeated --import of one
    artifact dedup instead of duplicating."""
    key = {k: e.get(k) for k in ("rung", "ts", "states_per_sec",
                                 "sig", "env", "source")}
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def make_entry(rung: str, states_per_sec: Optional[float],
               ts: Optional[float] = None, *,
               run: Optional[str] = None, kind: str = "metrics",
               platform: Optional[str] = None,
               env: Optional[Dict[str, Any]] = None,
               source: Optional[str] = None,
               sig: Optional[str] = None) -> Dict[str, Any]:
    e: Dict[str, Any] = {
        "v": 1,
        "ts": float(ts) if ts is not None else time.time(),
        "rung": rung,
        "run": run or rung,
        "kind": kind,
        "states_per_sec": states_per_sec,
        "platform": platform,
        "env": dict(env or {}),
        "source": source,
    }
    if sig:
        e["sig"] = sig
    e["id"] = _entry_id(e)
    return e


def append_entries(entries: List[Dict[str, Any]],
                   path: Optional[str] = None) -> int:
    """flock-serialized append of pre-built entries; returns the count
    written. Raises on IO errors — callers that must not fail use
    append_summary."""
    p = ledger_path(path)
    if p is None or not entries:
        return 0
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    payload = "".join(
        json.dumps(e, sort_keys=True, separators=(",", ":"),
                   default=str) + "\n"
        for e in entries)
    with open(p, "a", encoding="utf-8") as fh:
        try:
            import fcntl
            fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
        except (ImportError, OSError):
            pass  # non-posix / NFS without locks: plain O_APPEND
        fh.write(payload)
        fh.flush()
    return len(entries)


def _rate_of(summary: Dict[str, Any]) -> Optional[float]:
    res = summary.get("result") or {}
    gen, wall = res.get("generated"), res.get("wall_s")
    if gen and wall:
        return gen / wall
    return None


def append_summary(summary: Dict[str, Any],
                   source: Optional[str] = None,
                   rung: Optional[str] = None,
                   path: Optional[str] = None) -> bool:
    """Append one metrics summary (the dict `Telemetry.summary()`
    builds) to the ledger.  Never raises; returns False when disabled,
    when no states/sec rate computes (a trace-only or failed run has no
    trajectory point), or on any IO error."""
    try:
        p = ledger_path(path)
        if p is None:
            return False
        rate = _rate_of(summary)
        if rate is None:
            return False
        if rung is None:
            if source:
                rung = os.path.basename(source)
                for ext in (".json", ".jsonl"):
                    if rung.endswith(ext):
                        rung = rung[:-len(ext)]
            else:
                spec = summary.get("spec") or \
                    (summary.get("meta") or {}).get("spec")
                rung = os.path.basename(str(spec or "run"))
                if rung.endswith(".tla"):
                    rung = rung[:-4]
        env = dict(summary.get("env") or {})
        serve = summary.get("serve") or {}
        e = make_entry(
            rung, rate, summary.get("started_at"),
            kind="metrics",
            platform=env.get("platform")
            or (summary.get("gauges") or {}).get("device.platform"),
            env=env, source=source,
            sig=serve.get("sig"))
        return append_entries([e], p) > 0
    except Exception:  # noqa: BLE001 — the ledger never breaks a run
        return False


def read_entries(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """All entries, torn-line tolerant, deduped by id (first wins)."""
    p = ledger_path(path)
    out: List[Dict[str, Any]] = []
    seen = set()
    if p is None or not os.path.exists(p):
        return out
    with open(p, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue  # torn tail from a crashed writer
            if not isinstance(e, dict) or "rung" not in e:
                continue
            eid = e.get("id") or _entry_id(e)
            if eid in seen:
                continue
            seen.add(eid)
            out.append(e)
    return out


# ---------------------------------------------------------------- import

def _parse_ts(v) -> Optional[float]:
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return datetime.datetime.fromisoformat(
                v.replace("Z", "+00:00")).timestamp()
        except ValueError:
            return None
    return None


def entries_from_artifact(path: str) -> List[Dict[str, Any]]:
    """Ledger entries for one committed artifact via report.load_record
    — one per run for metrics/bench shapes, one per (rung, devices)
    curve point for multichip scaling artifacts."""
    rec = report.load_record(path)
    mtime = os.path.getmtime(path)
    env = report._effective_env(rec)
    if rec["kind"] == "multichip":
        ts = _parse_ts(rec["summary"].get("generated_at")) or mtime
        out = []
        for key, pt in rec["curve"].items():
            out.append(make_entry(
                key, pt.get("states_per_sec_per_chip"), ts,
                run=rec["label"], kind="multichip",
                platform=rec["platform"], env=env, source=path))
        return out
    if rec["kind"] == "bench":
        return [make_entry(
            "bench", rec["states_per_sec"], mtime,
            run=rec["label"], kind="bench",
            platform=rec["platform"], env=env, source=path)]
    ts = _parse_ts(rec["summary"].get("started_at")) or mtime
    return [make_entry(
        rec["label"], rec["states_per_sec"], ts,
        run=rec["label"], kind="metrics",
        platform=rec["platform"], env=env, source=path)]


def import_artifacts(paths: List[str], path: Optional[str] = None,
                     skipped: Optional[List[str]] = None) -> int:
    """Backfill committed artifacts (`obs history --import`); globs are
    expanded, entries already in the ledger (by content id) are
    skipped. Returns the number of NEW entries appended.  Unparseable
    artifacts (e.g. a failed bench run with `parsed: null`) are
    recorded in `skipped` and do not abort the import — a dead run is
    a fact about the history, not an import failure."""
    files: List[str] = []
    for p in paths:
        if any(ch in p for ch in "*?["):
            files.extend(sorted(_glob.glob(p)))
        elif os.path.isdir(p):
            files.extend(sorted(_glob.glob(os.path.join(p, "*.json"))))
        else:
            files.append(p)
    have = {e.get("id") for e in read_entries(path)}
    fresh: List[Dict[str, Any]] = []
    for f in files:
        try:
            ents = entries_from_artifact(f)
        except (OSError, ValueError, KeyError) as e:
            if skipped is not None:
                skipped.append(f"{f}: {e}")
            continue
        for e in ents:
            if e["id"] not in have:
                have.add(e["id"])
                fresh.append(e)
    return append_entries(fresh, path)


# --------------------------------------------------------------- history

def trajectory(entries: List[Dict[str, Any]]
               ) -> Dict[str, List[Dict[str, Any]]]:
    """Group by rung, each list sorted by (ts, run label)."""
    by: Dict[str, List[Dict[str, Any]]] = {}
    for e in entries:
        by.setdefault(str(e.get("rung")), []).append(e)
    for rows in by.values():
        rows.sort(key=lambda e: (e.get("ts") or 0.0,
                                 str(e.get("run") or "")))
    return by


def flag_latest(rows: List[Dict[str, Any]], threshold_pct: float,
                window: int) -> Optional[str]:
    """REGRESS flag when the LATEST entry of a rung drops more than
    threshold below the best of the preceding `window` entries.  Only
    the latest is judged — a freshly imported history must not spam
    flags for drops that later runs already recovered from; the gate
    cares whether the run just appended regressed."""
    if len(rows) < 2:
        return None
    cur = rows[-1]
    rate = cur.get("states_per_sec")
    if not isinstance(rate, (int, float)):
        return None
    ref = [r for r in rows[-1 - window:-1]
           if isinstance(r.get("states_per_sec"), (int, float))]
    if not ref:
        return None
    best = max(ref, key=lambda r: r["states_per_sec"])
    bv = best["states_per_sec"]
    if bv <= 0:
        return None
    d = (rate - bv) / bv * 100.0
    if d >= -threshold_pct:
        return None
    flag = (f"REGRESS states/sec {cur.get('rung')}: best-of-window "
            f"{bv:,.1f} ({best.get('run')}) -> {rate:,.1f} "
            f"({cur.get('run')}) ({d:+.1f}%)")
    env = report._env_changes(best.get("env") or {},
                              cur.get("env") or {})
    if env:
        flag += f"  [env changed: {'; '.join(env)}]"
    return flag


def _fmt_rate(x) -> str:
    return "-" if not isinstance(x, (int, float)) else f"{x:,.0f}"


def cmd_history(args, out=None) -> int:
    """`python -m jaxmc.obs history` — the per-rung states/sec
    trajectory across all recorded runs, optionally backfilling
    committed artifacts first (--import) and gating
    (--fail-on-regress)."""
    out = out if out is not None else sys.stdout
    lpath = ledger_path(getattr(args, "ledger", None))
    if getattr(args, "import_files", None):
        skipped: List[str] = []
        n = import_artifacts(args.import_files, lpath, skipped=skipped)
        print(f"imported {n} new entr{'y' if n == 1 else 'ies'} "
              f"into {lpath}", file=out)
        for s in skipped:
            print(f"  skipped {s}", file=out)
    entries = read_entries(lpath)
    if getattr(args, "rung", None):
        entries = [e for e in entries
                   if str(e.get("rung")) == args.rung]
    if not entries:
        print(f"ledger {lpath}: no entries"
              + (f" for rung {args.rung}" if getattr(args, "rung", None)
                 else ""), file=out)
        return 0
    by = trajectory(entries)
    kw = max(len(k) for k in by)
    print(f"== ledger history: {lpath} ({len(entries)} entries, "
          f"{len(by)} rungs)", file=out)
    print(f"  {'rung':<{kw}}  {'runs':>4}  trajectory (oldest -> "
          f"latest states/sec)", file=out)
    flags: List[str] = []
    for rung in sorted(by):
        rows = by[rung]
        tail = rows[-6:]
        cells = " -> ".join(_fmt_rate(r.get("states_per_sec"))
                            for r in tail)
        if len(rows) > len(tail):
            cells = "... " + cells
        rates = [r["states_per_sec"] for r in rows
                 if isinstance(r.get("states_per_sec"), (int, float))]
        note = ""
        if rates:
            best = max(rates)
            last = rows[-1].get("states_per_sec")
            if isinstance(last, (int, float)) and best > 0:
                note = f"  (last vs best {100.0 * last / best:.0f}%)"
        print(f"  {rung:<{kw}}  {len(rows):>4}  {cells}{note}",
              file=out)
        f = flag_latest(rows, args.threshold, args.window)
        if f:
            flags.append(f)
    print("", file=out)
    if flags:
        print("regressions:", file=out)
        for f in flags:
            print(f"  {f}", file=out)
    else:
        print(f"no regressions flagged (latest-vs-best-of-{args.window}"
              f", threshold {args.threshold:.0f}%).", file=out)
    return 1 if (flags and args.fail_on_regress) else 0
