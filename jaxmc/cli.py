r"""jaxmc command-line interface.

    python -m jaxmc check SPEC.tla [--cfg F.cfg]
        [--backend interp|jax|auto|cpu|gpu|tpu]
    python -m jaxmc simulate SPEC.tla [--walks N --depth N --coverage]
    python -m jaxmc info SPEC.tla
    python -m jaxmc.serve ...       (checking-as-a-service daemon)

Mirrors the reference's `make test` contract (tlc *tla, Makefile:6-7): check a
spec against its model config, print TLC-style progress and a counterexample
trace on violation. Exit status 0 = no error, 1 = violation, 2 = usage/error,
143 = drained on SIGTERM (checkpointed, resumable).

Since ISSUE 7 the check flow itself lives in jaxmc/session.py
(CheckSession: parse -> compile -> explore as resumable stages); this
module is the thin driver that owns argument parsing, output rendering,
and the exit-code policy — stdout/stderr and exit codes are
byte-identical to the pre-session CLI.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def cmd_check(args) -> int:
    from . import drain, obs

    t0 = time.time()
    # telemetry is a PARALLEL channel: stdout stays byte-identical; a
    # NullTelemetry (every method a no-op) serves runs that asked for no
    # artifact, so the engines' instrumentation costs nothing
    want_tel = bool(args.metrics_out or args.trace or args.profile)
    tel = obs.Telemetry(
        trace_path=args.trace,
        meta={"command": "check", "backend": args.backend,
              "spec": args.spec, "cfg": args.cfg,
              "argv": list(sys.argv[1:]),
              "env": obs.environment_meta()}) if want_tel \
        else obs.NullTelemetry()
    if args.profile:
        # per-dispatch device profiling (ISSUE 17, obs/prof.py): wall
        # mode adds block-until-ready walls + byte accounting to the
        # always-on dispatch counters; a sync cannot change values, so
        # counts/traces stay bit-identical to a profile-off run
        tel.prof.mode = args.profile
    log = obs.Logger(tel, quiet=args.quiet)
    # the watchdog names a wedged phase (device init, a pathological BFS
    # level) on stderr and in the trace WHILE it hangs — start() is a
    # no-op on the NullTelemetry, so runs without an artifact pay nothing
    wd = obs.Watchdog(tel).start()
    # graceful shutdown (ISSUE 7 satellite): SIGTERM requests a
    # cooperative drain — the engine checkpoints at its next safe
    # boundary and returns, so the finally below closes spans and joins
    # the watchdog instead of leaking both; the process exits 143 with
    # the reason named (jaxmc/drain.py)
    drain.install()
    xla_tracing = args.profile == "xla" and _start_xla_trace(args, tel)
    try:
        with obs.use(tel):
            return _run_check(args, tel, log, t0)
    finally:
        if xla_tracing:
            _stop_xla_trace()
        wd.stop()
        tel.close()


def _start_xla_trace(args, tel) -> bool:
    """--profile=xla: wrap the whole run in a jax.profiler trace
    capture to a named artifact dir (JAXMC_XLA_TRACE_DIR, else next to
    --metrics-out, else a fresh tempdir).  Best-effort: a backend
    without profiler support degrades to wall-mode profiling with a
    warning, never a failed run."""
    tdir = os.environ.get("JAXMC_XLA_TRACE_DIR") or \
        (args.metrics_out + ".xla" if args.metrics_out else None)
    if tdir is None:
        import tempfile
        tdir = tempfile.mkdtemp(prefix="jaxmc-xla-")
    try:
        import jax
        jax.profiler.start_trace(tdir)
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        print(f"warning: --profile=xla trace capture unavailable "
              f"({e}); continuing with wall-mode profiling",
              file=sys.stderr)
        return False
    tel.prof.xla_trace_dir = tdir
    print(f"-- profile: xla trace capture -> {tdir}", file=sys.stderr)
    return True


def _stop_xla_trace() -> None:
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:  # noqa: BLE001 — never mask the run's own exit
        pass


def _metrics_error(args, tel, error: str) -> None:
    if args.metrics_out:
        tel.write_metrics(args.metrics_out,
                          result={"ok": False, "distinct": 0,
                                  "generated": 0, "diameter": 0,
                                  "truncated": False, "error": error})


def _run_check(args, tel, log, t0) -> int:
    from .engine.explore import format_trace
    from .session import CheckSession, SessionConfig

    if args.analyze not in ("off", "warn", "strict"):
        # argparse validates only user-typed values against choices —
        # a typo'd JAXMC_ANALYZE env default must fail LOUDLY, not
        # silently degrade a strict CI gate to warn
        print(f"error: invalid --analyze/JAXMC_ANALYZE value "
              f"{args.analyze!r} (expected off, warn or strict)",
              file=sys.stderr)
        _metrics_error(args, tel, f"invalid analyze mode {args.analyze!r}")
        return 2
    sess = CheckSession(SessionConfig.from_args(args), tel=tel, log=log)
    if args.analyze != "off":
        # static analysis stage (ISSUE 9), BEFORE parse so a cfg defect
        # that would make bind_model refuse still reports its full
        # diagnostic list; strict mode refuses to go further
        from .session import AnalyzeError
        try:
            for d in sess.analyze():
                print(f"analyze: {d.render()}", file=sys.stderr)
        except AnalyzeError as ex:
            for d in ex.diagnostics:
                print(f"analyze: {d.render()}", file=sys.stderr)
            print(f"error: --analyze=strict refused the run ({ex}); "
                  f"fix the spec/cfg or re-run with --analyze=warn",
                  file=sys.stderr)
            _metrics_error(args, tel, f"analyze strict: {ex}")
            return 2
    if sess.parse() == "assumes":
        rc = sess.run_assumes()
        if args.metrics_out:
            tel.write_metrics(args.metrics_out,
                              result={"ok": rc == 0, "distinct": 0,
                                      "generated": 0, "diameter": 0,
                                      "truncated": False,
                                      "mode": "assumes"})
        return rc
    if args.backend == "interp":
        res = sess.explore()
    else:
        from . import faults
        from .compile.vspec import CompileError, ModeError
        from .engine.ckpt import CkptError
        faults.ensure_shared_state()  # one budget for run + fallback
        try:
            sess.compile()
            res = sess.explore()
        except ImportError as e:
            print(f"error: the jax backend is not available in this build "
                  f"({e})", file=sys.stderr)
            _metrics_error(args, tel, f"jax unavailable: {e}")
            return 2
        except ModeError as e:
            print(f"error: {e}", file=sys.stderr)
            _metrics_error(args, tel, str(e))
            return 2
        except CompileError as e:
            print(f"error: this spec is outside the jax backend's "
                  f"compilable subset ({e}); re-run with "
                  f"--backend interp", file=sys.stderr)
            _metrics_error(args, tel, str(e))
            return 2
        except CkptError:
            raise  # main() maps checkpoint defects to exit 2
        except (faults.FaultInjected, RuntimeError, OSError, MemoryError,
                ConnectionError) as e:
            # TERMINAL device failure (init retries exhausted, the XLA
            # runtime died mid-search, the tunnel dropped): fall back to
            # the parallel CPU engine RESUMING from the last host
            # snapshot instead of exiting with hours of progress lost.
            # Spec-compatibility refusals (ModeError/CompileError) and
            # semantic errors (EvalError) are handled above/elsewhere —
            # the interp would hit those identically, so no fallback.
            if args.no_device_fallback:
                raise
            res = sess.demote_to_cpu(e)
    wall = time.time() - t0
    print(f"{res.generated} states generated, {res.distinct} distinct states "
          f"found ({res.generated / max(res.wall_s, 1e-9):.0f} states/sec, "
          f"backend={args.backend}, wall {wall:.2f}s)")
    for w in getattr(res, "warnings", []):
        print(f"Warning: {w}")
    if args.metrics_out:
        mst = getattr(sess.model, "_memo", None)
        if mst is not None:
            tel.gauge("memo.hits", mst.hits)
            tel.gauge("memo.misses", mst.misses)
        result = {"ok": res.ok, "distinct": res.distinct,
                  "generated": res.generated, "diameter": res.diameter,
                  "truncated": bool(getattr(res, "truncated", False)),
                  "wall_s": round(res.wall_s, 6),
                  "warnings": list(getattr(res, "warnings", []))}
        if getattr(res, "drained", False):
            result["drained"] = True
        # ISSUE 12 result surface: seen-key mode, the fingerprint
        # collision bound, the named exhausted resource on truncation,
        # and the tier-hierarchy summary when the run spilled
        result["seen_mode"] = getattr(res, "seen_mode", "exact")
        if getattr(res, "collision_p", None) is not None:
            result["collision_p"] = res.collision_p
        if getattr(res, "trunc_reason", None):
            result["trunc_reason"] = res.trunc_reason
        if getattr(res, "tiers", None):
            result["tiers"] = res.tiers
        if res.violation is not None:
            result["violation"] = {"kind": res.violation.kind,
                                   "name": res.violation.name}
        tel.write_metrics(args.metrics_out, result=result)
    if res.ok:
        if getattr(res, "drained", False):
            # cooperative SIGTERM drain: checkpointed at a safe
            # boundary, spans closed, resumable — exit 143, never a
            # silent 0 (the search did NOT complete)
            from . import drain
            print("Search DRAINED at a safe boundary - no error found "
                  "in the explored prefix.")
            print(f"jaxmc: drained ({drain.reason()})"
                  + (f"; resume with --resume {args.checkpoint}"
                     if args.checkpoint else "; no checkpoint was "
                     "configured"), file=sys.stderr)
            return drain.DRAIN_EXIT_CODE
        if getattr(res, "truncated", False):
            print("Search TRUNCATED at state limit - no error found in the "
                  "explored prefix.")
        else:
            print("Model checking completed. No error has been found.")
        return 0
    print(format_trace(res.violation))
    return 1


def cmd_simulate(args) -> int:
    """TLC's -simulate mode: random behaviors, invariants checked along
    the way (engine/simulate.py)."""
    from .engine.simulate import random_walks
    from .engine.explore import format_trace
    from .session import load_model

    model = load_model(args.spec, args.cfg, no_deadlock=args.no_deadlock,
                       includes=args.include)
    v = random_walks(model, n_walks=args.walks, depth=args.depth,
                     seed=args.seed, check_invariants=True,
                     coverage_guided=args.coverage,
                     check_deadlock=model.check_deadlock)
    if v is None:
        print(f"{args.walks} behaviors of length <= {args.depth} simulated. "
              f"No error has been found.")
        return 0
    print(format_trace(v))
    return 1


def cmd_sweep(args) -> int:
    from .corpus import sweep
    return 1 if sweep(backend=args.backend, include_slow=args.slow,
                      metrics_out=args.metrics_out) else 0


def cmd_info(args) -> int:
    from .sem.modules import Loader
    from .front import tla_ast as A

    ldr = Loader([os.path.dirname(os.path.abspath(args.spec))])
    mod = ldr.load_path(args.spec)
    print(f"module {mod.name}")
    print(f"  extends:   {', '.join(mod.ast.extends) or '-'}")
    print(f"  constants: {', '.join(n for n, _ in mod.constants) or '-'}")
    print(f"  variables: {', '.join(mod.variables) or '-'}")
    ops = [u.name for u in mod.ast.units if isinstance(u, A.OpDef)]
    print(f"  operators: {len(ops)}")
    # batch compatibility surface (ISSUE 13): which constants would
    # ride the batch axis, the layout-compat class key, and analyze's
    # state-space estimate — the parse-time facts the serve fleet
    # schedules on.  Needs a bindable cfg; silent otherwise (info on a
    # bare module stays cfg-free).
    cfgp = getattr(args, "cfg", None) or \
        os.path.splitext(args.spec)[0] + ".cfg"
    if os.path.exists(cfgp):
        # ONE model load + ONE bounds fixpoint serve both the batch
        # line and the analysis surface below
        model = rep = None
        try:
            from .session import load_model
            from .analyze.bounds import infer_state_bounds
            model = load_model(args.spec, cfgp, False)
            rep = infer_state_bounds(model)
            model._bounds_report = rep
        except Exception:  # noqa: BLE001 — info must never fail on
            model = None   # an analysis defect
        try:
            from .session import SessionConfig, batch_profile
            prof = batch_profile(SessionConfig(
                spec=args.spec, cfg=cfgp, backend="jax",
                host_seen=True), model=model)
        except Exception:  # noqa: BLE001
            prof = None
        if prof is not None:
            est = prof.cost_estimate \
                if prof.cost_estimate is not None else "?"
            print(f"  batch:     sig={prof.bsig} "
                  f"lifted=[{', '.join(prof.lift) or '-'}] "
                  f"est_states={est}")
        if model is None:
            print("  analyze:   unavailable (model does not bind)")
            return 0
        # analysis surface (ISSUE 15): why a spec did or did not get
        # the fast path — proven per-element lane bounds, the
        # predicted state count the capacity ladder/fast lane reads,
        # and the arm-independence matrix regrouping/--por consume
        try:
            from .analyze.bounds import state_space_estimate
            from .analyze.independence import (independence_report,
                                               por_refusal)
            if rep is None:
                print("  bounds:    analysis bailed (no proofs)")
            else:
                ebs = rep.element_bounds()
                lanes = rep.lane_bounds()
                parts = []
                for v in model.vars:
                    if v in lanes:
                        parts.append(f"{v}∈[{lanes[v][0]},"
                                     f"{lanes[v][1]}]")
                    elif v in ebs:
                        parts.append(f"{v}:{ebs[v]!r}")
                est = state_space_estimate(model, rep)
                print(f"  bounds:    "
                      f"{'converged' if rep.converged else 'TRUNCATED'}"
                      f" proven=[{', '.join(parts) or '-'}] "
                      f"predicted_states="
                      f"{est if est is not None else '?'}")
            irep = independence_report(model)
            refusal = por_refusal(model)
            print(f"  independence: {len(irep.labels)} arms, "
                  f"{irep.commuting_pairs()} commuting pairs, "
                  f"{len(irep.por_safe)} por-safe"
                  + (f" (--por disabled: {refusal})" if refusal
                     else ""))
            for row in irep.matrix_rows():
                print(f"    {row}")
            # dynamic-key classification (ISSUE 18): WHY each arm is
            # (or is not) element-commuting — the key expressions the
            # element-atom footprints resolved to
            print("  key classes:")
            for row in irep.keyclass_rows():
                print(f"    {row}")
        except Exception as ex:  # noqa: BLE001 — info must never fail
            if os.environ.get("JAXMC_DEBUG"):
                raise
            print(f"  analyze:   unavailable ({type(ex).__name__})")
    return 0


def main(argv=None) -> int:
    from .compile.vspec import Bounds  # no jax dependency
    from .backend import BACKEND_CHOICES  # no jax dependency
    ap = argparse.ArgumentParser(prog="jaxmc")
    sub = ap.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("check", help="model-check a spec")
    c.add_argument("spec")
    c.add_argument("--cfg", default=None)
    c.add_argument("-I", "--include", action="append", default=[],
                   help="extra module search directories (MC shims "
                        "extending reference specs)")
    c.add_argument("--backend", choices=list(BACKEND_CHOICES),
                   default="interp",
                   help="interp = the exact Python engine; jax = the "
                        "XLA engine on whatever platform jax picks "
                        "(honors --platform); cpu|gpu|tpu = the XLA "
                        "engine PINNED to that platform; auto = probe "
                        "the visible platforms with the preflight "
                        "oracle (seconds, hang-proof) and run on the "
                        "best live one (verdict in the metrics "
                        "artifact as backend.oracle_choice)")
    c.add_argument("--platform", default=os.environ.get("JAXMC_PLATFORM"),
                   help="pin the jax platform (e.g. 'cpu', 'tpu') before "
                        "device init - 'cpu' keeps --backend jax usable "
                        "when the accelerator plugin would hang on a dead "
                        "link (env: JAXMC_PLATFORM; plugin registration "
                        "ignores JAX_PLATFORMS, so this uses "
                        "jax.config.update)")
    c.add_argument("--max-states", type=int, default=None)
    c.add_argument("--workers", type=int, metavar="N", default=None,
                   help="interp backend: worker processes for parallel "
                        "frontier expansion (default: JAXMC_WORKERS, "
                        "else min(cpu_count, 8); 1 = the serial engine; "
                        "results are bit-identical either way)")
    c.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="jax backend: persistent XLA compilation-cache "
                        "directory — repeat runs skip the per-arm "
                        "compiles; hit/miss lands in the metrics "
                        "artifact as compile.persistent_cache_* "
                        "(env: JAXMC_COMPILE_CACHE)")
    c.add_argument("--no-deadlock", action="store_true",
                   help="disable deadlock checking")
    c.add_argument("--analyze", choices=["off", "warn", "strict"],
                   default=os.environ.get("JAXMC_ANALYZE", "off"),
                   help="static analysis stage between parse and "
                        "compile (ISSUE 9): lint the spec/cfg pair "
                        "(unused defs/VARIABLEs/CONSTANTs, dead "
                        "actions, cfg mismatches, symmetry hazards — "
                        "stable JMC* codes). warn prints diagnostics "
                        "on stderr and continues; strict exits 2 on "
                        "any error diagnostic BEFORE compiling "
                        "(env: JAXMC_ANALYZE). Bounds inference and "
                        "demotion prediction are independent of this "
                        "flag (JAXMC_ANALYZE_BOUNDS / "
                        "JAXMC_ANALYZE_PREDICT, both default on)")
    c.add_argument("--por", action="store_true",
                   help="partial-order reduction (ISSUE 15, opt-in): "
                        "expand ONE provably-commuting invisible arm "
                        "per state (persistent-set filter; BFS cycle "
                        "proviso) instead of every enabled arm. "
                        "Preserves invariant/deadlock verdicts and "
                        "reports traces that replay under unreduced "
                        "semantics — raw state counts SHRINK by "
                        "design. Runs on the exact interpreter engine; "
                        "disabled with a named reason on CONSTRAINT/"
                        "SYMMETRY/VIEW/temporal models. Reduction "
                        "facts: jaxmc info --cfg prints the arm "
                        "independence matrix")
    c.add_argument("--no-device-fallback", action="store_true",
                   help="jax backend: exit on a terminal device failure "
                        "instead of falling back to the parallel CPU "
                        "engine (which resumes from the last host "
                        "snapshot when --checkpoint is set)")
    c.add_argument("--quiet", action="store_true")
    c.add_argument("--progress-every", type=float, default=30.0)
    c.add_argument("--seq-cap", type=int, default=Bounds.seq_cap,
                   help="jax backend: sequence-length capacity FLOOR "
                        "(actual cap = max(floor, observed * margin); "
                        "raise if a run aborts with capacity overflow)")
    c.add_argument("--grow-cap", type=int, default=Bounds.grow_cap,
                   help="jax backend: growing-set capacity floor")
    c.add_argument("--kv-cap", type=int, default=Bounds.kv_cap,
                   help="jax backend: message-table domain capacity floor")
    c.add_argument("--no-trace", action="store_true",
                   help="jax backend: skip trace bookkeeping (benchmarks)")
    c.add_argument("--host-seen", action="store_true",
                   help="jax backend: keep the seen-set in the native C++ "
                        "fingerprint store (state spaces beyond device "
                        "memory; usually faster)")
    c.add_argument("--seen", choices=("auto", "exact", "fingerprint"),
                   default="auto",
                   help="jax backend: dedup-key mode. auto = exact keys "
                        "on narrow layouts, 128-bit fingerprints past "
                        "FP_THRESHOLD (today's default); fingerprint = "
                        "force fingerprints on ANY layout (4-8x the "
                        "states per seen tier; the collision-"
                        "probability bound is reported in the result); "
                        "exact = refuse to fingerprint (errors on wide "
                        "layouts / resident / host-seen)")
    c.add_argument("--seen-cap", type=int, default=None, metavar="ROWS",
                   help="jax backend: device seen-table cap in key "
                        "rows (env: JAXMC_SEEN_CAP). On overflow the "
                        "sorted device prefix SPILLS to host-RAM and "
                        "then disk tiers (out-of-core checking) "
                        "instead of growing device memory — counts and "
                        "traces stay bit-identical to the uncapped "
                        "run. Default: no cap (grow on device)")
    c.add_argument("--seen-spill", default=None, metavar="DIR",
                   help="jax backend: disk-tier directory for spilled "
                        "seen-set runs (env: JAXMC_SPILL_DIR; default "
                        "a temp dir). Host-RAM tier budget: "
                        "JAXMC_TIER_HOST_KEYS keys")
    c.add_argument("--sample", type=int, nargs=3,
                   default=[800, 40, 60],
                   metavar=("BFS", "WALKS", "DEPTH"),
                   help="jax backend: layout-sampling effort (BFS-prefix "
                        "states, random walks, walk depth). Deep models "
                        "need more walks/depth so every container shape "
                        "and record variant is OBSERVED - an unobserved "
                        "variant demotes its reader kernels to the "
                        "interpreter (hybrid) or aborts")
    c.add_argument("--chunk", type=int, default=2048,
                   help="jax backend: frontier rows expanded per kernel "
                        "call (bounds device memory; host-seen mode)")
    c.add_argument("--resident", action="store_true",
                   help="jax backend: run the WHOLE search device-side "
                        "(frontier, fingerprint set, level loop in one "
                        "jitted while_loop) - fastest over a high-latency "
                        "device link; no traces, no temporal properties")
    c.add_argument("--checkpoint", default=None,
                   help="write periodic checkpoints to this file "
                        "(TLC's states/ equivalent; both backends)")
    c.add_argument("--checkpoint-every", type=float, default=600.0)
    c.add_argument("--resume", default=None,
                   help="resume a run from a checkpoint (the backend and "
                        "device mode must match the writing run's)")
    c.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write an end-of-run JSON metrics artifact: "
                        "phase wall times, per-level BFS counts, "
                        "expansion-mode/memo/fingerprint/compile-cost "
                        "counters, the env fingerprint and the result "
                        "block (schema jaxmc.metrics/4; see "
                        "jaxmc/obs/schema.py; render/compare with "
                        "python -m jaxmc.obs report|diff|top)")
    c.add_argument("--trace", default=None, metavar="FILE",
                   help="stream telemetry events as JSONL while the run "
                        "is live (span_open/span/level/log plus "
                        "watchdog heartbeat/stall beats); a killed "
                        "run leaves open spans naming the phase it "
                        "died in, and a wedged phase is flagged by a "
                        "stall event while it hangs (knobs: "
                        "JAXMC_HEARTBEAT_EVERY/JAXMC_STALL_FACTOR/"
                        "JAXMC_STALL_MIN_S)")
    c.add_argument("--profile", nargs="?", const="wall", default=None,
                   choices=("wall", "xla"),
                   help="per-dispatch device profiling (obs/prof.py): "
                        "block-until-ready wall, bytes and recompiles "
                        "per named dispatch site plus the HBM buffer "
                        "model, stamped into --metrics-out as the "
                        "prof{} block (render with python -m "
                        "jaxmc.obs top). --profile=xla additionally "
                        "captures a jax.profiler trace to "
                        "JAXMC_XLA_TRACE_DIR (default: "
                        "METRICS_OUT.xla/). Profiling never changes "
                        "counts or traces")
    c.set_defaults(fn=cmd_check)

    m = sub.add_parser("simulate",
                       help="check invariants along random behaviors "
                            "(TLC -simulate)")
    m.add_argument("spec")
    m.add_argument("--cfg", default=None)
    m.add_argument("-I", "--include", action="append", default=[])
    m.add_argument("--walks", type=int, default=100)
    m.add_argument("--depth", type=int, default=100)
    m.add_argument("--seed", type=int, default=0)
    m.add_argument("--coverage", action="store_true",
                   help="bias toward rarely-taken action families")
    m.add_argument("--no-deadlock", action="store_true",
                   help="disable deadlock reporting")
    m.set_defaults(fn=cmd_simulate)

    i = sub.add_parser("info", help="parse a spec and print a summary")
    i.add_argument("spec")
    i.add_argument("--cfg", default=None,
                   help="model config for the batch-compat surface "
                        "(default: <spec>.cfg when present)")
    i.set_defaults(fn=cmd_info)

    s = sub.add_parser("sweep",
                       help="check the WHOLE corpus with expected "
                            "verdicts (the reference's `tlc *tla`)")
    s.add_argument("--backend", choices=("interp", "jax"),
                   default="interp")
    s.add_argument("--slow", action="store_true",
                   help="include the multi-minute models")
    s.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="write a per-case JSON metrics artifact "
                        "(status, wall time, expansion mode) next to "
                        "the sweep log")
    s.set_defaults(fn=cmd_sweep)

    args = ap.parse_args(argv)
    from .engine.ckpt import CkptError  # no jax dependency
    try:
        return args.fn(args)
    except CkptError as e:
        # the checkpoint exit-code contract: every resume defect (bad
        # path, module mismatch, truncation, checksum failure) is ONE
        # actionable line on stderr and exit 2 — never a traceback,
        # never a silently-wrong resume
        print(f"error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        if os.environ.get("JAXMC_DEBUG"):
            raise
        return 2


if __name__ == "__main__":
    sys.exit(main())
