r"""Deterministic fault injection (JAXMC_FAULTS) — the chaos harness.

Long exact-enumeration runs die in a handful of boring ways: an
OOM-killed pool worker, a transient chunk failure, a clipped checkpoint
file, a device plugin that refuses to come up.  The fault-tolerance
layer (engine/parallel.py requeue/respawn, engine/ckpt.py integrity
checks, cli.py device fallback) exists to survive exactly those — and
this registry lets tests and `make chaos` trigger each one on demand,
deterministically, without root or cgroup tricks.

Grammar (comma-separated sites, colon-separated params):

    JAXMC_FAULTS=worker_kill:level=2,chunk_error:level=1:n=3,ckpt_corrupt

Reserved params:

    n=K        fire at most K times TOTAL across every process sharing
               the run (default 1; the cross-process latch lives in a
               shared state directory, see below)
    mode=M     site-specific variant (ckpt_corrupt: truncate | flip)

Any other param is a CONTEXT MATCHER: the site fires only when the
caller's keyword context carries the same value (string-compared), e.g.
`worker_kill:level=2` fires only for `kill_self("worker_kill",
level=2)`.  A param naming a key the call site does not pass never
matches (so a typo'd matcher disables the fault instead of firing it
everywhere).

Sites wired in this PR:

    worker_kill       a parallel-engine pool WORKER SIGKILLs itself at
                      the start of a chunk (simulated OOM kill)
    chunk_error       a pool worker raises a transient error instead of
                      expanding its chunk
    run_kill          the MAIN process SIGKILLs itself entering a BFS
                      level (serial / parallel / device engines) — the
                      kill/resume parity harness.  Resident engines
                      fire it at their DISPATCH boundaries: for the
                      mesh engine under multi-level supersteps
                      (ISSUE 10) `level=` therefore matches only
                      depths that are superstep boundaries — pin
                      JAXMC_MESH_SUPERSTEP=1 to make every level a
                      boundary in chaos runs
    ckpt_corrupt      every checkpoint write leaves a truncated
                      (mode=truncate, default) or bit-flipped
                      (mode=flip) file behind
    device_init_fail  device/plugin init raises (cli.py retries)
    compile_fail      a per-arm kernel compile raises transiently
                      (tpu/bfs.py retries)
    device_run_fail   the device search loop raises entering a level
                      (cli.py demotes to the parallel CPU engine)
    tier_io_error     a hierarchical-seen-set disk write fails
                      (backend/tiers.py, ISSUE 12): the tier store
                      must DEGRADE to host-tier-only with a named
                      `tier.io_degraded` event — counts stay exact,
                      the run never crashes (ctx: op=write)

Persistent-compile-cache guard sites (ISSUE 5, jaxmc/compile/cache.py —
each must degrade to COLD compilation with the run intact, pinned by
tests/test_cache_guard.py):

    cache_hang        the cache health-probe subprocess wedges (the
                      known cross-build blob-reload hang): the guard's
                      timeout fires, the dir is quarantined, the run
                      compiles cold
    cache_corrupt     one cache entry is zero-truncated before the
                      corruption scan: the entry is quarantined into
                      <dir>/.quarantine and the cache stays enabled
    cache_lock        the guard's flock acquisition reports contention
                      (another process mid-quarantine): cold fallback
                      for this process only

Fleet-serving sites (ISSUE 19, serve/{queue,daemon}.py — the chaos
surface for `make fleet-check` and tests/test_chaos.py):

    daemon_kill       the serve daemon SIGKILLs itself mid-run, right
                      after marking jobs running (ctx: job=<id>,
                      kind=solo|vbatch, spec=<basename>) — a peer must
                      detect the expired lease, steal the job, and
                      finish it bit-identically from its checkpoint;
                      repeated deaths exhaust the cross-daemon retry
                      budget and quarantine the job
    lease_stall       a daemon's fleet loop skips a heartbeat/renewal
                      tick (ctx: daemon=<id>): its leases age toward
                      expiry while the job thread keeps running — the
                      double-claim chaos leg (exactly one winner; the
                      stalled daemon must drop its now-stolen results)
    spool_io_error    an atomic spool write (job record / result /
                      quarantine) raises (ctx: file=<basename>): the
                      queue retries with backoff, then degrades with a
                      named `serve.spool_degraded` event (HTTP 503,
                      never a raw 500)

Mesh sites (ISSUE 8, tpu/mesh.py — evaluated at ENGINE BUILD time, not
per dispatch, because the routing is compiled into the jitted step):

    mesh_skew         the owner-routing hash collapses to shard 0 on
                      BOTH the host init-shard path and the device
                      all_to_all routing (one formula, so they cannot
                      disagree): every state lands on one seen shard,
                      forcing worst-case imbalance, the a2a spill pass
                      and — once the spill overflows — the
                      gamma-growth level rerun.  Counts and traces
                      must stay exact throughout, under BOTH merge
                      strategies (rank / fullsort, ISSUE 10) and any
                      superstep size (tests/test_mesh_resident.py).

Cross-process accounting: the first registry to activate creates a
state directory and exports it as JAXMC_FAULTS_STATE, so forked pool
workers AND subprocess children share one `n=` budget (the latch is an
O_CREAT|O_EXCL file per firing — atomic across processes).  Every
firing emits a `fault.injected` trace event and bumps the
`faults.injected` counter on the active telemetry.
"""

from __future__ import annotations

import errno
import os
import signal
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

_RESERVED = ("n", "mode")


class FaultInjected(RuntimeError):
    """Raised by `inject` sites when the named fault fires."""

    def __init__(self, site: str, ctx: Optional[Dict[str, Any]] = None):
        self.site = site
        self.ctx = dict(ctx or {})
        extra = "".join(f" {k}={v}" for k, v in sorted(self.ctx.items()))
        super().__init__(f"injected fault: {site}{extra} (JAXMC_FAULTS)")


class FaultSpec:
    __slots__ = ("site", "n", "mode", "match")

    def __init__(self, site: str, params: Dict[str, str]):
        self.site = site
        try:
            self.n = max(0, int(params.get("n", "1")))
        except ValueError:
            self.n = 1
        self.mode = params.get("mode")
        self.match = {k: v for k, v in params.items()
                      if k not in _RESERVED}

    def matches(self, ctx: Dict[str, Any]) -> bool:
        for k, want in self.match.items():
            if k not in ctx or str(ctx[k]) != want:
                return False
        return True


def parse_faults(s: str) -> List[FaultSpec]:
    """Parse a JAXMC_FAULTS value; malformed entries are skipped (the
    harness must never take a run down by itself)."""
    out: List[FaultSpec] = []
    for entry in (s or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site = parts[0].strip()
        if not site:
            continue
        params: Dict[str, str] = {}
        for p in parts[1:]:
            if "=" in p:
                k, _, v = p.partition("=")
                params[k.strip()] = v.strip()
        out.append(FaultSpec(site, params))
    return out


# ---------------------------------------------------------------- registry

_CACHE: Optional[Tuple[str, List[FaultSpec]]] = None


def _specs() -> List[FaultSpec]:
    """The active fault list, re-parsed when JAXMC_FAULTS changes (tests
    flip it mid-process via monkeypatch)."""
    global _CACHE
    env = os.environ.get("JAXMC_FAULTS", "")
    if _CACHE is not None and _CACHE[0] == env:
        return _CACHE[1]
    specs = parse_faults(env) if env else []
    _CACHE = (env, specs)
    return specs


def _state_dir() -> str:
    """The shared cross-process latch directory (created lazily, exported
    so fork/subprocess children inherit the same budget)."""
    d = os.environ.get("JAXMC_FAULTS_STATE")
    if d:
        return d
    d = tempfile.mkdtemp(prefix="jaxmc-faults-")
    os.environ["JAXMC_FAULTS_STATE"] = d
    return d


def _claim(site: str, budget: int) -> bool:
    """Atomically claim one of the site's `budget` firings across every
    process sharing the state dir."""
    if budget <= 0:
        return False
    d = _state_dir()
    for i in range(budget):
        path = os.path.join(d, f"{site}.{i}")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError as ex:
            if ex.errno == errno.EEXIST:
                continue
            return False  # state dir gone: fail closed, never crash
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        return True
    return False


def active() -> bool:
    return bool(_specs())


def ensure_shared_state() -> None:
    """Pin the cross-process state dir BEFORE forking children, so the
    whole process tree spends ONE `n=` budget.  A worker forked before
    this ran would lazily create its own dir and re-fire every respawn."""
    if active():
        _state_dir()


def targets(*sites: str) -> bool:
    """True when any configured fault names one of `sites` — engines use
    this to pick the code path the fault can actually reach (e.g. the
    parallel engine forces the worker pool on when worker faults are
    configured, so a tiny model still exercises them)."""
    want = set(sites)
    return any(sp.site in want for sp in _specs())


def fire(site: str, **ctx: Any) -> Optional[FaultSpec]:
    """The matched spec when `site` should fail HERE, else None.  Spends
    one unit of the spec's cross-process `n=` budget and records the
    firing on the active telemetry."""
    for sp in _specs():
        if sp.site != site or not sp.matches(ctx):
            continue
        if not _claim(site, sp.n):
            continue
        try:  # telemetry must never break the harness (or vice versa)
            from . import obs
            tel = obs.current()
            tel.event("fault.injected", site=site,
                      **{k: str(v) for k, v in ctx.items()})
            tel.counter("faults.injected")
        except Exception:  # noqa: BLE001
            pass
        return sp
    return None


def inject(site: str, **ctx: Any) -> None:
    """Raise FaultInjected when the site fires (transient-error sites)."""
    if fire(site, **ctx) is not None:
        raise FaultInjected(site, ctx)


def kill_self(site: str, **ctx: Any) -> None:
    """SIGKILL the CURRENT process when the site fires — the simulated
    OOM kill.  No cleanup handlers run, exactly like the real thing."""
    if fire(site, **ctx) is not None:
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(5)  # never proceed past a pending SIGKILL


def corrupt_file(site: str, path: str, **ctx: Any) -> bool:
    """Damage `path` in place when the site fires: mode=truncate (default)
    clips the tail, mode=flip flips one payload byte.  Returns True when
    the file was damaged (checkpoint writers call this AFTER the atomic
    rename, so the damage models post-write disk corruption)."""
    sp = fire(site, path=os.path.basename(path), **ctx)
    if sp is None:
        return False
    try:
        size = os.path.getsize(path)
        if sp.mode == "flip" and size > 0:
            with open(path, "r+b") as fh:
                fh.seek(max(0, size - max(1, size // 4)))
                b = fh.read(1)
                fh.seek(-1, os.SEEK_CUR)
                fh.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
        else:
            with open(path, "r+b") as fh:
                fh.truncate(max(1, size // 2))
        return True
    except OSError:
        return False


def reset_for_tests() -> None:
    """Drop the parse cache and detach from the shared state dir so each
    test gets a fresh `n=` budget."""
    global _CACHE
    _CACHE = None
    os.environ.pop("JAXMC_FAULTS_STATE", None)
