r"""`python -m jaxmc.tracecheck` — the `make trace-check` gate.

End-to-end proof of the PR-16 observability contract, in one process,
against a fresh spool:

  1. boot an in-process serve daemon (2 worker threads, fleet trace,
     device-owner routing ON) and submit a deliberately SLOW interp job
     (a CONSTRAINT-bounded grid whose actions carry an expensive
     bounded-quantifier guard, so analyze proves a state-space estimate
     AND the search lasts long enough to scrape mid-run) with
     --workers 2, so the fork pool spawns real worker processes;
  2. while that job runs, poll GET /metrics and assert (a) every
     sample line parses as Prometheus text 0.0.4, (b) the per-job
     jaxmc_search_progress_est{job="<id>"} gauge is present and MOVES
     between scrapes, (c) GET /jobs/<id>/events answers mid-run from
     the bounded ring;
  3. resubmit the identical job — the warm counters must move
     (serve.warm_hits via the signature-keyed warm registry);
  4. run one jax resident job, which device-owner routing sends to the
     spawned owner process — a third OS process in the trace;
  5. merge the daemon trace + every per-job trace with `python -m
     jaxmc.obs timeline --fail-on-orphans` and assert the summary line
     counts >= 3 distinct processes and ZERO orphan spans (every
     process joined the fleet trace through JAXMC_TRACE_CTX);
  6. gate the warm artifact against the cold one with `obs diff
     --fail-on-regress`.

Exit 0 only when every assertion holds; each failure prints one
`trace-check: FAIL: ...` line.  `make bench-check` runs this after the
serve smoke.
"""

from __future__ import annotations

import argparse
import glob
import io
import os
import re
import sys
import tempfile
import time
import urllib.request
from typing import List, Optional

# the slow scrape target: ~230 distinct states over 21 levels, frontier
# wide enough (> workers*4) that the interp fork pool actually forks,
# CONSTRAINT-bounded tightly enough that the analyze interval fixpoint
# converges BEFORE widening (~30 iterations) and proves an estimate;
# the \A guard costs ~Q interpreter steps per successor, which is what
# makes the search last seconds instead of milliseconds
_SLOW_SPEC = """\
-------------------------- MODULE traceload --------------------------
EXTENDS Naturals

VARIABLES a, b

Slow == \\A i \\in 1 .. {q} : i + a >= 0

Init == a = 0 /\\ b = 0

Next == \\/ a' = a + 1 /\\ b' = b /\\ Slow
        \\/ b' = b + 1 /\\ a' = a /\\ Slow

Bound == a + b <= {bound}

TypeInv == a >= 0 /\\ b >= 0

Spec == Init /\\ [][Next]_<<a, b>>
======================================================================
"""

_SLOW_CFG = """\
SPECIFICATION Spec
CONSTRAINT Bound
INVARIANT TypeInv
CHECK_DEADLOCK FALSE
"""

# one Prometheus 0.0.4 sample line: name{labels}? value
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?"
    r" -?\d+(\.\d+)?([eE][-+]?\d+)?$")


def _scrape(host: str, port: int, timeout: float = 10.0) -> str:
    url = f"http://{host}:{port}/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        ctype = resp.headers.get("Content-Type", "")
        assert "text/plain" in ctype, f"/metrics Content-Type {ctype!r}"
        return resp.read().decode()


def _prom_errors(text: str) -> List[str]:
    return [ln for ln in text.splitlines()
            if ln and not ln.startswith("#") and not _SAMPLE.match(ln)]


def _value(text: str, name: str, jid: Optional[str] = None
           ) -> Optional[float]:
    want = name + ('{job="%s"} ' % jid if jid else " ")
    for ln in text.splitlines():
        if ln.startswith(want):
            return float(ln.rsplit(" ", 1)[1])
    return None


def _summary_counts(timeline_text: str) -> dict:
    """The trailing machine-parseable line of `obs timeline`."""
    for ln in reversed(timeline_text.splitlines()):
        if ln.startswith("summary: "):
            return {k: int(v) for k, v in
                    (kv.split("=") for kv in ln[len("summary: "):]
                     .split())}
    raise AssertionError(f"no summary line in timeline output:\n"
                         f"{timeline_text[-500:]}")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.tracecheck",
        description="the make trace-check observability gate")
    ap.add_argument("--spool", default=None,
                    help="default: a fresh temp dir")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--slow-q", type=int, default=1500,
                    help="quantifier width of the slow job's guard "
                         "(scales its wall time; ~1500 -> ~5-10s)")
    ap.add_argument("--bound", type=int, default=20,
                    help="grid CONSTRAINT bound (must stay small "
                         "enough that the bounds fixpoint converges)")
    args = ap.parse_args(argv)

    from .obs.report import main as obs_main
    from .serve.daemon import ServeDaemon
    from .serve.protocol import ServeClient

    spool = args.spool or tempfile.mkdtemp(prefix="jaxmc_trace_check_")
    # hermetic durable state + the observability knobs under test:
    # device work in a spawned owner process (a third OS process for
    # the timeline), fast heartbeats so the slow job's ring carries
    # progress-stamped beats within the scrape window
    os.environ["JAXMC_SERVE_DEVICE_OWNER"] = "1"
    os.environ.setdefault("JAXMC_PROFILE_STORE",
                          os.path.join(spool, "profiles"))
    os.environ.setdefault("JAXMC_HEARTBEAT_EVERY", "2")

    spec_dir = os.path.join(spool, "specs")
    os.makedirs(spec_dir, exist_ok=True)
    slow_spec = os.path.join(spec_dir, "traceload.tla")
    with open(slow_spec, "w", encoding="utf-8") as fh:
        fh.write(_SLOW_SPEC.format(q=args.slow_q, bound=args.bound))
    with open(os.path.join(spec_dir, "traceload.cfg"), "w",
              encoding="utf-8") as fh:
        fh.write(_SLOW_CFG)
    jax_spec = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "specs", "constoy.tla")

    daemon_trace = os.path.join(spool, "daemon.trace.jsonl")
    daemon = ServeDaemon(spool, workers=2, trace=daemon_trace,
                         quiet=False).start()
    failures: List[str] = []
    try:
        client = ServeClient("127.0.0.1", daemon.port)
        slow_opts = {"backend": "interp", "workers": 2,
                     "progress_every": 2}

        # ---- 1+2: the slow job, scraped live --------------------------
        code, job = client.submit(slow_spec, None, slow_opts)
        assert code == 200, f"slow submit failed ({code}): {job}"
        jid = job["id"]
        est_samples: List[float] = []
        prom_errors: List[str] = []
        events_midrun = False
        deadline = time.time() + args.timeout
        while True:
            _, rec = client.job(jid)
            st = rec.get("status")
            text = _scrape("127.0.0.1", daemon.port)
            prom_errors.extend(_prom_errors(text))
            v = _value(text, "jaxmc_search_progress_est", jid)
            if v is not None and st == "running":
                est_samples.append(v)
            if not events_midrun and st == "running":
                ecode, ebody = client._request(
                    "GET", f"/jobs/{jid}/events")
                events_midrun = ecode == 200 and \
                    bool(ebody.get("events"))
            if st in ("done", "failed", "drained"):
                break
            if time.time() > deadline:
                raise AssertionError(
                    f"slow job still {st!r} after {args.timeout}s")
            time.sleep(0.4)
        assert st == "done", \
            f"slow job ended {st!r}: {rec.get('error')}"
        if prom_errors:
            failures.append(
                f"/metrics lines failed Prometheus parse: "
                f"{prom_errors[:3]}")
        if len(set(est_samples)) < 2 or \
                (est_samples and est_samples[-1] <= est_samples[0]):
            failures.append(
                f"per-job search.progress_est did not move mid-run "
                f"(samples: {est_samples[:8]}); slow the job down "
                f"with --slow-q")
        if not events_midrun:
            failures.append(
                "GET /jobs/<id>/events never answered mid-run")

        # ---- 3: warm resubmission — the warm counters must move -------
        code, wjob = client.submit(slow_spec, None, slow_opts)
        assert code == 200, f"warm submit failed ({code}): {wjob}"
        wrec = client.wait(wjob["id"], timeout=args.timeout)
        assert wrec["status"] == "done", \
            f"warm job ended {wrec['status']!r}: {wrec.get('error')}"
        text = _scrape("127.0.0.1", daemon.port)
        warm_hits = _value(text, "jaxmc_serve_warm_hits")
        submitted = _value(text, "jaxmc_serve_jobs_submitted")
        if not warm_hits:
            failures.append(
                f"serve.warm_hits did not move on the identical "
                f"resubmission (jaxmc_serve_warm_hits={warm_hits})")
        if not submitted or submitted < 2:
            failures.append(
                f"jaxmc_serve_jobs_submitted={submitted}, expected "
                f">= 2")
        if _value(text, "jaxmc_serve_queue_depth") is None:
            failures.append("jaxmc_serve_queue_depth missing from "
                            "/metrics")

        # ---- 4: one jax job through the device-owner process ----------
        code, ojob = client.submit(jax_spec, None, {
            "backend": "jax", "platform": "cpu", "resident": True,
            "no_trace": True})
        assert code == 200, f"owner submit failed ({code}): {ojob}"
        orec = client.wait(ojob["id"], timeout=args.timeout)
        assert orec["status"] == "done", \
            f"owner job ended {orec['status']!r}: {orec.get('error')}"

        # ---- 5: one timeline over every process's trace ---------------
        traces = [daemon_trace] + sorted(glob.glob(
            os.path.join(spool, "results", "*.trace.jsonl")))
        buf = io.StringIO()
        rc = obs_main(["timeline", "--fail-on-orphans"] + traces,
                      out=buf)
        tl = buf.getvalue()
        sys.stdout.write(tl)
        counts = _summary_counts(tl)
        if rc != 0 or counts.get("orphans", -1) != 0:
            failures.append(
                f"obs timeline found {counts.get('orphans')} orphan "
                f"spans (rc={rc}) — a trace-context hop broke")
        if counts.get("processes", 0) < 3:
            failures.append(
                f"timeline stitched only {counts.get('processes')} "
                f"distinct processes, expected >= 3 (daemon + fork "
                f"workers + device owner)")

        # ---- 6: cold -> warm regression gate --------------------------
        cold_path = daemon.q.result_path(jid)
        warm_path = daemon.q.result_path(wjob["id"])
        rc = obs_main(["diff", "--fail-on-regress", cold_path,
                       warm_path])
        if rc != 0:
            failures.append("obs diff flagged a cold->warm regression")

        for f in failures:
            print(f"trace-check: FAIL: {f}", file=sys.stderr)
        if not failures:
            print(f"trace-check: PASS — {counts['processes']} "
                  f"processes, {counts['events']} events, 0 orphan "
                  f"spans; progress_est moved "
                  f"{est_samples[0]:.3f} -> {est_samples[-1]:.3f} "
                  f"mid-run (spool: {spool})")
        return 1 if failures else 0
    finally:
        daemon.shutdown()


if __name__ == "__main__":
    sys.exit(main())
