r"""Multi-host (DCN) distributed BFS — SURVEY.md §2.3/§5 "distributed
communication backend".

The single-controller MeshExplorer shards over the devices of ONE
process. This module runs the SAME sharded level step (mesh.py
_get_mesh_step — compiled kernels, all_gather exchange, fp128
hash-partitioned seen shards, psum'd totals) over a mesh that spans
SEVERAL jax processes, the way a TPU pod spans hosts: each process
contributes its local devices, `jax.distributed.initialize` wires the
coordinator, and the collectives ride the inter-process transport (Gloo
on CPU here; ICI/DCN on real pods — the program is identical, which is
the point of jax's multi-controller model).

Multi-controller discipline: every process executes the same host loop;
device data lives in global arrays built with
`jax.make_array_from_callback`; the host reads ONLY replicated psum'd
scalars (via its own addressable shard). The frontier keeps a FIXED
per-device capacity (the step's out_cap variant) so no process ever
needs another host's rows between levels; outgrowing it aborts loudly
with a replicated flag.

Validated end to end on this box by dryrun_multihost
(__graft_entry__.py): 2 processes x 4 virtual CPU devices run the FULL
reference-raft MCraftMicro model to completion with the pinned counts
(6185 generated / 694 distinct), exercising the same code path a
multi-host pod would (VERDICT r3 #7; ROADMAP gap 6).
"""

from __future__ import annotations

import os
import sys
from typing import Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _local_scalar(arr) -> int:
    """Read a replicated (psum'd) per-device scalar from MY addressable
    shard — np.asarray(global_array) is illegal for non-addressable
    multi-process arrays."""
    import numpy as np
    return int(np.asarray(arr.addressable_shards[0].data).reshape(-1)[0])


def run_multihost_child(process_id: int, num_processes: int,
                        coordinator: str, local_devices: int = 4,
                        spec: str = None, cfg: str = None,
                        FC: int = 256, SC: int = 4096,
                        max_levels: int = 200) -> Tuple[int, int]:
    """One process of the multi-host run. MUST be called before any other
    jax initialization in the process. Returns (generated, distinct) —
    identical on every process (psum'd totals)."""
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags.strip() +
        f" --xla_force_host_platform_device_count={local_devices}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..sem.modules import Loader, bind_model
    from ..front.cfg import parse_cfg
    from .mesh import MeshExplorer

    devs = jax.devices()  # GLOBAL devices, across all processes
    D = len(devs)
    assert D == num_processes * local_devices, (D, num_processes)
    mesh = Mesh(np.array(devs), ("d",))

    spec = spec or os.path.join(_REPO, "specs", "MCraftMicro.tla")
    cfg = cfg or os.path.join(_REPO, "specs", "MCraft_micro.cfg")
    model = bind_model(
        Loader([os.path.dirname(spec),
                "/root/reference/examples"]).load_path(spec),
        parse_cfg(open(cfg).read()))

    # the compile pipeline is process-local and deterministic: both
    # processes build byte-identical kernels and step programs
    me = MeshExplorer(model, mesh=mesh, store_trace=False)
    W, K = me.W, me.K

    # init states: identical host computation on every process (the
    # shard construction is shared with MeshExplorer.run — one layout
    # rule for host and device dedup)
    from .bfs import filter_init_states
    init_rows = np.stack([me.layout.encode(st) for st in me.init_states])
    explored, viol = filter_init_states(model, me.layout, init_rows)
    assert viol is None, "initial-state violation in the dryrun model"
    seen_h, front_h, fcount_h = me._init_shards(
        init_rows, explored, D, SC, FC)

    def dist(h):
        sh = NamedSharding(mesh, P("d"))
        return jax.make_array_from_callback(
            h.shape, sh, lambda idx: h[idx])

    seen = dist(seen_h)
    frontier, fcount = dist(front_h), dist(fcount_h)

    generated = len(init_rows)
    distinct = len(explored)
    step = me._get_mesh_step(SC, FC, out_cap=FC)
    depth = 0
    while depth < max_levels:
        (seen, _seen_cnt, frontier, fcount, tot_gen, tot_new,
         any_ovf, tot_front, fixed_ovf, any_inv, any_dead,
         any_assert) = step(seen, frontier, fcount)
        if _local_scalar(any_ovf):
            raise RuntimeError("kernel capacity overflow in the "
                               "multi-host run")
        if _local_scalar(fixed_ovf):
            raise RuntimeError(
                f"fixed shard capacity exceeded (FC={FC}, SC={SC}): "
                f"raise them for this model")
        if _local_scalar(any_assert):
            raise RuntimeError("Assert violation in the dryrun model")
        if _local_scalar(any_inv):
            raise RuntimeError("invariant violation in the dryrun model")
        if model.check_deadlock and _local_scalar(any_dead):
            raise RuntimeError("deadlock in the dryrun model")
        generated += _local_scalar(tot_gen)
        distinct += _local_scalar(tot_new)
        depth += 1
        if _local_scalar(tot_front) == 0:
            return generated, distinct
    raise RuntimeError(f"did not converge in {max_levels} levels")


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--coordinator", default="localhost:29521")
    ap.add_argument("--local-devices", type=int, default=4)
    a = ap.parse_args()
    gen, dist_ = run_multihost_child(
        a.process_id, a.num_processes, a.coordinator, a.local_devices)
    print(f"MULTIHOST p{a.process_id}: {gen} generated / "
          f"{dist_} distinct", flush=True)


if __name__ == "__main__":
    main()
