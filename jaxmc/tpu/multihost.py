"""Compatibility shim: jaxmc.tpu.multihost moved to
jaxmc.backend.multihost (ISSUE 11).  `python -m jaxmc.tpu.multihost`
keeps working for existing drivers."""

import sys

from ..backend.multihost import (  # noqa: F401
    fmt_trace_line,
    main,
    run_multihost_child,
)

__all__ = ["fmt_trace_line", "main", "run_multihost_child"]

if __name__ == "__main__":
    sys.exit(main())
