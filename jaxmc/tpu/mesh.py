r"""Multi-chip BFS over a jax.sharding.Mesh (SURVEY.md §2.3, §5).

Frontier data-parallelism + fingerprint-space sharding: each device owns
(a) a shard of the frontier (expanded locally with the same compiled action
kernels as the single-chip path) and (b) a hash range of the seen-set.
Per level, every device expands its frontier shard, the candidate successors
are all_gather'd over the ICI axis, and each device keeps exactly the rows
whose row-hash lands in its range — the structural analogue of
ring-partitioned attention state for a model checker (SURVEY.md §5
"long-context" row). Dedup within a shard is the same exact lexicographic
sort as tpu/bfs.py; totals are psum'd.

The driver validates this path with N virtual CPU devices via
__graft_entry__.dryrun_multichip (no multi-chip hardware needed).
Collective-efficiency upgrades (hash-routed ppermute/all_to_all instead of
all_gather) are planned once profiling on real multi-chip hardware exists.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..sem.modules import Model
from ..sem.enumerate import enumerate_init
from ..engine.explore import CheckResult, Violation
from ..compile.ground import CompileError, build_layout, ground_actions
from ..compile.kernel import compile_action, compile_predicate
from .bfs import (SENTINEL, SYMMETRY_WARNING, _pow2_at_least,
                  filter_init_states)


def _row_hash(rows, xp=jnp):
    """Deterministic FNV-1a row hash for owner routing (uint32 lanes).
    xp=jnp on device, xp=np for host-side init-state routing — ONE
    implementation so the two can never diverge."""
    h = xp.full(rows.shape[:-1], 2166136261, xp.uint32)
    for i in range(rows.shape[-1]):
        h = (h ^ rows[..., i].astype(xp.uint32)) * xp.uint32(16777619)
    return h


class MeshExplorer:
    """BFS with the frontier and seen-set sharded across a device mesh."""

    def __init__(self, model: Model, mesh: Optional[Mesh] = None,
                 log: Callable[[str], None] = None,
                 max_states: Optional[int] = None,
                 progress_every: float = 30.0):
        self.model = model
        self.log = log or (lambda s: None)
        self.max_states = max_states
        self.progress_every = progress_every
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("d",))
        self.mesh = mesh
        self.D = mesh.devices.size

        base_ctx = model.ctx()
        self.init_states = enumerate_init(model.init, base_ctx, model.vars)
        self.layout = build_layout(model, self.init_states)
        self.actions = ground_actions(model)
        self.compiled = [compile_action(model, self.layout, ga)
                         for ga in self.actions]
        self.inv_fns = [(nm, compile_predicate(model, self.layout, ex))
                        for nm, ex in model.invariants]
        self.con_fns = [(nm, compile_predicate(model, self.layout, ex))
                        for nm, ex in model.constraints]
        if model.action_constraints:
            raise CompileError("action constraints not compiled yet")
        self.A = len(self.compiled)
        self.W = self.layout.width
        self._step_cache: Dict[Tuple[int, int], Callable] = {}

    def _get_step(self, SC: int, FC: int) -> Callable:
        """Per-device seen capacity SC, per-device frontier capacity FC."""
        key = (SC, FC)
        if key in self._step_cache:
            return self._step_cache[key]
        A, W, D = self.A, self.W, self.D
        acts = self.compiled
        inv_fns = self.inv_fns
        con_fns = self.con_fns

        def device_step(seen, frontier, fcount):
            # per-device blocks: seen [SC,W], frontier [FC,W], fcount [1]
            seen = seen.reshape(SC, W)
            frontier = frontier.reshape(FC, W)
            me = lax.axis_index("d")
            fvalid = jnp.arange(FC) < fcount[0]
            ens, aoks, succs = [], [], []
            for ca in acts:
                en, aok, succ = jax.vmap(ca.fn)(frontier)
                ens.append(en)
                aoks.append(aok)
                succs.append(succ)
            en = jnp.stack(ens)
            aok = jnp.stack(aoks)
            succ = jnp.stack(succs)
            valid = en & fvalid[None, :]
            assert_bad = jnp.any((~aok) & fvalid[None, :])
            dead_local = jnp.any(fvalid & ~jnp.any(en, axis=0))
            gen_local = jnp.sum(valid)

            C = A * FC
            cand = jnp.where(valid.reshape(C)[:, None],
                             succ.reshape(C, W), SENTINEL)
            # ICI exchange: gather all candidates, keep my hash range
            allc = lax.all_gather(cand, "d", tiled=True)     # [D*C, W]
            owner = (_row_hash(allc) % jnp.uint32(D)).astype(jnp.int32)
            mine = (owner == me) & (allc[:, 0] != SENTINEL)
            allc = jnp.where(mine[:, None], allc, SENTINEL)

            # exact dedup against my seen shard
            G = D * C
            rows_all = jnp.concatenate([seen, allc])
            flag = jnp.concatenate([jnp.zeros(SC, jnp.int32),
                                    jnp.ones(G, jnp.int32)])
            ops = tuple(rows_all[:, i] for i in range(W)) + (flag,)
            sorted_ = lax.sort(ops, num_keys=W + 1, is_stable=True)
            rows = jnp.stack(sorted_[:W], axis=1)
            sflag = sorted_[W]
            rvalid = rows[:, 0] != SENTINEL
            neq_prev = jnp.concatenate([
                jnp.array([True]), jnp.any(rows[1:] != rows[:-1], axis=1)])
            new = (sflag == 1) & rvalid & neq_prev
            new_count = jnp.sum(new)

            # hash skew can route up to G new rows to one device, so the
            # compacted buffers are G-sized — truncating to C would silently
            # drop states
            ops2 = ((1 - new.astype(jnp.int32)),) + \
                tuple(rows[:, i] for i in range(W))
            comp = lax.sort(ops2, num_keys=1, is_stable=True)
            new_rows = jnp.stack(comp[1:], axis=1)[:max(G, 1)]

            keep = ((sflag == 0) & rvalid) | new
            ops3 = ((1 - keep.astype(jnp.int32)),) + \
                tuple(rows[:, i] for i in range(W))
            comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
            seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
            seen_count2 = jnp.sum(keep)

            # constraints FIRST: violating states stay fingerprinted in the
            # seen shard but are discarded — not distinct, not checked, not
            # explored (TLC semantics, testout2:265)
            nvalid = jnp.arange(new_rows.shape[0]) < new_count
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows)
            ops4 = ((1 - explore.astype(jnp.int32)),) + \
                tuple(new_rows[:, i] for i in range(W))
            comp4 = lax.sort(ops4, num_keys=1, is_stable=True)
            front_rows = jnp.stack(comp4[1:], axis=1)[:max(G, 1)]
            front_count = jnp.sum(explore)
            frontvalid = jnp.arange(front_rows.shape[0]) < front_count
            inv_bad = jnp.asarray(False)
            for nm, f in inv_fns:
                inv_bad = inv_bad | jnp.any(frontvalid &
                                            ~jax.vmap(f)(front_rows))

            # global reductions over ICI
            tot_gen = lax.psum(gen_local, "d")
            tot_new = lax.psum(front_count, "d")
            any_dead = lax.psum(dead_local.astype(jnp.int32), "d") > 0
            any_assert = lax.psum(assert_bad.astype(jnp.int32), "d") > 0
            any_inv = lax.psum(inv_bad.astype(jnp.int32), "d") > 0
            tot_front = lax.psum(front_count, "d")

            return (seen2.reshape(1, SC, W), seen_count2.reshape(1),
                    front_rows.reshape(1, -1, W), front_count.reshape(1),
                    tot_gen.reshape(1), tot_new.reshape(1),
                    any_dead.reshape(1), any_assert.reshape(1),
                    any_inv.reshape(1), tot_front.reshape(1))

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map
        step = jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P("d"), P("d"), P("d")),
            out_specs=(P("d"), P("d"), P("d"), P("d"), P("d"), P("d"),
                       P("d"), P("d"), P("d"), P("d"))))
        self._step_cache[key] = step
        return step

    def run(self) -> CheckResult:
        t0 = time.time()
        model = self.model
        layout = self.layout
        D, W = self.D, self.W
        warnings = []
        if model.properties:
            warnings.append("temporal properties NOT checked (unimplemented)"
                            f": {', '.join(n for n, _ in model.properties)}")
        if model.symmetry is not None:
            warnings.append(SYMMETRY_WARNING)

        # encode + host-dedup init states, distribute by owner hash
        rows = {}
        for st in self.init_states:
            rows[layout.encode(st).tobytes()] = None
        init_rows = np.stack([np.frombuffer(k, dtype=np.int32)
                              for k in rows]) if rows \
            else np.zeros((0, W), np.int32)
        n_init = len(init_rows)
        generated = n_init

        explored_init, init_viol = filter_init_states(model, layout,
                                                      init_rows)
        if init_viol is not None:
            nm, st = init_viol
            return self._mk(False, len(explored_init) + 1, generated, 0,
                            t0, warnings, Violation(
                                "invariant", nm,
                                [(st, "Initial predicate")]))
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        distinct = int(explored_mask.sum())
        self.log(f"Finished computing initial states: {distinct} distinct "
                 f"state{'s' if distinct != 1 else ''} generated.")

        owner = (_row_hash(init_rows, xp=np) % np.uint32(D)).astype(np.int64)

        per_dev = [init_rows[(owner == d) & explored_mask]
                   for d in range(D)]
        seen_per_dev = [init_rows[owner == d] for d in range(D)]
        FC = _pow2_at_least(
            max(max((len(p) for p in per_dev), default=1), 1), lo=64)
        SC = _pow2_at_least(4 * FC, lo=256)

        frontier = np.full((D, FC, W), SENTINEL, np.int32)
        seen = np.full((D, SC, W), SENTINEL, np.int32)
        fcount = np.zeros((D,), np.int32)
        for d in range(D):
            p = per_dev[d]
            frontier[d, :len(p)] = p
            sp = seen_per_dev[d]
            if len(sp):
                order = np.lexsort(tuple(sp[:, i]
                                         for i in reversed(range(W))))
                seen[d, :len(sp)] = sp[order]
            fcount[d] = len(p)
        frontier = jnp.asarray(frontier)
        seen = jnp.asarray(seen)
        fcount = jnp.asarray(fcount)
        seen_counts = np.array([len(p) for p in seen_per_dev], np.int64)

        depth = 0
        last_progress = time.time()
        while int(np.sum(np.asarray(fcount))) > 0:
            C = self.A * FC
            if int(seen_counts.max(initial=0)) + D * C > SC:
                SC2 = _pow2_at_least(int(seen_counts.max(initial=0)) + D * C,
                                     SC)
                pad = jnp.full((D, SC2 - SC, W), SENTINEL, jnp.int32)
                seen = jnp.concatenate([seen, pad], axis=1)
                SC = SC2
            step = self._get_step(SC, FC)
            (seen, seen_cnt, front_rows, front_cnt, tot_gen, tot_new,
             any_dead, any_assert, any_inv, tot_front) = step(
                seen, frontier, fcount)

            if model.check_deadlock and bool(np.asarray(any_dead)[0]):
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "deadlock", "deadlock", [],
                                    "deadlock found (mesh backend has no "
                                    "trace reconstruction yet)"))
            if bool(np.asarray(any_assert)[0]):
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "assert", "Assert", [],
                                    "assertion violated (mesh backend has "
                                    "no trace reconstruction yet)"))

            generated += int(np.asarray(tot_gen)[0])
            new_total = int(np.asarray(tot_new)[0])
            distinct += new_total
            seen_counts = np.asarray(seen_cnt).astype(np.int64)

            if bool(np.asarray(any_inv)[0]):
                return self._mk(False, distinct, generated, depth + 1, t0,
                                warnings, Violation(
                                    "invariant", "invariant", [],
                                    "invariant violated (mesh backend has "
                                    "no trace reconstruction yet)"))
            depth += 1
            if self.max_states and distinct >= self.max_states:
                self.log("-- state limit reached, search truncated")
                return self._mk(True, distinct, generated, depth, t0,
                                warnings, truncated=True)

            # next frontier: per-device new rows, capacity = max new count
            fcount = front_cnt
            max_front = int(np.asarray(front_cnt).max(initial=0))
            if max_front > FC:
                FC = _pow2_at_least(max_front, FC)
                fr = np.asarray(front_rows)
                k = min(fr.shape[1], FC)
                nf = np.full((D, FC, W), SENTINEL, np.int32)
                nf[:, :k] = fr[:, :k]
                frontier = jnp.asarray(nf)
            else:
                frontier = front_rows[:, :FC]

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, "
                         f"{int(np.asarray(tot_front)[0])} on queue.")

        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct states "
                 f"found, 0 states left on queue.")
        return self._mk(True, distinct, generated, depth - 1, t0, warnings)

    def _mk(self, ok, distinct, generated, diameter, t0, warnings,
            violation=None, truncated=False):
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings)
