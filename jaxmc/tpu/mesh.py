r"""Multi-chip BFS over a jax.sharding.Mesh (SURVEY.md §2.3, §5).

Frontier data-parallelism + fingerprint-space sharding: each device owns
(a) a shard of the frontier, expanded with the SAME compiled kernels as
the single-chip path (compile/kernel2.py — wide layouts, slotted dynamic
\E, capacity buckets), and (b) a hash range of the seen-set, held as
128-bit fingerprints with an explicit validity lane (never in-band
sentinels — a valid state's lane can legitimately equal SENTINEL).
Per level, every device expands its frontier shard, the candidate rows and
their fingerprint keys are all_gather'd over the ICI axis, and each device
keeps exactly the rows whose fingerprint lands in its range — the
structural analogue of ring-partitioned attention state for a model
checker (SURVEY.md §5 "long-context" row). Dedup within a shard is the
same validity-lane-first lexicographic key sort as tpu/bfs.py; totals are
psum'd. CONSTRAINT-discarded states are fingerprinted but never counted,
checked, or explored (TLC semantics).

The driver validates this path with N virtual CPU devices via
__graft_entry__.dryrun_multichip (no multi-chip hardware needed).
Collective-efficiency upgrades (hash-routed ppermute/all_to_all instead of
all_gather) are planned once profiling on real multi-chip hardware exists.
Counterexample traces and refinement PROPERTYs are single-chip features
for now — the mesh reports their absence in warnings.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..sem.modules import Model
from ..engine.explore import CheckResult, Violation
from .bfs import (SENTINEL, SYMMETRY_WARNING, TpuExplorer, _pow2_at_least,
                  filter_init_states, fingerprint128)


class MeshExplorer(TpuExplorer):
    """BFS with the frontier and seen-set sharded across a device mesh.

    Shares TpuExplorer's whole compile pipeline (layout sampling, slotted
    kernels, compiled invariants/constraints); only the search loop is
    mesh-sharded. Dedup is always on 128-bit fingerprints (the key layout
    the seen shards store)."""

    def __init__(self, model: Model, mesh: Optional[Mesh] = None,
                 log: Callable[[str], None] = None,
                 max_states: Optional[int] = None,
                 progress_every: float = 30.0, **kw):
        super().__init__(model, log=log, max_states=max_states,
                         progress_every=progress_every,
                         store_trace=False, **kw)
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("d",))
        self.mesh = mesh
        self.D = mesh.devices.size
        # seen shards store fingerprint keys: force fp mode on any width
        self.fp_mode = True
        self.K = 4 + 1
        self._mesh_step_cache: Dict[Tuple[int, int], Callable] = {}

    # ---- the sharded level step ----
    def _get_mesh_step(self, SC: int, FC: int) -> Callable:
        key = (SC, FC)
        if key in self._mesh_step_cache:
            return self._mesh_step_cache[key]
        A, W, K, D = self.A, self.W, self.K, self.D
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns
        keys_of = self._keys_of
        expand = self._expand_fn()
        C = A * FC
        G = D * C

        def device_step(seen_keys, frontier, fcount):
            # per-device blocks: seen_keys [SC,K], frontier [FC,W], [1]
            seen_keys = seen_keys.reshape(SC, K)
            frontier = frontier.reshape(FC, W)
            me = lax.axis_index("d")
            fvalid = jnp.arange(FC) < fcount[0]
            en, aok, ov, succ = expand(frontier)
            valid = en & fvalid[None, :]
            assert_bad = jnp.any((~aok) & fvalid[None, :])
            overflow = jnp.any(ov & fvalid[None, :])
            dead_local = jnp.any(fvalid & ~jnp.any(en, axis=0))
            gen_local = jnp.sum(valid)

            cand = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            cand = jnp.where(cvalid[:, None], cand, SENTINEL)
            ckeys = keys_of(cand, cvalid)                 # [C, K]

            # ICI exchange: gather all candidates + keys, keep my range
            gcand = lax.all_gather(cand, "d", tiled=True)    # [G, W]
            gkeys = lax.all_gather(ckeys, "d", tiled=True)   # [G, K]
            gvalid = gkeys[:, 0] == 0     # explicit validity lane
            owner = (gkeys[:, 1].astype(jnp.uint32)
                     % jnp.uint32(D)).astype(jnp.int32)
            mine = gvalid & (owner == me)
            # foreign/invalid rows: validity lane 1 (sorts last), data
            # lanes sentinel so equal keys cannot straddle the mask
            gkeys = jnp.where(mine[:, None], gkeys,
                              jnp.concatenate([jnp.ones(1, jnp.int32),
                                               jnp.full(K - 1, SENTINEL,
                                                        jnp.int32)]))

            # merge-dedup against my seen shard (key sort; seen first at
            # equal keys via the flag tiebreaker)
            allk = jnp.concatenate([seen_keys, gkeys])    # [SC+G, K]
            flag = jnp.concatenate([jnp.zeros(SC, jnp.int32),
                                    jnp.ones(G, jnp.int32)])
            idx0 = jnp.arange(SC + G, dtype=jnp.int32)
            ops = tuple(allk[:, i] for i in range(K)) + (flag, idx0)
            sorted_ = lax.sort(ops, num_keys=K + 1, is_stable=True)
            skeys = jnp.stack(sorted_[:K], axis=1)
            sflag = sorted_[K]
            perm = sorted_[K + 1]
            cidx = perm - SC              # candidate position (<0: seen)
            rvalid = skeys[:, 0] == 0
            neq_prev = jnp.concatenate([
                jnp.array([True]),
                jnp.any(skeys[1:] != skeys[:-1], axis=1)])
            new = (sflag == 1) & rvalid & neq_prev
            new_count = jnp.sum(new)

            # compact the new rows (gather payload by sorted position)
            ops2 = ((1 - new.astype(jnp.int32)), cidx)
            comp = lax.sort(ops2, num_keys=1, is_stable=True)
            new_cidx = comp[1][:G]
            safe = jnp.clip(new_cidx, 0, G - 1)
            new_rows = jnp.take(gcand, safe, axis=0)
            nvalid = jnp.arange(G) < new_count
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)

            # merged seen keys, compacted (keeps key order)
            keep = ((sflag == 0) & rvalid) | new
            ops3 = ((1 - keep.astype(jnp.int32)),) + \
                tuple(skeys[:, i] for i in range(K))
            comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
            seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
            seen_count2 = jnp.sum(keep)

            # constraints FIRST: violating states stay fingerprinted in
            # the seen shard but are discarded — not distinct, not
            # checked, not explored (TLC semantics, testout2:265)
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows)
            idx4 = jnp.arange(G, dtype=jnp.int32)
            ops4 = ((1 - explore.astype(jnp.int32)), idx4)
            comp4 = lax.sort(ops4, num_keys=1, is_stable=True)
            front_rows = jnp.take(new_rows, comp4[1], axis=0)
            front_count = jnp.sum(explore)
            frontvalid = jnp.arange(G) < front_count
            inv_bad = jnp.asarray(False)
            for nm, f in inv_fns:
                inv_bad = inv_bad | jnp.any(frontvalid &
                                            ~jax.vmap(f)(front_rows))

            # global reductions over ICI
            tot_gen = lax.psum(gen_local, "d")
            tot_new = lax.psum(front_count, "d")
            any_dead = lax.psum(dead_local.astype(jnp.int32), "d") > 0
            any_assert = lax.psum(assert_bad.astype(jnp.int32), "d") > 0
            any_ovf = lax.psum(overflow.astype(jnp.int32), "d") > 0
            any_inv = lax.psum(inv_bad.astype(jnp.int32), "d") > 0
            tot_front = lax.psum(front_count, "d")

            return (seen2.reshape(1, SC, K), seen_count2.reshape(1),
                    front_rows.reshape(1, G, W), front_count.reshape(1),
                    tot_gen.reshape(1), tot_new.reshape(1),
                    any_dead.reshape(1), any_assert.reshape(1),
                    any_ovf.reshape(1), any_inv.reshape(1),
                    tot_front.reshape(1))

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map
        step = jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P("d"), P("d"), P("d")),
            out_specs=tuple([P("d")] * 11)))
        self._mesh_step_cache[key] = step
        return step

    def _owner_of(self, rows: np.ndarray) -> np.ndarray:
        """Host-side owner routing — the SAME fingerprint the device keys
        use (lane 1 of _keys_of == fingerprint128 word 0), so host and
        device can never disagree on ownership."""
        if not len(rows):
            return np.zeros(0, np.int64)
        fp = np.asarray(fingerprint128(jnp.asarray(rows)))
        return (fp[:, 0].astype(np.uint32) % np.uint32(self.D)) \
            .astype(np.int64)

    def run(self) -> CheckResult:
        t0 = time.time()
        model = self.model
        layout = self.layout
        D, W, K = self.D, self.W, self.K
        warnings = ["mesh backend: dedup on 128-bit fingerprints; "
                    "collision probability < n^2 * 2^-129; no "
                    "counterexample traces yet"]
        warnings.extend(self._temporal_warnings())
        if self.live_obligations:
            warnings.append(
                "temporal properties NOT checked on the mesh backend "
                "(single-chip --backend jax checks them): "
                + ", ".join(sorted({ob.prop_name
                                    for ob in self.live_obligations})))
        if self.refiners:
            warnings.append(
                "refinement properties NOT checked on the mesh backend "
                "(single-chip --backend jax checks them): "
                + ", ".join(rc.name for rc in self.refiners))
        warnings.extend(self._symmetry_warnings())

        rows = {}
        for st in self.init_states:
            rows[layout.encode(st).tobytes()] = None
        init_rows = np.stack([np.frombuffer(k, dtype=np.int32)
                              for k in rows]) if rows \
            else np.zeros((0, W), np.int32)
        n_init = len(init_rows)
        generated = n_init

        explored_init, init_viol = filter_init_states(model, layout,
                                                      init_rows)
        if init_viol is not None:
            nm, st = init_viol
            return self._mk(False, len(explored_init) + 1, generated, 0,
                            t0, warnings, Violation(
                                "invariant", nm,
                                [(st, "Initial predicate")]))
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        distinct = int(explored_mask.sum())
        self.log(f"Finished computing initial states: {distinct} distinct "
                 f"state{'s' if distinct != 1 else ''} generated.")

        owner = self._owner_of(init_rows)
        per_dev = [init_rows[(owner == d) & explored_mask]
                   for d in range(D)]
        seen_per_dev = [init_rows[owner == d] for d in range(D)]
        FC = _pow2_at_least(
            max(max((len(p) for p in per_dev), default=1), 1), lo=64)
        SC = _pow2_at_least(4 * FC, lo=256)

        frontier = np.full((D, FC, W), SENTINEL, np.int32)
        seen = np.full((D, SC, K), SENTINEL, np.int32)
        seen[:, :, 0] = 1  # empty slots: validity lane 1
        fcount = np.zeros((D,), np.int32)
        for d in range(D):
            p = per_dev[d]
            frontier[d, :len(p)] = p
            sp = seen_per_dev[d]
            if len(sp):
                k = np.asarray(self._keys_of(
                    jnp.asarray(sp), jnp.ones(len(sp), bool)))
                order = np.lexsort(tuple(k[:, i]
                                         for i in reversed(range(K))))
                seen[d, :len(sp)] = k[order]
            fcount[d] = len(p)
        frontier = jnp.asarray(frontier)
        seen = jnp.asarray(seen)
        fcount = jnp.asarray(fcount)
        seen_counts = np.array([len(p) for p in seen_per_dev], np.int64)

        depth = 0
        last_progress = time.time()
        while int(np.sum(np.asarray(fcount))) > 0:
            C = self.A * FC
            need = int(seen_counts.max(initial=0)) + D * C
            if need > SC:
                SC2 = _pow2_at_least(need, SC)
                pad = np.full((D, SC2 - SC, K), SENTINEL, np.int32)
                pad[:, :, 0] = 1
                seen = jnp.concatenate([seen, jnp.asarray(pad)], axis=1)
                SC = SC2
            step = self._get_mesh_step(SC, FC)
            (seen, seen_cnt, front_rows, front_cnt, tot_gen, tot_new,
             any_dead, any_assert, any_ovf, any_inv, tot_front) = step(
                seen, frontier, fcount)

            if bool(np.asarray(any_ovf)[0]):
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "error", "capacity overflow", [],
                                    "a container exceeded its lane "
                                    "capacity (raise --seq-cap/--grow-cap/"
                                    "--kv-cap); counts would no longer "
                                    "be exact"))
            if model.check_deadlock and bool(np.asarray(any_dead)[0]):
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "deadlock", "deadlock", [],
                                    "deadlock found (mesh backend has no "
                                    "trace reconstruction yet)"))
            if bool(np.asarray(any_assert)[0]):
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "assert", "Assert", [],
                                    "assertion violated (mesh backend has "
                                    "no trace reconstruction yet)"))

            generated += int(np.asarray(tot_gen)[0])
            distinct += int(np.asarray(tot_new)[0])
            seen_counts = np.asarray(seen_cnt).astype(np.int64)

            if bool(np.asarray(any_inv)[0]):
                return self._mk(False, distinct, generated, depth + 1, t0,
                                warnings, Violation(
                                    "invariant", "invariant", [],
                                    "invariant violated (mesh backend has "
                                    "no trace reconstruction yet)"))
            depth += 1
            if self.max_states and distinct >= self.max_states:
                self.log("-- state limit reached, search truncated")
                return self._mk(True, distinct, generated, depth, t0,
                                warnings, truncated=True)

            # next frontier: per-device kept rows; capacity grows to the
            # max shard (hash skew can route up to G rows to one device)
            fcount = front_cnt
            max_front = int(np.asarray(front_cnt).max(initial=0))
            if max_front > FC:
                FC = _pow2_at_least(max_front, FC)
                fr = np.asarray(front_rows)
                k = min(fr.shape[1], FC)
                nf = np.full((D, FC, W), SENTINEL, np.int32)
                nf[:, :k] = fr[:, :k]
                frontier = jnp.asarray(nf)
            else:
                frontier = front_rows[:, :FC]

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, "
                         f"{int(np.asarray(tot_front)[0])} on queue.")

        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct "
                 f"states found, 0 states left on queue.")
        return self._mk(True, distinct, generated, depth - 1, t0, warnings)

    def _mk(self, ok, distinct, generated, diameter, t0, warnings,
            violation=None, truncated=False):
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings)
