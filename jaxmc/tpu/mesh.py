"""Compatibility shim: jaxmc.tpu.mesh moved to jaxmc.backend.mesh
(ISSUE 11 — the engine layer is backend-portable now).  Import from
jaxmc.backend.mesh in new code."""

from ..backend.mesh import MeshExplorer  # noqa: F401

__all__ = ["MeshExplorer"]
