r"""Multi-chip BFS over a jax.sharding.Mesh (SURVEY.md §2.3, §5).

Frontier data-parallelism + fingerprint-space sharding: each device owns
(a) a shard of the frontier, expanded with the SAME compiled kernels as
the single-chip path (compile/kernel2.py — wide layouts, slotted dynamic
\E, capacity buckets), and (b) a hash range of the seen-set, held as
128-bit fingerprints with an explicit validity lane (never in-band
sentinels — a valid state's lane can legitimately equal SENTINEL).
Per level, every device expands its frontier shard, the candidate rows and
their fingerprint keys are all_gather'd over the ICI axis, and each device
keeps exactly the rows whose fingerprint lands in its range — the
structural analogue of ring-partitioned attention state for a model
checker (SURVEY.md §5 "long-context" row). A hash-routed
ppermute/all_to_all exchange (traffic ~C*gamma instead of C*D per device)
is the planned upgrade once profiled on real multi-chip hardware. Dedup within a shard is the same
validity-lane-first lexicographic key sort as tpu/bfs.py; totals are
psum'd. CONSTRAINT-discarded states are fingerprinted but never counted,
checked, or explored (TLC semantics).

Parity features (VERDICT r2 #5):
  * counterexample TRACES with action provenance: each kept new-frontier
    row carries its global candidate index off the device; the host keeps
    per-level (rows, provenance) so a violation replays the shortest path
    exactly like the single-chip level mode (store_trace=True, default);
  * NAMED violations: the step reports which invariant failed (index into
    the cfg INVARIANT list) plus the violating row; deadlock/assert
    report the offending state row the same way;
  * checkpoint/resume at level boundaries (--checkpoint/--resume), the
    TLC states/ equivalent, with full-run count exactness.

The driver validates this path with N virtual CPU devices via
__graft_entry__.dryrun_multichip (no multi-chip hardware needed) on the
raft workload. Refinement and temporal PROPERTYs check on the mesh too
(r4): the exchanged-candidate stream feeds the same host-side stepwise
refinement and behavior-graph liveness checkers as the single-chip
device modes (store_trace required; resume with PROPERTYs is rejected).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import obs
from ..sem.modules import Model
from ..engine.explore import CheckResult, Violation
from ..compile.vspec import ModeError
from ..compile.kernel2 import OV_DEMOTED, OV_PACK
from .bfs import (SENTINEL, TpuExplorer, _LiveGraph, _pow2_at_least,
                  filter_init_states, fingerprint128)

_BIG = np.int32(2 ** 31 - 1)


class MeshExplorer(TpuExplorer):
    """BFS with the frontier and seen-set sharded across a device mesh.

    Shares TpuExplorer's whole compile pipeline (layout sampling, slotted
    kernels, compiled invariants/constraints); only the search loop is
    mesh-sharded. Dedup is always on 128-bit fingerprints (the key layout
    the seen shards store)."""

    def __init__(self, model: Model, mesh: Optional[Mesh] = None,
                 log: Callable[[str], None] = None,
                 max_states: Optional[int] = None,
                 progress_every: float = 30.0, store_trace: bool = True,
                 exchange: str = "gather", **kw):
        super().__init__(model, log=log, max_states=max_states,
                         progress_every=progress_every,
                         store_trace=store_trace, **kw)
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("d",))
        self.mesh = mesh
        self.D = mesh.devices.size
        # seen shards store fingerprint keys: force fp mode on any width
        self.fp_mode = True
        self.K = 4 + 1
        # ICI exchange strategy (SURVEY.md §2.3 "communication
        # scheduling"): "gather" all_gathers every candidate to every
        # device (traffic C*D per device, no routing state); "a2a"
        # hash-routes each candidate straight to its owner via
        # all_to_all with per-peer buckets of B = C*gamma/D (traffic
        # C*gamma). Bucket overflow (hash skew beyond gamma) reruns the
        # level with gamma doubled.
        if exchange not in ("gather", "a2a"):
            raise ValueError(f"exchange must be 'gather' or 'a2a', "
                             f"got {exchange!r}")
        self.exchange = exchange
        self._a2a_gamma = 2.0
        self._mesh_step_cache: Dict[Tuple, Callable] = {}

    # ---- the sharded level step ----
    def _a2a_bucket(self, C: int, FC: int) -> int:
        import math
        # floor: R = D*B must cover the frontier capacity FC, or a
        # sparse no-overflow level could hand the next step a frontier
        # narrower than its compiled shape (review r3)
        return max(1, math.ceil(C * self._a2a_gamma / self.D),
                   math.ceil(FC / self.D))

    def _get_mesh_step(self, SC: int, FC: int,
                       out_cap: Optional[int] = None) -> Callable:
        """out_cap=None: the single-controller step (MeshExplorer.run —
        the host compacts/resizes the frontier between levels). out_cap
        set: the MULTI-HOST variant (tpu/multihost.py): the new frontier
        is cropped on device to a fixed [out_cap] shard so the host never
        needs non-addressable remote rows, and three extra REPLICATED
        flags (psum'd over the DCN+ICI axis) are appended to the outputs:
        any_inv (any device saw an invariant violation), fixed_ovf (a
        frontier/seen shard outgrew its fixed capacity, incl. a2a bucket
        overflow), any_dead, any_assert."""
        a2a = self.exchange == "a2a"
        B = self._a2a_bucket(self.A * FC, FC) if a2a else 0
        key = (SC, FC, B, out_cap)
        if key in self._mesh_step_cache:
            return self._mesh_step_cache[key]
        A, W, K, D = self.A, self.W, self.K, self.D
        PW = self.PW
        plan = self.plan
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns
        keys_of = self._keys_of
        expand = self._expand_fn()
        # refinement/temporal PROPERTYs: stream every exchanged
        # candidate (revisits included) to the host, which runs the SAME
        # stepwise refinement and behavior-graph checkers as the
        # single-chip device modes (r4; closes VERDICT r3 #9)
        need_edges = (out_cap is None and
                      (bool(self.refiners) or self.collect_edges))
        C = A * FC
        # R: rows each device holds after the exchange. gather: every
        # candidate from every device (D*C); a2a: my bucket from each
        # peer (D*B)
        G = D * C
        R = D * B if a2a else G
        Pw = K + PW + 1  # a2a payload: [keys | packed row | src-index]

        def device_step(seen_keys, frontier_p, fcount):
            # per-device blocks: seen_keys [SC,K], frontier [FC,PW], [1]
            seen_keys = seen_keys.reshape(SC, K)
            frontier = plan.unpack_rows(frontier_p.reshape(FC, PW))
            me = lax.axis_index("d")
            fvalid = jnp.arange(FC) < fcount[0]
            en, aok, ov, succ = expand(frontier)
            valid = en & fvalid[None, :]
            abad = (~aok) & fvalid[None, :]
            assert_bad = jnp.any(abad)
            # first (action, slot) whose enabled evaluation hit a failed
            # Assert — provenance for the assert trace
            aflat = jnp.argmax(abad.reshape(-1))
            asrt_a = (aflat // FC).astype(jnp.int32)
            asrt_f = (aflat % FC).astype(jnp.int32)
            # ov is the int overflow code (kernel2.OV_*); any nonzero
            # valid-row code aborts the mesh run. The MAX code is kept
            # (not just a flag) so the host can tell OV_DEMOTED — a
            # compile-recovery demotion, where raising caps cannot help —
            # from a real lane-capacity overflow
            overflow = jnp.max(jnp.where(fvalid[None, :], ov, 0)) \
                .astype(jnp.int32)
            dead = fvalid & ~jnp.any(en, axis=0)
            dead_local = jnp.any(dead)
            dead_slot = jnp.argmax(dead).astype(jnp.int32)
            gen_local = jnp.sum(valid)

            cand_u = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            cand_u = jnp.where(cvalid[:, None], cand_u, SENTINEL)
            ckeys, cand, pack_ovf = keys_of(cand_u, cvalid)  # [C, K/PW]
            # pack-guard overflow joins the overflow channel (OV_PACK);
            # kernel codes (OV_DEMOTED) keep priority
            overflow = jnp.where(
                overflow != 0, overflow,
                jnp.where(pack_ovf, OV_PACK, 0).astype(jnp.int32))

            invalid_key = jnp.concatenate(
                [jnp.ones(1, jnp.int32),
                 jnp.full(K - 1, SENTINEL, jnp.int32)])
            a2a_ovf = jnp.asarray(False)
            if a2a:
                # hash-route each candidate straight to its owner:
                # bucket-sort by destination, scatter into [D, B] slots,
                # one all_to_all. Traffic per device: D*B = C*gamma rows
                # instead of gather's C*D.
                dest = jnp.where(
                    cvalid,
                    (ckeys[:, 1].astype(jnp.uint32)
                     % jnp.uint32(D)).astype(jnp.int32),
                    D)
                sperm = lax.sort(
                    (dest, jnp.arange(C, dtype=jnp.int32)),
                    num_keys=1, is_stable=True)[1]
                sdest = jnp.take(dest, sperm)
                counts = jnp.zeros((D + 1,), jnp.int32).at[dest].add(1)
                excl = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     jnp.cumsum(counts)[:-1]])
                pos = jnp.arange(C, dtype=jnp.int32) -                     jnp.take(excl, sdest)
                a2a_ovf = jnp.any(counts[:D] > B)
                slot = jnp.where((sdest < D) & (pos < B),
                                 sdest * B + pos, D * B)
                srcid = me.astype(jnp.int32) * C + sperm
                payload = jnp.concatenate(
                    [jnp.take(ckeys, sperm, axis=0),
                     jnp.take(cand, sperm, axis=0),
                     srcid[:, None]], axis=1)          # [C, Pw]
                buckets = jnp.full((D * B + 1, Pw), SENTINEL, jnp.int32)
                buckets = buckets.at[:, 0].set(1)  # invalid slots
                buckets = buckets.at[slot].set(payload, mode="drop")
                recv = lax.all_to_all(
                    buckets[:D * B].reshape(D, B, Pw), "d",
                    split_axis=0, concat_axis=0).reshape(R, Pw)
                gkeys = recv[:, :K]
                gcand = recv[:, K:K + PW]
                gsrc = recv[:, K + PW]
                gvalid = gkeys[:, 0] == 0
                # routed rows are mine by construction; invalid slots
                # keep the sorts-last key shape
                gkeys = jnp.where(gvalid[:, None], gkeys, invalid_key)
            else:
                # ICI exchange: gather all candidates + keys, keep my
                # range
                gcand = lax.all_gather(cand, "d", tiled=True)  # [G, PW]
                gkeys = lax.all_gather(ckeys, "d", tiled=True)  # [G, K]
                gsrc = jnp.arange(R, dtype=jnp.int32)
                gvalid = gkeys[:, 0] == 0     # explicit validity lane
                owner = (gkeys[:, 1].astype(jnp.uint32)
                         % jnp.uint32(D)).astype(jnp.int32)
                mine = gvalid & (owner == me)
                # foreign/invalid rows: validity lane 1 (sorts last),
                # data lanes sentinel so equal keys cannot straddle the
                # mask
                gkeys = jnp.where(mine[:, None], gkeys, invalid_key)

            # merge-dedup against my seen shard (key sort; seen first at
            # equal keys via the flag tiebreaker)
            allk = jnp.concatenate([seen_keys, gkeys])    # [SC+R, K]
            flag = jnp.concatenate([jnp.zeros(SC, jnp.int32),
                                    jnp.ones(R, jnp.int32)])
            idx0 = jnp.arange(SC + R, dtype=jnp.int32)
            ops = tuple(allk[:, i] for i in range(K)) + (flag, idx0)
            sorted_ = lax.sort(ops, num_keys=K + 1, is_stable=True)
            skeys = jnp.stack(sorted_[:K], axis=1)
            sflag = sorted_[K]
            perm = sorted_[K + 1]
            cidx = perm - SC              # candidate position (<0: seen)
            rvalid = skeys[:, 0] == 0
            neq_prev = jnp.concatenate([
                jnp.array([True]),
                jnp.any(skeys[1:] != skeys[:-1], axis=1)])
            new = (sflag == 1) & rvalid & neq_prev
            new_count = jnp.sum(new)

            # compact the new rows (gather payload by sorted position);
            # new_src is each new row's GLOBAL candidate index (gsrc
            # lane) — the provenance the host needs for traces
            ops2 = ((1 - new.astype(jnp.int32)), cidx)
            comp = lax.sort(ops2, num_keys=1, is_stable=True)
            new_cidx = comp[1][:R]
            safe = jnp.clip(new_cidx, 0, R - 1)
            new_rows = jnp.take(gcand, safe, axis=0)
            new_src = jnp.take(gsrc, safe)
            nvalid = jnp.arange(R) < new_count
            new_rows = jnp.where(nvalid[:, None], new_rows, SENTINEL)

            # merged seen keys, compacted (keeps key order)
            keep = ((sflag == 0) & rvalid) | new
            ops3 = ((1 - keep.astype(jnp.int32)),) + \
                tuple(skeys[:, i] for i in range(K))
            comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
            seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
            seen_count2 = jnp.sum(keep)

            # constraints FIRST: violating states stay fingerprinted in
            # the seen shard but are discarded — not distinct, not
            # checked, not explored (TLC semantics, testout2:265)
            new_rows_u = plan.unpack_rows(new_rows) \
                if (con_fns or inv_fns) else new_rows
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows_u)
            idx4 = jnp.arange(R, dtype=jnp.int32)
            ops4 = ((1 - explore.astype(jnp.int32)), idx4)
            comp4 = lax.sort(ops4, num_keys=1, is_stable=True)
            front_rows = jnp.take(new_rows, comp4[1], axis=0)
            front_rows_u = jnp.take(new_rows_u, comp4[1], axis=0)
            # provenance follows the same two compactions
            front_src = jnp.take(new_src, comp4[1])
            front_count = jnp.sum(explore)
            frontvalid = jnp.arange(R) < front_count
            # named invariants: index of the FIRST cfg invariant any kept
            # row violates, plus the first violating slot
            inv_which = jnp.int32(_BIG)
            inv_slot = jnp.int32(-1)
            for i, (nm, f) in enumerate(inv_fns):
                bad = frontvalid & ~jax.vmap(f)(front_rows_u)
                anyb = jnp.any(bad)
                hit = anyb & (inv_which == _BIG)
                inv_which = jnp.where(hit, jnp.int32(i), inv_which)
                inv_slot = jnp.where(hit,
                                     jnp.argmax(bad).astype(jnp.int32),
                                     inv_slot)

            # global totals over ICI; violation flags stay PER-DEVICE so
            # the host can locate the offending device's row/provenance
            tot_gen = lax.psum(gen_local, "d")
            tot_new = lax.psum(front_count, "d")
            any_ovf = lax.pmax(overflow, "d")  # 0 = none, else max OV_*
            tot_front = lax.psum(front_count, "d")

            any_a2a_ovf = lax.psum(a2a_ovf.astype(jnp.int32), "d") > 0
            if out_cap is not None:
                # multi-host: fixed-capacity frontier shard + replicated
                # abort flags — the host loop reads ONLY replicated
                # scalars and its own addressable shards. a2a bucket
                # overflow folds into the fixed-capacity abort (the
                # multi-host loop cannot re-run a level, so it aborts
                # loudly instead of retrying with a larger gamma).
                fixed_ovf = lax.psum(
                    ((front_count > out_cap) | (seen_count2 > SC) |
                     a2a_ovf).astype(jnp.int32), "d") > 0
                any_inv = lax.psum(
                    (inv_which != _BIG).astype(jnp.int32), "d") > 0
                any_dead = lax.psum(
                    dead_local.astype(jnp.int32), "d") > 0
                any_assert = lax.psum(
                    assert_bad.astype(jnp.int32), "d") > 0
                # indices 0-11 are the r4 surface; 12+ add PER-DEVICE
                # provenance (each process reads only its own shards) so
                # the multi-host loop can assemble exact counterexample
                # traces via the process-allgather protocol
                # (multihost.py, VERDICT r4 #7)
                return (seen2.reshape(1, SC, K), seen_count2.reshape(1),
                        front_rows[:out_cap].reshape(1, out_cap, PW),
                        front_count.reshape(1),
                        tot_gen.reshape(1), tot_new.reshape(1),
                        any_ovf.reshape(1), tot_front.reshape(1),
                        fixed_ovf.reshape(1), any_inv.reshape(1),
                        any_dead.reshape(1), any_assert.reshape(1),
                        front_src[:out_cap].reshape(1, out_cap),
                        inv_which.reshape(1), inv_slot.reshape(1),
                        dead_local.reshape(1), dead_slot.reshape(1),
                        assert_bad.reshape(1), asrt_a.reshape(1),
                        asrt_f.reshape(1))
            out = (seen2.reshape(1, SC, K), seen_count2.reshape(1),
                   front_rows.reshape(1, R, PW), front_count.reshape(1),
                   front_src.reshape(1, R),
                   tot_gen.reshape(1), tot_new.reshape(1),
                   dead_local.reshape(1), dead_slot.reshape(1),
                   assert_bad.reshape(1), asrt_a.reshape(1),
                   asrt_f.reshape(1), any_ovf.reshape(1),
                   inv_which.reshape(1), inv_slot.reshape(1),
                   tot_front.reshape(1), any_a2a_ovf.reshape(1))
            if need_edges:
                # every exchanged candidate row + its explore mask +
                # global source index — the host-side edge stream.
                # gather mode: identical on every device (host reads
                # device 0); a2a: each device holds its own bucket.
                exp_all = gvalid
                gcand_u = plan.unpack_rows(gcand)
                for nm, f in con_fns:
                    exp_all = exp_all & jax.vmap(f)(gcand_u)
                out = out + (gcand.reshape(1, R, PW),
                             exp_all.reshape(1, R),
                             gsrc.reshape(1, R))
            return out

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map
        n_out = 20 if out_cap is not None else \
            (20 if need_edges else 17)
        step = jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P("d"), P("d"), P("d")),
            out_specs=tuple([P("d")] * n_out)))
        self._mesh_step_cache[key] = step
        return step

    def _init_shards(self, init_rows: np.ndarray, explored_idx,
                     D: int, SC: int, FC: int,
                     keys=None, packed=None, owner=None):
        """Host-side initial shard construction shared by the
        single-controller run() and the multi-host loop
        (tpu/multihost.py): per-owner frontier fill and lexsorted seen
        keys with the validity-lane-1 empty-slot convention. One layout
        rule, so host and device dedup can never diverge. Returns
        (seen [D,SC,K], frontier [D,FC,W], fcount [D]) as numpy."""
        K = self.K
        if keys is None:
            keys, packed, povf = self._host_keys(init_rows)
            if povf:
                from ..compile.vspec import CompileError
                raise CompileError(self._pack_ovf_msg())
            owner = self._owner_from_keys(keys)
        exp = np.zeros(len(init_rows), bool)
        exp[np.asarray(explored_idx, int)] = True
        frontier = np.full((D, FC, self.PW), SENTINEL, np.int32)
        seen = np.full((D, SC, K), SENTINEL, np.int32)
        seen[:, :, 0] = 1  # empty slots: validity lane 1
        fcount = np.zeros((D,), np.int32)
        for d in range(D):
            p = packed[(owner == d) & exp]
            frontier[d, :len(p)] = p
            k = keys[owner == d]
            if len(k):
                order = np.lexsort(tuple(k[:, i]
                                         for i in reversed(range(K))))
                seen[d, :len(k)] = k[order]
            fcount[d] = len(p)
        return seen, frontier, fcount

    def _owner_from_keys(self, keys: np.ndarray) -> np.ndarray:
        """THE ownership formula (keys lane 1 mod D) — one definition
        for every host path; device_step mirrors it in jnp."""
        return (keys[:, 1].astype(np.uint32) % np.uint32(self.D)) \
            .astype(np.int64)

    # ---- trace reconstruction (host side) ----
    #
    # self._levels[L] = (rows [D, cap_L, W] np, src [D, cap_L] np | None).
    # Level 0 holds the initial frontier (src None). For L >= 1, slot i on
    # device d holds global candidate index g = src[d][i]; with C_L =
    # A * FC_L (the expanding level's capacity): source device g // C_L,
    # candidate c = g % C_L, action c // FC_L, parent slot c % FC_L.

    def _mesh_trace_to(self, dev: int, slot: int, depth: int,
                       extra: Optional[Tuple[Dict, str]] = None):
        if not self.store_trace:
            return None
        out = []
        d, i = dev, slot
        for lvl in range(depth, -1, -1):
            rows, src, FC = self._levels[lvl]
            st = self.layout.decode_packed(np.asarray(rows[d][i]))
            if lvl == 0:
                out.append((st, "Initial predicate"))
            else:
                g = int(src[d][i])
                C = self.A * FC
                a = (g % C) // FC
                out.append((st, self.labels_flat[a]))
                d, i = g // C, (g % C) % FC
        out.reverse()
        if extra is not None:
            out.append(extra)
        return out

    def _mesh_refine_edges(self, frontier_np, ecand, eexp, esrc,
                           FC, depth):
        """Stepwise refinement over this level's explored candidate
        edges — the host runs the SAME checkers as the single-chip
        modes, with parents resolved through the global source index
        (g -> source device, action, frontier slot)."""
        C = self.A * FC
        idxs = np.nonzero(eexp)[0]
        if not len(idxs):
            return None
        parents: Dict[Tuple[int, int], dict] = {}
        if len(self._ref_pair_cache) > (1 << 20):
            self._ref_pair_cache.clear()
        for c in idxs:
            g = int(esrc[c])
            d_src, cc = g // C, g % C
            a, f = cc // FC, cc % FC
            key = (frontier_np[d_src, f].tobytes(), ecand[c].tobytes())
            if key in self._ref_pair_cache:
                continue
            self._ref_pair_cache.add(key)
            pst = parents.get((d_src, f))
            if pst is None:
                pst = self.layout.decode_packed(frontier_np[d_src, f])
                parents[(d_src, f)] = pst
            sst = self.layout.decode_packed(ecand[c])
            for rc in self.refiners:
                if not rc.check_edge(pst, sst):
                    trace = self._mesh_trace_to(
                        d_src, f, depth,
                        extra=(sst, self.labels_flat[a]))
                    return self._viol("property", rc.name, trace,
                                      self._refine_msg(rc))
        return None

    def _viol(self, kind, name, trace, msg=None):
        if trace is None:
            note = (f"{kind} found (mesh traces disabled by "
                    f"store_trace=False)")
            return Violation(kind, name, [], msg or note)
        return Violation(kind, name, trace, msg)

    # ---- checkpoint/resume (level boundaries) ----

    def _mesh_ck(self, seen, seen_counts, frontier, fcount, FC, SC,
                 depth, generated, distinct):
        self._write_ck(
            "mesh", D=self.D, FC=FC, SC=SC, depth=depth,
            generated=generated, distinct=distinct,
            seen=np.asarray(seen), seen_counts=np.asarray(seen_counts),
            frontier=np.asarray(frontier), fcount=np.asarray(fcount),
            levels=self._levels if self.store_trace else None)

    def run(self) -> CheckResult:
        t0 = time.time()
        tel = obs.current()
        model = self.model
        layout = self.layout
        D, W, K = self.D, self.W, self.K
        warnings = ["mesh backend: dedup on 128-bit fingerprints; "
                    "collision probability < n^2 * 2^-129"]
        warnings.extend(self._temporal_warnings())
        # the edge stream feeds refiners and non-[]P liveness; []P-only
        # obligations still need the behavior-graph STATES (per-level
        # kept rows), so the mode guards key on the wider condition
        need_edges = bool(self.refiners) or self.collect_edges
        need_props = bool(self.refiners) or bool(self.live_obligations)
        if need_props and not self.store_trace:
            raise ModeError(
                "mesh refinement/temporal checking needs the per-level "
                "row stream: run with store_trace=True (default)")
        if need_props and self.resume_from:
            raise ModeError(
                "mesh resume with refinement/temporal PROPERTYs is not "
                "supported - use the single-chip device modes")
        warnings.extend(self._symmetry_warnings())

        init_rows, explored_init, n_init, err = \
            self._prepare_init(t0, warnings)
        if err is not None:
            return err
        generated = n_init
        explored_mask = np.zeros(n_init, bool)
        explored_mask[explored_init] = True
        distinct = int(explored_mask.sum())

        self._levels: List[Tuple[np.ndarray, Optional[np.ndarray], int]] \
            = []
        graph = None   # behavior graph (temporal PROPERTYs)
        fsids = None   # flat (d*FC + slot) -> graph state id

        if self.resume_from:
            ck = self._load_ck("mesh")
            if ck["D"] != D:
                raise ValueError(
                    f"cannot resume: checkpoint has {ck['D']} devices, "
                    f"mesh has {D}")
            FC, SC = ck["FC"], ck["SC"]
            depth = ck["depth"]
            generated = ck["generated"]
            distinct = ck["distinct"]
            seen = jnp.asarray(ck["seen"])
            seen_counts = ck["seen_counts"].astype(np.int64)
            frontier = jnp.asarray(ck["frontier"])
            fcount = jnp.asarray(ck["fcount"])
            if ck.get("levels") is not None:
                self._levels = ck["levels"]
            elif self.store_trace:
                # advisor r3: match _restore_ck_state — a user expecting
                # traces must hear it up front, not get an empty-trace
                # violation later
                raise ValueError(
                    "cannot resume with traces: the checkpoint was "
                    "written with --no-trace")
            self.log(f"Resuming mesh run at depth {depth} "
                     f"({distinct} distinct states)")
        else:
            init_keys, init_packed, init_povf = \
                self._host_keys(init_rows)
            if init_povf:
                from ..compile.vspec import CompileError
                raise CompileError(self._pack_ovf_msg())
            owner = self._owner_from_keys(init_keys)
            per_dev = [init_rows[(owner == d) & explored_mask]
                       for d in range(D)]
            FC = _pow2_at_least(
                max(max((len(p) for p in per_dev), default=1), 1), lo=64)
            SC = _pow2_at_least(4 * FC, lo=256)
            explored_idx = np.nonzero(explored_mask)[0]
            seen, frontier, fcount = self._init_shards(
                init_rows, explored_idx, D, SC, FC,
                keys=init_keys, packed=init_packed, owner=owner)
            if self.live_obligations:
                graph = _LiveGraph(self.labels_flat, self.collect_edges)
                graph.add_inits(init_packed, explored_idx)
                # (d, slot) -> behavior-graph state id, flat [D*FC]
                fsids = np.full(D * FC, -1, np.int64)
                for d in range(D):
                    for i in range(int(fcount[d])):
                        fsids[d * FC + i] = graph.sid_by_key[
                            frontier[d, i].tobytes()]
            if self.store_trace:
                self._levels.append((frontier.copy(), None, FC))
            frontier = jnp.asarray(frontier)
            seen = jnp.asarray(seen)
            fcount = jnp.asarray(fcount)
            seen_counts = np.array([int((owner == d).sum())
                                    for d in range(D)], np.int64)
            depth = 0

        last_progress = last_ck = time.time()
        lvl_frontier = int(np.sum(np.asarray(fcount)))
        while lvl_frontier > 0:
            lvl_t0 = time.time()
            lvl_gen0 = generated
            C = self.A * FC
            need = int(seen_counts.max(initial=0)) + D * C
            if need > SC:
                SC2 = _pow2_at_least(need, SC)
                pad = np.full((D, SC2 - SC, K), SENTINEL, np.int32)
                pad[:, :, 0] = 1
                seen = jnp.concatenate([seen, jnp.asarray(pad)], axis=1)
                SC = SC2
            expanding_FC = FC
            while True:
                step = self._get_mesh_step(SC, FC)
                outs = step(seen, frontier, fcount)
                (seen2_, seen_cnt, front_rows, front_cnt, front_src,
                 tot_gen, tot_new, dead_local, dead_slot, assert_local,
                 asrt_a, asrt_f, any_ovf, inv_which, inv_slot,
                 tot_front, a2a_ovf) = outs[:17]
                if self.exchange == "a2a" and \
                        bool(np.asarray(a2a_ovf)[0]):
                    # hash skew exceeded the per-peer bucket: rerun the
                    # level with doubled capacity factor (inputs are
                    # untouched — the step is functional)
                    self._a2a_gamma *= 2
                    self.log(f"-- mesh: a2a bucket overflow, gamma -> "
                             f"{self._a2a_gamma}")
                    continue
                seen = seen2_
                break

            ovc = int(np.asarray(any_ovf)[0])
            if ovc:
                if ovc == OV_DEMOTED:
                    msg = ("a demoted compile-recovery fired (the "
                           "kernel under-approximates here): run the "
                           "host_seen mode, which demotes the arm to "
                           "the interpreter and restarts — raising "
                           "caps cannot help")
                elif ovc == OV_PACK:
                    msg = self._pack_ovf_msg()
                else:
                    msg = ("a container exceeded its lane capacity "
                           f"({self._caps_note()}); counts would no "
                           "longer be exact")
                return self._mk(False, distinct, generated, depth, t0,
                                warnings, Violation(
                                    "error", "capacity overflow", [],
                                    msg))
            dead_np = np.asarray(dead_local)
            if model.check_deadlock and dead_np.any():
                dv = int(np.argmax(dead_np))
                ds = int(np.asarray(dead_slot)[dv])
                trace = self._mesh_trace_to(dv, ds, depth)
                return self._mk(False, distinct, generated, depth, t0,
                                warnings,
                                self._viol("deadlock", "deadlock", trace))
            assert_np = np.asarray(assert_local)
            if assert_np.any():
                av = int(np.argmax(assert_np))
                aa = int(np.asarray(asrt_a)[av])
                af = int(np.asarray(asrt_f)[av])
                trace = self._mesh_trace_to(av, af, depth)
                return self._mk(
                    False, distinct, generated, depth, t0, warnings,
                    self._viol("assert", "Assert", trace,
                               f"assertion in {self.labels_flat[aa]}"))

            ecand = eexp = esrc = None
            if need_edges:
                # the exchanged candidate stream (revisits included):
                # gather mode replicates it on every device (read device
                # 0); a2a routes disjoint buckets (concatenate all)
                if self.exchange == "a2a":
                    ecand = np.asarray(outs[17]).reshape(-1, self.PW)
                    eexp = np.asarray(outs[18]).reshape(-1)
                    esrc = np.asarray(outs[19]).reshape(-1)
                else:
                    ecand = np.asarray(outs[17][0])
                    eexp = np.asarray(outs[18][0])
                    esrc = np.asarray(outs[19][0])
                if self.refiners:
                    fr_np = np.asarray(frontier)
                    rv = self._mesh_refine_edges(fr_np, ecand, eexp,
                                                 esrc, expanding_FC,
                                                 depth)
                    if rv is not None:
                        return self._mk(False, distinct, generated,
                                        depth, t0, warnings, rv)

            generated += int(np.asarray(tot_gen)[0])
            distinct += int(np.asarray(tot_new)[0])
            seen_counts = np.asarray(seen_cnt).astype(np.int64)
            tel.level(depth, frontier=lvl_frontier,
                      generated=generated - lvl_gen0,
                      new=int(np.asarray(tot_new)[0]), distinct=distinct,
                      seen=int(seen_counts.sum()), devices=D,
                      wall_s=round(time.time() - lvl_t0, 6))
            self._fp_occupancy = int(seen_counts.sum())
            max_front = int(np.asarray(front_cnt).max(initial=0))
            # device->host frontier copies only when something needs
            # them (tracing, a violation to localize, or FC regrowth):
            # in the perf configuration (store_trace=False, clean level)
            # the frontier never leaves the device
            iw = np.asarray(inv_which)
            which = int(iw.min())
            need_host_rows = (self.store_trace or max_front > FC or
                              which != _BIG or graph is not None)
            front_rows_np = np.asarray(front_rows) if need_host_rows \
                else None
            if self.store_trace:
                # trim to the occupied prefix: keeping full G = D*A*FC
                # capacity per level would hold the padded expansion of
                # the whole search in host RAM
                keep = max(max_front, 1)
                self._levels.append(
                    (front_rows_np[:, :keep],
                     np.asarray(front_src)[:, :keep], expanding_FC))

            sids_per_dev = None
            if graph is not None:
                # behavior-graph bookkeeping: kept new rows register with
                # provenance a*(D*FCprev) + (d_src*FCprev + f) so
                # labels_flat and the flat parent-sid table resolve them;
                # then every explored candidate edge (revisits included)
                front_src_np = np.asarray(front_src)
                fcnt_np = np.asarray(front_cnt)
                Cprev = self.A * expanding_FC
                flat_rows, flat_prov, row_counts = [], [], []
                for d in range(D):
                    n = int(fcnt_np[d])
                    row_counts.append(n)
                    for i in range(n):
                        g = int(front_src_np[d, i])
                        d_src, cc = g // Cprev, g % Cprev
                        a, f = cc // expanding_FC, cc % expanding_FC
                        flat_rows.append(front_rows_np[d, i])
                        flat_prov.append(
                            a * (D * expanding_FC)
                            + d_src * expanding_FC + f)
                new_sids = graph.add_level(
                    np.asarray(flat_rows) if flat_rows
                    else np.zeros((0, self.PW), np.int32),
                    np.asarray(flat_prov, np.int64),
                    D * expanding_FC, fsids)
                if graph.collect_edges and ecand is not None:
                    eidx = np.nonzero(eexp)[0]
                    epar = np.empty(len(eidx), np.int64)
                    for k, c in enumerate(eidx):
                        g = int(esrc[c])
                        d_src, cc = g // Cprev, g % Cprev
                        epar[k] = d_src * expanding_FC + cc % expanding_FC
                    graph.add_edges(ecand[eidx], epar, fsids)
                sids_per_dev = []
                off = 0
                for d in range(D):
                    sids_per_dev.append(new_sids[off:off + row_counts[d]])
                    off += row_counts[d]

            if which != _BIG:
                nm = self.inv_fns[which][0]
                iv_dev = int(np.argmax(iw == which))
                iv_slot = int(np.asarray(inv_slot)[iv_dev])
                trace = self._mesh_trace_to(iv_dev, iv_slot, depth + 1)
                return self._mk(False, distinct, generated, depth + 1, t0,
                                warnings,
                                self._viol("invariant", nm, trace))
            depth += 1

            # next frontier: per-device kept rows; capacity grows to the
            # max shard (hash skew can route up to G rows to one device)
            fcount = front_cnt
            if max_front > FC:
                FC = _pow2_at_least(max_front, FC)
                k = min(front_rows_np.shape[1], FC)
                nf = np.full((D, FC, self.PW), SENTINEL, np.int32)
                nf[:, :k] = front_rows_np[:, :k]
                frontier = jnp.asarray(nf)
            else:
                frontier = front_rows[:, :FC]
            if graph is not None:
                # flat sid table for the NEXT level's frontier slots
                # (kept-row order is preserved by the compactions above)
                fsids = np.full(D * FC, -1, np.int64)
                for d in range(D):
                    for i, sid in enumerate(sids_per_dev[d]):
                        fsids[d * FC + i] = sid

            if self.max_states and distinct >= self.max_states:
                # a truncation point IS a level boundary: leave a
                # checkpoint so the run can be resumed past the limit
                if self.checkpoint_path:
                    self._mesh_ck(seen, seen_counts, frontier, fcount,
                                  FC, SC, depth, generated, distinct)
                self.log("-- state limit reached, search truncated")
                return self._mk(True, distinct, generated, depth, t0,
                                warnings, truncated=True)

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} generated, "
                         f"{distinct} distinct, "
                         f"{int(np.asarray(tot_front)[0])} on queue.")
            if self.checkpoint_path and \
                    now - last_ck >= self.checkpoint_every:
                last_ck = now
                self._mesh_ck(seen, seen_counts, frontier, fcount, FC,
                              SC, depth, generated, distinct)
            lvl_frontier = int(np.sum(np.asarray(fcount)))

        if graph is not None:
            viol = self._check_live(graph, warnings)
            if viol is not None:
                return self._mk(False, distinct, generated, depth - 1,
                                t0, warnings, viol)
        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct "
                 f"states found, 0 states left on queue.")
        return self._mk(True, distinct, generated, depth - 1, t0, warnings)

    def _mk(self, ok, distinct, generated, diameter, t0, warnings,
            violation=None, truncated=False):
        tel = obs.current()
        tel.high_water("device.mem_high_water_bytes",
                       obs.device_mem_high_water())
        occ = getattr(self, "_fp_occupancy", None)
        if occ is not None:
            tel.gauge("fingerprint.occupancy", occ)
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings)
