"""Compatibility shim: jaxmc.tpu.bfs moved to jaxmc.backend.bfs
(ISSUE 11 — the engine layer is backend-portable now).  Import from
jaxmc.backend.bfs in new code."""

from ..backend.bfs import (  # noqa: F401
    FP_THRESHOLD,
    SENTINEL,
    SYMMETRY_WARNING,
    TpuExplorer,
    filter_init_states,
    fingerprint128,
    _LiveGraph,
    _lower_bound,
    _lsd_sort,
    _pow2_at_least,
    _rank_merge,
)

__all__ = ["FP_THRESHOLD", "SENTINEL", "SYMMETRY_WARNING",
           "TpuExplorer", "filter_init_states", "fingerprint128"]
