r"""Device-resident BFS engine (BACKEND=jax) — SURVEY.md §7.5.

The hot loop reconstructed in SURVEY.md §3.2, as array programs: the frontier
and the seen-set live on the accelerator as i32[cap, W] row matrices; one
jitted level step expands every (state x grounded action) pair with vmap,
masks disabled instances, and deduplicates EXACTLY by lexicographic
multi-key sort (jax.lax.sort over the W state lanes) — no fingerprint
collisions, unlike TLC's probabilistic hashing (testout2:261-264).

Capacities are power-of-two buckets that grow on demand, so jit recompiles
O(log N) times; all shapes inside a step are static (XLA/TPU requirement).
Parent provenance rides the sorts as a non-key operand and is streamed to
host per level for counterexample reconstruction — disable with
store_trace=False for benchmark runs.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..sem.modules import Model
from ..sem.enumerate import enumerate_init
from ..engine.explore import CheckResult, Violation
from ..compile.ground import (CompileError, StateLayout, build_layout,
                              ground_actions)
from ..compile.kernel import compile_action, compile_predicate

SENTINEL = np.int32(2**31 - 1)


def _pow2_at_least(n: int, lo: int = 256) -> int:
    c = lo
    while c < n:
        c *= 2
    return c


class TpuExplorer:
    def __init__(self, model: Model, log: Callable[[str], None] = None,
                 max_states: Optional[int] = None, store_trace: bool = True,
                 progress_every: float = 30.0):
        self.model = model
        self.log = log or (lambda s: None)
        self.max_states = max_states
        self.store_trace = store_trace
        self.progress_every = progress_every

        base_ctx = model.ctx()
        self.init_states = enumerate_init(model.init, base_ctx, model.vars)
        self.layout = build_layout(model, self.init_states)
        self.actions = ground_actions(model)
        self.compiled = [compile_action(model, self.layout, ga)
                         for ga in self.actions]
        self.inv_fns = [(nm, compile_predicate(model, self.layout, ex))
                        for nm, ex in model.invariants]
        self.constraint_fns = [(nm, compile_predicate(model, self.layout, ex))
                               for nm, ex in model.constraints]
        if model.action_constraints:
            raise CompileError("action constraints not compiled yet - "
                               "use the interp backend")
        self.A = len(self.compiled)
        self.W = self.layout.width
        self._step_cache: Dict[Tuple[int, int], Callable] = {}

    # ---- jitted level step, compiled per (seen_cap, frontier_cap) ----
    def _get_step(self, SC: int, FC: int) -> Callable:
        key = (SC, FC)
        if key in self._step_cache:
            return self._step_cache[key]
        A, W = self.A, self.W
        acts = self.compiled
        inv_fns = self.inv_fns
        con_fns = self.constraint_fns

        def expand(frontier):
            ens, aoks, succs = [], [], []
            for ca in acts:
                en, aok, succ = jax.vmap(ca.fn)(frontier)
                ens.append(en)
                aoks.append(aok)
                succs.append(succ)
            return (jnp.stack(ens), jnp.stack(aoks), jnp.stack(succs))

        @jax.jit
        def step(seen, frontier, fcount):
            fvalid = jnp.arange(FC) < fcount
            en, aok, succ = expand(frontier)          # [A,FC] [A,FC] [A,FC,W]
            valid = en & fvalid[None, :]
            assert_bad = (~aok) & fvalid[None, :]
            dead = fvalid & ~jnp.any(en, axis=0)
            gen = jnp.sum(valid)

            C = A * FC
            cand = succ.reshape(C, W)
            cvalid = valid.reshape(C)
            prov = jnp.arange(C, dtype=jnp.int32)
            cand = jnp.where(cvalid[:, None], cand, SENTINEL)

            allr = jnp.concatenate([seen, cand])       # [SC+C, W]
            flag = jnp.concatenate([
                jnp.zeros(SC, jnp.int32), jnp.ones(C, jnp.int32)])
            aprov = jnp.concatenate([
                jnp.full(SC, -1, jnp.int32), prov])
            ops = tuple(allr[:, i] for i in range(W)) + (flag, aprov)
            sorted_ = lax.sort(ops, num_keys=W + 1, is_stable=True)
            rows = jnp.stack(sorted_[:W], axis=1)
            sflag, sprov = sorted_[W], sorted_[W + 1]
            rvalid = rows[:, 0] != SENTINEL
            neq_prev = jnp.concatenate([
                jnp.array([True]),
                jnp.any(rows[1:] != rows[:-1], axis=1)])
            new = (sflag == 1) & rvalid & neq_prev
            new_count = jnp.sum(new)

            # compact new rows (and their provenance) to the front, keeping
            # lexicographic order (stable single-key sort)
            ops2 = ((1 - new.astype(jnp.int32)),) + \
                tuple(rows[:, i] for i in range(W)) + (sprov,)
            comp = lax.sort(ops2, num_keys=1, is_stable=True)
            new_rows = jnp.stack(comp[1:W + 1], axis=1)[:C]
            new_prov = comp[W + 1][:C]
            nvalid = jnp.arange(C) < new_count

            # merged seen-set, compacted and still sorted
            keep = ((sflag == 0) & rvalid) | new
            ops3 = ((1 - keep.astype(jnp.int32)),) + \
                tuple(rows[:, i] for i in range(W))
            comp3 = lax.sort(ops3, num_keys=1, is_stable=True)
            seen2 = jnp.stack(comp3[1:], axis=1)[:SC]
            seen_count2 = jnp.sum(keep)

            # invariants over the new distinct states
            inv_bad_any = jnp.asarray(False)
            inv_bad_idx = jnp.asarray(0, jnp.int32)
            inv_bad_which = jnp.asarray(-1, jnp.int32)
            for wi, (nm, f) in enumerate(inv_fns):
                ok = jax.vmap(f)(new_rows)
                bad = nvalid & ~ok
                any_ = jnp.any(bad)
                idx = jnp.argmax(bad)
                first = jnp.logical_and(any_, ~inv_bad_any)
                inv_bad_idx = jnp.where(first, idx, inv_bad_idx)
                inv_bad_which = jnp.where(first, wi, inv_bad_which)
                inv_bad_any = inv_bad_any | any_
            # constraints: violating states stay in seen but leave the search
            explore = nvalid
            for nm, f in con_fns:
                explore = explore & jax.vmap(f)(new_rows)
            explore_count = jnp.sum(explore)
            # push explored rows to the front for the next frontier
            ops4 = ((1 - explore.astype(jnp.int32)),) + \
                tuple(new_rows[:, i] for i in range(W)) + (new_prov,)
            comp4 = lax.sort(ops4, num_keys=1, is_stable=True)
            front_rows = jnp.stack(comp4[1:W + 1], axis=1)[:C]
            front_prov = comp4[W + 1][:C]

            return dict(gen=gen, dead=dead, assert_bad=assert_bad,
                        seen=seen2, seen_count=seen_count2,
                        new_rows=new_rows, new_prov=new_prov,
                        new_count=new_count,
                        front_rows=front_rows, front_prov=front_prov,
                        front_count=explore_count,
                        inv_bad_any=inv_bad_any, inv_bad_idx=inv_bad_idx,
                        inv_bad_which=inv_bad_which)

        self._step_cache[key] = step
        return step

    # ---- host-side search loop ----
    def run(self) -> CheckResult:
        t0 = time.time()
        model = self.model
        layout = self.layout
        W = self.W
        warnings = []
        if model.properties:
            names = ", ".join(n for n, _ in model.properties)
            warnings.append(
                f"temporal properties NOT checked (unimplemented): {names}")

        # initial states (dedup on host; tiny)
        rows = {}
        for st in self.init_states:
            rows[layout.encode(st).tobytes()] = st
        init_rows = np.stack([np.frombuffer(k, dtype=np.int32)
                              for k in rows.keys()]) \
            if rows else np.zeros((0, W), np.int32)
        n_init = len(init_rows)
        generated = n_init
        distinct = n_init
        self.log(f"Finished computing initial states: {n_init} distinct "
                 f"state{'s' if n_init != 1 else ''} generated.")

        # invariants + constraints on init states (host-side interpreter)
        from ..sem.eval import eval_expr, _bool
        explored_init = []
        for i, row in enumerate(init_rows):
            st = layout.decode(row)
            ctx = model.ctx(state=st)
            for nm, ex in model.invariants:
                if not _bool(eval_expr(ex, ctx), f"invariant {nm}"):
                    return self._mk_result(
                        False, distinct, generated, 0, t0, warnings,
                        Violation("invariant", nm,
                                  [(st, "Initial predicate")]))
            if all(_bool(eval_expr(ex, ctx), f"constraint {nm}")
                   for nm, ex in model.constraints):
                explored_init.append(i)

        # capacities
        FC = _pow2_at_least(max(n_init, 1))
        SC = _pow2_at_least(4 * max(n_init, 1))

        front_init = init_rows[explored_init] if n_init else init_rows
        n_front = len(front_init)
        frontier = np.full((FC, W), SENTINEL, np.int32)
        frontier[:n_front] = front_init
        frontier = jnp.asarray(frontier)
        fcount = n_front
        seen = np.full((SC, W), SENTINEL, np.int32)
        if n_init:
            order = np.lexsort(tuple(init_rows[:, i]
                                     for i in reversed(range(W))))
            seen[:n_init] = init_rows[order]
        seen = jnp.asarray(seen)
        seen_count = n_init

        # trace bookkeeping: per level (rows np, prov np, frontier_cap)
        trace_levels: List[Tuple[np.ndarray, Optional[np.ndarray], int]] = []
        trace_levels.append((np.asarray(init_rows), None, 0))
        frontier_maps: List[np.ndarray] = [np.asarray(explored_init,
                                                      dtype=np.int64)]

        depth = 0
        last_progress = time.time()
        while fcount > 0:
            # capacity management
            C = self.A * FC
            if seen_count + C > SC:
                SC2 = _pow2_at_least(seen_count + C, SC)
                pad = jnp.full((SC2 - SC, W), SENTINEL, jnp.int32)
                seen = jnp.concatenate([seen, pad])
                SC = SC2
            step = self._get_step(SC, FC)
            out = step(seen, frontier, fcount)

            # violations first (device->host sync points)
            if bool(jnp.any(out["assert_bad"])):
                ab = np.asarray(out["assert_bad"])
                a, f = np.unravel_index(np.argmax(ab), ab.shape)
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth, int(f))
                trace.append((None, self.actions[int(a)].label))
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("assert", "Assert",
                              [x for x in trace if x[0] is not None],
                              f"assertion in {self.actions[int(a)].label}"))
            if model.check_deadlock and bool(jnp.any(out["dead"])):
                f = int(jnp.argmax(out["dead"]))
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth, f)
                return self._mk_result(
                    False, distinct, generated, depth, t0, warnings,
                    Violation("deadlock", "deadlock", trace))

            new_count = int(out["new_count"])
            generated += int(out["gen"])
            distinct += new_count
            seen = out["seen"]
            seen_count = int(out["seen_count"])

            if self.store_trace:
                new_rows_h = np.asarray(out["new_rows"][:max(new_count, 1)])
                new_prov_h = np.asarray(out["new_prov"][:max(new_count, 1)])
                trace_levels.append(
                    (new_rows_h[:new_count], new_prov_h[:new_count], FC))
            if bool(out["inv_bad_any"]):
                idx = int(out["inv_bad_idx"])
                which = int(out["inv_bad_which"])
                nm = self.inv_fns[which][0]
                trace = self._trace_to(trace_levels, frontier_maps,
                                       depth + 1, idx, from_new=True)
                return self._mk_result(
                    False, distinct, generated, depth + 1, t0, warnings,
                    Violation("invariant", nm, trace))

            front_count = int(out["front_count"])
            if self.store_trace:
                # map frontier positions back to new_rows positions: the
                # frontier is the explore-compacted permutation of new rows;
                # recover by matching provenance
                fp = np.asarray(out["front_prov"][:max(front_count, 1)])
                npv = np.asarray(out["new_prov"][:max(new_count, 1)])
                pos = {int(p): i for i, p in enumerate(npv[:new_count])}
                frontier_maps.append(
                    np.asarray([pos[int(p)] for p in fp[:front_count]],
                               dtype=np.int64))
            depth += 1

            if self.max_states and distinct >= self.max_states:
                self.log("-- state limit reached, search truncated")
                return self._mk_result(True, distinct, generated, depth, t0,
                                       warnings, None, truncated=True)

            # next frontier
            if front_count > FC:
                FC = _pow2_at_least(front_count, FC)
            nf = jnp.full((FC, W), SENTINEL, jnp.int32)
            nf = nf.at[:min(front_count, FC)].set(
                out["front_rows"][:min(front_count, FC)])
            frontier = nf
            fcount = front_count

            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} states generated, "
                         f"{distinct} distinct states found, "
                         f"{fcount} states left on queue.")

        self.log("Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {distinct} distinct states "
                 f"found, 0 states left on queue.")
        self.log(f"The depth of the complete state graph search is "
                 f"{depth}.")
        return self._mk_result(True, distinct, generated, depth - 1, t0,
                               warnings)

    def _mk_result(self, ok, distinct, generated, diameter, t0, warnings,
                   violation=None, truncated=False) -> CheckResult:
        return CheckResult(ok=ok, distinct=distinct, generated=generated,
                           diameter=max(diameter, 0), violation=violation,
                           wall_s=time.time() - t0, truncated=truncated,
                           warnings=warnings)

    def _trace_to(self, trace_levels, frontier_maps, level: int, idx: int,
                  from_new: bool = False) -> List[Tuple[Dict, str]]:
        """Reconstruct the path to frontier index idx at `level` (or to
        new-row index idx when from_new)."""
        if not self.store_trace:
            return []
        out = []
        lvl = level
        cur = idx
        if not from_new and lvl < len(frontier_maps):
            cur = int(frontier_maps[lvl][cur])
        while lvl >= 0:
            rows, prov, par_FC = trace_levels[lvl]
            row = rows[cur]
            st = self.layout.decode(row)
            if prov is None:
                out.append((st, "Initial predicate"))
                break
            p = int(prov[cur])
            a, f = p // par_FC, p % par_FC
            out.append((st, self.actions[a].label))
            lvl -= 1
            cur = int(frontier_maps[lvl][f]) if lvl < len(frontier_maps) \
                else f
        out.reverse()
        return out
