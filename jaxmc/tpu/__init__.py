"""Compatibility shims: the engines moved to jaxmc/backend (ISSUE 11).

`jaxmc/tpu/` was a misnomer the moment the engines ran on cpu-XLA —
the device layer is now the backend-portable package jaxmc/backend
({bfs,mesh,multihost} parameterized over a BackendDescriptor).  These
modules re-export the public surface so existing imports keep working;
new code should import from jaxmc.backend.
"""
