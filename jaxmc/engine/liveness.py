r"""Temporal (liveness) property checking over the behavior graph.

TLC checks PROPERTY formulas with temporal operators against the full
reachable state graph plus fairness from the SPECIFICATION formula
(SURVEY.md §3.2 "liveness" row). This module covers the corpus's property
forms exactly:

  []P                      LiveHourClock.tla:27 TypeInvariance
  []<>P                    LiveHourClock.tla:22 AllTimes (\A-quantified)
  []<><<A>>_v              LiveHourClock.tla:17 AlwaysTick
  P ~> Q                   MCAlternatingBit.tla:11 SentLeadsToRcvd,
                           MCInnerSerial.tla AlwaysResponds (quantified)
  <>[]Q and [](P => <>[]Q) RealTime/MCRealTimeHourClock.tla:43
                           ErrorTemporal (an expected-to-fail property)
  WF_v(A) / SF_v(A)        fairness-as-property: MCLiveInternalMemory.cfg:7
                           PROPERTY Liveness (LiveInternalMemory.tla:17)
  disjunctions of []<>-class atoms
                           MCLiveWriteThroughCache.tla:129-143
                           LM_Inner_Liveness/Liveness2 ([]<>~EnabledX \/
                           []<><<X>>_v — the hand-instantiated ENABLED
                           construction), incl. the fairness half of a
                           spec-shaped PROPERTY (LM_Inner_LISpec, whose
                           Init/[][Next]_v half the refinement checker
                           covers stepwise)

with fairness WF_v(A) / SF_v(A), possibly \A-quantified or behind named
operators (AlternatingBit.tla:72-75 ABFairness).

Semantics. A behavior is an infinite path through the kept-state graph
where every state additionally has an implicit stuttering self-loop (TLC's
view: finite behaviors extend by stuttering). A property of the forms
above is violated iff some FAIR lasso (reachable cycle) avoids it:

  []<>G : a fair cycle with no G-state (or no G-edge for <<A>>_v)
  P ~> Q: a fair cycle inside the ~Q subgraph, reachable from a P/\~Q
          state through ~Q states
  <>[]Q : a fair cycle visiting a ~Q state
  [](P => <>[]Q): as <>[]Q but the cycle must be reachable from a P-state

A cycle through SCC S is fair iff for every WF(A,v): S has an <<A>>_v
edge, or some state of S has <<A>>_v disabled (an all-states closed walk
then passes it infinitely often, so A is not continuously enabled); for
every SF(A,v): S has an <<A>>_v edge, or NO state of S enables <<A>>_v —
otherwise the A-enabled states are deleted and the remaining sub-SCCs
searched (the standard refinement). Stuttering self-loops are never
<<A>>_v edges (v is unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from ..front import tla_ast as A
from ..sem.values import EvalError, fmt, tla_eq
from ..sem.eval import OpClosure, eval_expr, iter_binders, _bool
from ..sem.enumerate import Walker
from ..sem.modules import Model


class UnsupportedProperty(Exception):
    """The property is outside the supported temporal fragment."""


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

@dataclass
class Obligation:
    """One checkable temporal obligation (a conjunct of a PROPERTY, with
    any \\A binders already instantiated into `bound`).

    For kind 'ae_disj', exprs holds atom tuples instead of plain nodes:
    ('pred', P) for []<>P, ('action', A, v) for []<><<A>>_v, and
    ('WF'|'SF', A, v) for fairness-as-property — the obligation is the
    disjunction of the atoms, and its negation (the violation search) is
    the conjunction of the atoms' <>[]-style negations."""
    prop_name: str
    kind: str          # 'always' | 'ae' | 'ae_action' | 'leadsto' | 'ea'
    #                    | 'p_ea' | 'ae_disj'
    exprs: Tuple[Any, ...]
    bound: Dict[str, Any]

    def describe(self) -> str:
        b = ""
        if self.bound:
            b = " [" + ", ".join(f"{k} = {fmt(v)}"
                                 for k, v in sorted(self.bound.items())) + "]"
        return f"{self.prop_name}{b}"


def _chase(e: A.Node, model: Model, seen=None):
    """Resolve Ident/0-ary OpApp references to definition bodies."""
    seen = seen or set()
    while True:
        nm = None
        if isinstance(e, A.Ident):
            nm = e.name
        elif isinstance(e, A.OpApp) and not e.args and not e.path:
            nm = e.name
        if nm is None or nm in seen:
            return e
        d = model.defs.get(nm)
        if isinstance(d, OpClosure) and not d.params:
            seen.add(nm)
            e = d.body
            continue
        return e


def _op(e, name, nargs=None):
    return isinstance(e, A.OpApp) and e.name == name and \
        (nargs is None or len(e.args) == nargs)


def _ae_atom(e: A.Node, model: Model):
    """Recognize one []<>-class disjunct. Returns an atom tuple —
    ('pred', P) | ('action', A, v) | ('WF'|'SF', A, v) — or None."""
    e = _chase(e, model)
    if isinstance(e, A.Fair):
        return (e.kind, e.action, e.sub)
    if _op(e, "[]", 1):
        x = _chase(e.args[0], model)
        if _op(x, "<>", 1):
            y = _chase(x.args[0], model)
            if isinstance(y, A.AngleAction):
                return ("action", y.action, y.sub)
            if not _contains_temporal(y, model):
                return ("pred", y)
    return None


def classify_property(model: Model, prop_name: str, expr: A.Node,
                      bound: Dict[str, Any]) -> List[Obligation]:
    """Split a PROPERTY into obligations; raises UnsupportedProperty."""
    e = _chase(expr, model)
    if _op(e, "/\\", 2):
        return (classify_property(model, prop_name, e.args[0], bound) +
                classify_property(model, prop_name, e.args[1], bound))
    if isinstance(e, A.Quant) and e.kind == "A":
        out = []
        ctx = model.ctx().with_bound(bound)
        for b in iter_binders(e.binders, ctx, eval_expr):
            out.extend(classify_property(model, prop_name, e.body,
                                         {**bound, **b}))
        return out
    if isinstance(e, A.Fair):
        # WF_v(A) / SF_v(A) checked AS a property (MCLiveInternalMemory
        # PROPERTY Liveness): a one-atom disjunction
        return [Obligation(prop_name, "ae_disj",
                           ((e.kind, e.action, e.sub),), bound)]
    if _op(e, "\\/", 2):
        # disjunction of []<>-class atoms (LM_Inner_Liveness[2]'s
        # []<>~EnabledX \/ []<><<X>>_v construction)
        disj: List[A.Node] = []
        work = [e]
        while work:
            d = _chase(work.pop(), model)
            if _op(d, "\\/", 2):
                work.extend(d.args)
            else:
                disj.append(d)
        atoms = [_ae_atom(d, model) for d in disj]
        if all(a is not None for a in atoms):
            return [Obligation(prop_name, "ae_disj", tuple(atoms), bound)]
        raise UnsupportedProperty("disjunction outside the []<> fragment")
    if _op(e, "~>", 2):
        return [Obligation(prop_name, "leadsto",
                           (e.args[0], e.args[1]), bound)]
    if _op(e, "[]", 1):
        x = _chase(e.args[0], model)
        if _op(x, "<>", 1):
            y = _chase(x.args[0], model)
            if isinstance(y, A.AngleAction):
                return [Obligation(prop_name, "ae_action",
                                   (y.action, y.sub), bound)]
            return [Obligation(prop_name, "ae", (y,), bound)]
        if _op(x, "=>", 2):
            q = _chase(x.args[1], model)
            if _op(q, "<>", 1):
                q2 = _chase(q.args[0], model)
                if _op(q2, "[]", 1):
                    return [Obligation(prop_name, "p_ea",
                                       (x.args[0], q2.args[0]), bound)]
        if _contains_temporal(x, model):
            raise UnsupportedProperty(f"[] over unsupported formula")
        return [Obligation(prop_name, "always", (x,), bound)]
    if _op(e, "<>", 1):
        x = _chase(e.args[0], model)
        if _op(x, "[]", 1):
            return [Obligation(prop_name, "ea", (x.args[0],), bound)]
        raise UnsupportedProperty("bare <> property")
    raise UnsupportedProperty(f"unsupported temporal form")


def collect_obligations(model: Model, refiners
                        ) -> Tuple[List[Obligation], List[str], bool]:
    """Classify every cfg PROPERTY into temporal obligations — the shared
    policy of the interp and jax backends (verdict/warning parity).

    `refiners` is the list of RefinementCheckers already built for
    spec-shaped PROPERTYs (engine/refinement.py): their Init/[][Next]_v
    halves check stepwise, and their fairness conjuncts are classified
    HERE into temporal obligations (the fairness half of LM_Inner_LISpec,
    MCLiveWriteThroughCache.cfg:4). On success the checker's
    liveness_skipped flag is cleared so the "fairness conjuncts are NOT
    checked" warning disappears. Instance-path refinements (V!Spec) keep
    the warning: their fairness would need instance-entered evaluation.

    Returns (obligations, unsupported_names, collect_edges):
    unsupported_names excludes properties a refinement checker already
    covers; collect_edges is True iff some obligation needs the edge log
    (everything except bare '[]P')."""
    refined_names = {rc.name for rc in refiners}
    obligations: List[Obligation] = []
    unsupported: List[str] = []
    for pnm, pexpr in model.properties:
        try:
            obligations.extend(classify_property(model, pnm, pexpr, {}))
        except (UnsupportedProperty, EvalError):
            if pnm not in refined_names:
                unsupported.append(pnm)
    for rc in refiners:
        if not rc.fair or rc.instances:
            continue
        try:
            obs = []
            for f in rc.fair:
                obs.extend(classify_property(model, rc.name, f, {}))
        except (UnsupportedProperty, EvalError):
            continue  # keep liveness_skipped: warning stays honest
        obligations.extend(obs)
        rc.liveness_skipped = False
    collect_edges = any(ob.kind != "always" for ob in obligations)
    return obligations, unsupported, collect_edges


def _contains_temporal(e: A.Node, model: Model, depth=0) -> bool:
    if depth > 40:
        return True
    e = _chase(e, model)
    if isinstance(e, (A.BoxAction, A.AngleAction, A.Fair, A.TemporalQuant,
                      A.Enabled)):
        return True
    if isinstance(e, A.OpApp):
        if e.name in ("[]", "<>", "~>", "-+->"):
            return True
        return any(_contains_temporal(a, model, depth + 1) for a in e.args)
    if isinstance(e, A.Quant):
        return _contains_temporal(e.body, model, depth + 1)
    return False


@dataclass
class FairnessConstraint:
    kind: str          # 'WF' | 'SF'
    action: A.Node
    sub: A.Node
    bound: Dict[str, Any]

    def describe(self) -> str:
        return f"{self.kind}({fmt_node(self.action)})"


def fmt_node(e) -> str:
    return getattr(e, "name", type(e).__name__)


def extract_fairness(model: Model) -> Tuple[List[FairnessConstraint],
                                            List[str]]:
    """Flatten the SPECIFICATION's fairness conjuncts into WF/SF
    constraints; returns (constraints, warnings for unhandled forms)."""
    out: List[FairnessConstraint] = []
    warns: List[str] = []

    def walk(e, bound):
        e = _chase(e, model)
        if _op(e, "/\\", 2):
            walk(e.args[0], bound)
            walk(e.args[1], bound)
            return
        if isinstance(e, A.Quant) and e.kind == "A":
            ctx = model.ctx().with_bound(bound)
            for b in iter_binders(e.binders, ctx, eval_expr):
                walk(e.body, {**bound, **b})
            return
        if isinstance(e, A.Fair):
            out.append(FairnessConstraint(e.kind, e.action, e.sub, bound))
            return
        if _op(e, "=>", 2):
            # (guard) => WF(...) with a constant guard under the binders
            # (InnerSerial.tla:116 "(oi # oj) => WF_...")
            try:
                g = _bool(eval_expr(e.args[0],
                                    model.ctx().with_bound(bound)))
            except EvalError:
                warns.append("fairness conjunct with unevaluable guard: "
                             "liveness may pass vacuously")
                return
            if g:
                walk(e.args[1], bound)
            return
        warns.append(f"fairness conjunct not understood "
                     f"({type(e).__name__}): liveness may pass vacuously")

    for f in model.fairness:
        walk(f, {})
    return out, warns


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

class LivenessChecker:
    """Checks obligations over a completed search's behavior graph.

    states: list of kept states; edges: list of (src_sid, dst_sid);
    parents/labels: BFS tree for trace reconstruction."""

    def __init__(self, model: Model, states: List[Dict], edges,
                 parents, labels):
        self.model = model
        self.states = states
        self.edges = edges
        self.parents = parents
        self.labels = labels
        self.n = len(states)
        self.adj: List[List[int]] = [[] for _ in range(self.n)]
        for s, t in edges:
            self.adj[s].append(t)
        self.fair, self.warnings = extract_fairness(model)
        # per-constraint caches: successor sets (enabledness) and edge
        # classifications (relation evaluation)
        self._succ_cache: List[Dict[int, Set[int]]] = \
            [dict() for _ in self.fair]
        self._edge_cache: List[Dict[Tuple[int, int], bool]] = \
            [dict() for _ in self.fair]
        self._state_key = {}
        for i, st in enumerate(states):
            self._state_key[self._key(st)] = i

    def _key(self, st):
        # value-equality key (NOT repr: repr of equal frozensets is
        # insertion-order dependent) — all TLA values are hashable
        return tuple(st[v] for v in self.model.vars)

    # ---- fairness action evaluation ----

    def _action_succs(self, c: FairnessConstraint, cache: Dict,
                      sid: int) -> Set[int]:
        """Graph-node ids of <<A>>_v successors of state sid for the
        action/subscript in `c` (sub must change). Used for ENABLEDness
        only — edge classification is relational (_is_action_edge),
        because an abstract action (ABCorrectness's CRcvMsg checked as a
        fairness atom of PROPERTY ABCSpec) assigns only the mapped
        variables: its instances are completed with the current state's
        values for unassigned variables (the refinement leaves them
        existentially free; "unchanged" witnesses enabledness)."""
        hit = cache.get(sid)
        if hit is not None:
            return hit
        st = self.states[sid]
        ctx = self.model.ctx().with_bound(c.bound)
        out: Set[int] = set()
        try:
            v0 = eval_expr(c.sub,
                           self.model.ctx(state=st).with_bound(c.bound))
            w = Walker("next", tuple(self.model.vars), st)
            for partial, _lbl in w.walk(c.action, ctx, {}, None):
                succ = {**st, **partial}
                # <<A>>_v: the subscript must change
                v1 = eval_expr(c.sub, self.model.ctx(state=succ)
                               .with_bound(c.bound))
                if tla_eq(v0, v1):
                    continue
                tid = self._state_key.get(self._key(succ))
                out.add(tid if tid is not None else -1)
        except EvalError:
            # treat evaluation failure as "enabled, successors unknown":
            # conservative for WF/SF (cannot justify fairness from it)
            out = {-1}
        cache[sid] = out
        return out

    def _is_action_edge(self, c: FairnessConstraint, ecache: Dict,
                        s: int, t: int) -> bool:
        """Is graph edge (s, t) an <<A>>_v step? Evaluated RELATIONALLY —
        A as a boolean over (state, primes), like refinement's
        check_edge — so abstract actions that leave concrete variables
        unconstrained classify correctly (the concrete step may change
        them alongside the mapped ones). Evaluation failure counts as
        "not an A-step": fairness is then never justified by this edge
        (conservative, same direction as the enabledness fallback)."""
        key = (s, t)
        hit = ecache.get(key)
        if hit is not None:
            return hit
        try:
            ctx = self.model.ctx(state=self.states[s],
                                 primes=self.states[t]).with_bound(c.bound)
            ok = _bool(eval_expr(c.action, ctx), "fairness action")
            if ok:
                v0 = eval_expr(c.sub, self.model.ctx(
                    state=self.states[s]).with_bound(c.bound))
                v1 = eval_expr(c.sub, self.model.ctx(
                    state=self.states[t]).with_bound(c.bound))
                ok = not tla_eq(v0, v1)
        except EvalError:
            ok = False
        ecache[key] = ok
        return ok

    def _enabled(self, ci: int, sid: int) -> bool:
        return bool(self._action_succs(self.fair[ci],
                                       self._succ_cache[ci], sid))

    def _is_fair_edge(self, ci: int, s: int, t: int) -> bool:
        return self._is_action_edge(self.fair[ci], self._edge_cache[ci],
                                    s, t)

    # ---- SCC machinery ----

    def _sccs(self, nodes: Set[int], edge_ok=None) -> List[Set[int]]:
        """Tarjan over the subgraph induced by `nodes` and the real edges
        passing edge_ok (iterative). Stuttering self-loops are implicit —
        every returned singleton is still a cycle."""
        index = {}
        low = {}
        onstack = {}
        stack: List[int] = []
        out: List[Set[int]] = []
        counter = [0]
        for root in nodes:
            if root in index:
                continue
            work = [(root, 0)]
            while work:
                v, pi = work[-1]
                if pi == 0:
                    index[v] = low[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    onstack[v] = True
                recurse = False
                nbrs = [w for w in self.adj[v] if w in nodes
                        and (edge_ok is None or edge_ok(v, w))]
                for i in range(pi, len(nbrs)):
                    w = nbrs[i]
                    if w not in index:
                        work[-1] = (v, i + 1)
                        work.append((w, 0))
                        recurse = True
                        break
                    if onstack.get(w):
                        low[v] = min(low[v], index[w])
                if recurse:
                    continue
                if low[v] == index[v]:
                    scc = set()
                    while True:
                        w = stack.pop()
                        onstack[w] = False
                        scc.add(w)
                        if w == v:
                            break
                    out.append(scc)
                work.pop()
                if work:
                    u, _ = work[-1]
                    low[u] = min(low[u], low[v])
        return out

    def _scc_supports_fair_cycle(self, scc: Set[int], edge_ok=None,
                                 require: Optional[List[Set[int]]] = None
                                 ) -> Optional[Set[int]]:
        """A subset of scc through which a fair cycle runs, or None.
        edge_ok(s, t) additionally restricts usable real edges; when
        `require` is given the cycle must visit one state of EACH set (so
        SF refinement keeps searching sub-cores that still contain one).
        Every node has an implicit stuttering self-loop (usable, never an
        <<A>>_v step), so singleton SCCs are cycles too."""
        def inner_edges(S):
            for s in S:
                for t in self.adj[s]:
                    if t in S and (edge_ok is None or edge_ok(s, t)):
                        yield s, t

        S = set(scc)
        if not S:
            return None
        if require is not None and any(not (S & r) for r in require):
            return None
        for ci, c in enumerate(self.fair):
            has_edge = any(self._is_fair_edge(ci, s, t)
                           for s, t in inner_edges(S))
            if has_edge:
                continue
            en = {s for s in S if self._enabled(ci, s)}
            if not en:
                continue
            if c.kind == "WF":
                if len(en) == len(S):
                    return None  # A continuously enabled, never taken
                continue  # some state disables A: covering walk is fair
            # SF: must avoid A-enabled states entirely
            S2 = S - en
            for sub in self._sccs(S2, edge_ok):
                r = self._scc_supports_fair_cycle(sub, edge_ok, require)
                if r is not None:
                    return r
            return None
        return S

    # ---- reachability + traces ----

    def _reachable_within(self, starts: Set[int],
                          nodes: Set[int]) -> Set[int]:
        seen = set(s for s in starts if s in nodes)
        work = list(seen)
        while work:
            v = work.pop()
            for w in self.adj[v]:
                if w in nodes and w not in seen:
                    seen.add(w)
                    work.append(w)
        return seen

    def _trace_to(self, sid: int) -> List[Tuple[Dict, str]]:
        out = []
        cur = sid
        while cur is not None:
            out.append((self.states[cur], self.labels[cur]))
            cur = self.parents[cur]
        out.reverse()
        return out

    def _eval_pred(self, expr: A.Node, bound, sid: int) -> bool:
        ctx = self.model.ctx(state=self.states[sid]).with_bound(bound)
        return _bool(eval_expr(expr, ctx), "temporal sub-formula")

    # ---- obligation checking ----

    def check(self, obligations: List[Obligation]
              ) -> Tuple[Optional[Tuple[str, List, str]], List[str]]:
        """Returns ((prop_name, trace, message) | None, warnings).
        Obligations come pre-classified (engine/explore.py) so the caller
        controls the unsupported-form warnings."""
        for ob in obligations:
            bad = self._check_obligation(ob)
            if bad is not None:
                return bad, list(self.warnings)
        return None, list(self.warnings)

    def _check_obligation(self, ob: Obligation):
        allnodes = set(range(self.n))
        if ob.kind == "always":
            for sid in range(self.n):
                if not self._eval_pred(ob.exprs[0], ob.bound, sid):
                    return (ob.describe(), self._trace_to(sid),
                            "state violates the []-predicate")
            return None

        if ob.kind == "ae":
            # violation: fair cycle within ~P
            nodes = {s for s in allnodes
                     if not self._eval_pred(ob.exprs[0], ob.bound, s)}
            return self._lasso(ob, nodes, starts=nodes,
                               msg="a fair behavior eventually avoids the "
                                   "[]<> target forever")

        if ob.kind == "ae_action":
            # the checked action is NOT a fairness assumption — it only
            # classifies edges (the violating cycle must avoid A-steps)
            action, sub = ob.exprs
            c = FairnessConstraint("", action, sub, ob.bound)
            cache: Dict[Tuple[int, int], bool] = {}

            def edge_ok(s, t):
                return not self._is_action_edge(c, cache, s, t)
            return self._lasso(
                ob, allnodes, starts=allnodes, edge_ok=edge_ok,
                msg="a fair behavior takes the <<A>>_v action only "
                    "finitely often")

        if ob.kind == "leadsto":
            # evaluate lazily: the consequent only needs a value on states
            # reachable after the antecedent held (TLC-style laziness —
            # AlwaysResponds's opIdQ(oi) is out-of-domain on states where
            # oi never entered opId, and those states never matter)
            p, q = ob.exprs
            starts = set()
            for s in allnodes:
                try:
                    if not self._eval_pred(p, ob.bound, s):
                        continue
                except EvalError:
                    continue  # antecedent unevaluable: no obligation here
                if self._eval_pred(q, ob.bound, s):
                    continue  # satisfied immediately
                starts.add(s)
            notq = set(starts)
            work = list(starts)
            while work:
                v = work.pop()
                for w in self.adj[v]:
                    if w in notq:
                        continue
                    if not self._eval_pred(q, ob.bound, w):
                        notq.add(w)
                        work.append(w)
            return self._lasso(
                ob, notq, starts=starts,
                msg="after the ~> antecedent, a fair behavior never "
                    "reaches the consequent")

        if ob.kind == "ae_disj":
            # violation of  atom1 \/ atom2 \/ ...  =  a fair lasso whose
            # cycle satisfies EVERY atom's <>[]-negation:
            #   ('pred', P)      ~[]<>P        : cycle within ~P
            #   ('action', A, v) ~[]<><<A>>_v  : no <<A>>_v edge on cycle
            #   ('WF', A, v)     <>[]En /\ <>[]~taken :
            #                    cycle within ENABLED<<A>>_v, no A-edge
            #   ('SF', A, v)     []<>En /\ <>[]~taken :
            #                    cycle meets ENABLED<<A>>_v, no A-edge
            nodes = set(allnodes)
            acts: List[Tuple[FairnessConstraint, Dict]] = []
            requires: List[Set[int]] = []
            for atom in ob.exprs:
                if atom[0] == "pred":
                    nodes = {s for s in nodes
                             if not self._eval_pred(atom[1], ob.bound, s)}
                    continue
                c = FairnessConstraint("", atom[1], atom[2], ob.bound)
                en_cache: Dict[int, Set[int]] = {}
                acts.append((c, {}))
                if atom[0] == "WF":
                    nodes = {s for s in nodes
                             if self._action_succs(c, en_cache, s)}
                elif atom[0] == "SF":
                    requires.append(
                        {s for s in allnodes
                         if self._action_succs(c, en_cache, s)})

            def edge_ok(s, t):
                return all(not self._is_action_edge(c, ecache, s, t)
                           for c, ecache in acts)
            return self._lasso(
                ob, nodes, starts=nodes,
                edge_ok=edge_ok if acts else None, require=requires,
                msg="a fair behavior violates every disjunct: each []<> "
                    "target (or fairness atom) fails from some point on")

        if ob.kind in ("ea", "p_ea"):
            if ob.kind == "p_ea":
                p, q = ob.exprs
                starts = {s for s in allnodes
                          if self._eval_pred(p, ob.bound, s)}
            else:
                q, = ob.exprs
                starts = allnodes
            reach = self._reachable_within(starts, allnodes)
            notq = {s for s in reach
                    if not self._eval_pred(q, ob.bound, s)}
            # fair cycle (within reach) visiting a ~Q state
            for scc in self._sccs(reach):
                if not (scc & notq):
                    continue
                core = self._scc_supports_fair_cycle(scc, require=[notq])
                if core is not None:
                    ent = min(core & notq)
                    return (ob.describe(), self._trace_to(ent),
                            "a fair behavior violates <>[] (the negated "
                            "state recurs forever after this point)")
            return None

        raise AssertionError(ob.kind)

    def _lasso(self, ob: Obligation, nodes: Set[int], starts: Set[int],
               msg: str, edge_ok=None, require=None):
        """Fair cycle within `nodes`, reachable (inside `nodes`) from
        `starts`, meeting each `require` set — the generic violation
        search."""
        reach = self._reachable_within(starts, nodes)
        for scc in self._sccs(reach, edge_ok):
            core = self._scc_supports_fair_cycle(scc, edge_ok,
                                                 require or None)
            if core is not None:
                ent = min(core)
                return (ob.describe(), self._trace_to(ent), msg)
        return None


