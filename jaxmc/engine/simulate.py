r"""Random-walk simulation (TLC's -simulate mode) and deep state sampling.

Two uses: (a) a CLI `simulate` subcommand checking invariants along random
behaviors without exhaustive search, (b) the layout sampler for the TPU
backend — raft's interesting structures (leaders, log entries, elections)
appear many levels deep, so shape inference mixes a BFS prefix with long
random walks (compile/vspec.py docstring).
"""

from __future__ import annotations

import random
from collections import deque
from typing import Dict, List

from ..sem.eval import TLCAssertFailure, eval_expr, _bool
from ..sem.enumerate import enumerate_init, enumerate_next, label_str
from ..sem.modules import Model
from .explore import Violation


def random_walks(model: Model, n_walks: int, depth: int,
                 seed: int = 0, collect=None,
                 check_invariants: bool = False,
                 coverage_guided: bool = False,
                 check_deadlock: bool = False):
    """Run random behaviors; returns a Violation or None. collect(state)
    is called on every visited state when given.

    coverage_guided biases successor choice toward action labels taken
    least often so far — plain uniform walks essentially never complete a
    raft election (Timeout keeps winning), while novelty-weighted walks
    reach leaders, log entries, and elections quickly."""
    rng = random.Random(seed)
    ctx = model.ctx()
    inits = enumerate_init(model.init, ctx, model.vars)
    if not inits:
        raise EvalError("no initial states satisfy the initial predicate")
    if check_invariants:
        for st in inits:
            ictx = model.ctx(state=st)
            for nm, expr in model.invariants:
                if not _bool(eval_expr(expr, ictx), f"invariant {nm}"):
                    return Violation("invariant", nm,
                                     [(st, "Initial predicate")])
    label_counts: Dict[str, int] = {}
    for w in range(n_walks):
        st = rng.choice(inits)
        trace = [(st, "Initial predicate")]
        if collect:
            collect(st)
        for _ in range(depth):
            try:
                succs = list(enumerate_next(model.next, ctx, model.vars, st))
            except TLCAssertFailure as ex:
                return Violation("assert", "Assert", trace, str(ex.out))
            if not succs:
                if check_deadlock:
                    return Violation("deadlock", "deadlock", trace)
                break
            if coverage_guided:
                # weight by action-family novelty (label name sans args)
                weights = []
                for _, lbl in succs:
                    fam = (lbl[0] if lbl else "?")
                    c = label_counts.get(fam, 0)
                    weights.append(1.0 / (1 + c) ** 2)
                st, label = rng.choices(succs, weights=weights, k=1)[0]
                fam = (label[0] if label else "?")
                label_counts[fam] = label_counts.get(fam, 0) + 1
            else:
                st, label = rng.choice(succs)
            trace.append((st, label_str(label)))
            if collect:
                collect(st)
            if check_invariants:
                ictx = model.ctx(state=st)
                for nm, expr in model.invariants:
                    if not _bool(eval_expr(expr, ictx), f"invariant {nm}"):
                        return Violation("invariant", nm, trace)
    return None


def sample_states(model: Model, bfs_states: int = 1500,
                  n_walks: int = 60, walk_depth: int = 60,
                  seed: int = 0) -> List[Dict]:
    """States for layout inference: BFS prefix (covers the breadth of early
    actions) + random walks (cover depth: leaders, full logs, elections).

    Constraint-violating states are excluded: the checker discards them
    (TLC semantics), so including them would size container capacities for
    a space the search never explores — on raft, sampling without the cfg
    CONSTRAINT grows the message table to the full potential message
    universe and the compiled kernels with it. The encoder's overflow
    guard still aborts exactly if a real run outgrows the inferred caps
    (one frontier step can exceed the constrained envelope; the sizing
    margin covers it)."""
    from ..sem.modules import satisfies_constraints
    ctx = model.ctx()

    def in_bounds(st):
        return satisfies_constraints(model, st)

    inits = enumerate_init(model.init, ctx, model.vars)
    states = [st for st in inits if in_bounds(st)]
    # ALL inits are sampled (discarded ones are still fingerprinted, so
    # the layout must encode them); only kept inits seed the expansion
    out = list(inits)

    def key(s):
        return tuple(sorted((k, repr(v)) for k, v in s.items()))

    seen = {key(s) for s in out}
    q = deque(states)
    while q and len(out) < bfs_states:
        st = q.popleft()
        try:
            succs = enumerate_next(model.next, ctx, model.vars, st)
            for succ, _ in succs:
                k = key(succ)
                if k not in seen and in_bounds(succ):
                    seen.add(k)
                    out.append(succ)
                    q.append(succ)
        except TLCAssertFailure:
            continue

    # coverage-guided walks with novelty restarts: whenever a walk first
    # takes a new action family, the resulting state seeds later walks —
    # deep structures (a raft leader's ClientRequest) are reached by
    # continuing from the rare prefix instead of re-finding it
    rng = random.Random(seed)
    label_counts: Dict[str, int] = {}
    novel_starts: List[Dict] = []

    def collect(st):
        k = key(st)
        if k not in seen and in_bounds(st):
            seen.add(k)
            out.append(st)

    starts = states
    if not starts:
        return out  # no constraint-satisfying init: nothing to walk
    for w in range(n_walks):
        pool = starts + novel_starts
        st = rng.choice(pool)
        for _ in range(walk_depth):
            try:
                succs = [sl for sl in
                         enumerate_next(model.next, ctx, model.vars, st)
                         if in_bounds(sl[0])]
            except TLCAssertFailure:
                break
            if not succs:
                break
            weights = []
            for _, lbl in succs:
                fam = lbl[0] if lbl else "?"
                weights.append(1.0 / (1 + label_counts.get(fam, 0)) ** 2)
            st, label = rng.choices(succs, weights=weights, k=1)[0]
            fam = label[0] if label else "?"
            first = fam not in label_counts
            label_counts[fam] = label_counts.get(fam, 0) + 1
            collect(st)
            if first:
                novel_starts.append(st)
    return out
