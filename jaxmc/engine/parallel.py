r"""Parallel exact BFS engine: worker-pool frontier expansion.

TLC gets its throughput from worker-parallel frontier expansion (Yu,
Manolios & Lamport, CHARME 1999); jaxmc's exact oracle path was pinned to
one core. This engine is the same idea adapted to the Python interpreter:

- level-synchronous BFS: the frontier at depth d is split into chunks and
  farmed to a `multiprocessing` fork pool; workers run the expensive pure
  work per successor — `enumerate_next`, action/state CONSTRAINTs,
  SYMMETRY canonicalization / VIEW fingerprints, invariants — against the
  model they inherited at fork time (no per-task model pickling);
- the PARENT REPLAYS the merge through the single `seen` dict in exact
  frontier order at the level barrier, running the byte-level algorithm
  of the serial engine (engine/explore.py) with the expensive evaluations
  precomputed.  `generated`/`distinct`/`diameter`, violation traces, and
  truncation points are therefore BIT-IDENTICAL to the serial engine on
  every path, including mid-level violations: the replay consumes worker
  records in the same order the serial loop would have produced them and
  stops at the same record.

Dedup/merge correctness notes:
- workers never see the global `seen` set; every successor's fingerprint
  key rides back with the record and the parent's dedup decides.  A
  record's constraint/invariant verdicts describe the record's CONCRETE
  successor and are consulted only when its key is globally new — for a
  duplicate key the parent uses the stored verdict, exactly like the
  serial engine (matters under SYMMETRY, where two concrete states share
  one canonical key);
- within one chunk, repeats of an already-emitted key are sent as slim
  (key-only) records to bound pickle volume; chunks merge in submission
  order, so the full record always precedes its slim repeats.

Known (documented) divergence from serial: `CheckResult.prints` — worker
expansion collects a state's Print output as one batch, so on violation
paths prints from the violating state's expansion may include output the
serial engine would have cut off mid-state; print ORDER within a state
interleaves invariant-eval prints after expansion prints.  Counts, logs,
traces and verdicts are unaffected (the CLI does not render prints).

Crash safety (ISSUE 4): the engine owns its worker pool (`_WorkerPool`,
a context-managed set of fork processes around two queues) instead of
`multiprocessing.Pool`, BECAUSE Pool loses the task a dead worker held
and wedges the imap iterator.  Workers announce each chunk before
expanding it, so when a worker dies (OOM kill, fault injection) the
parent knows exactly which chunks were in flight: it drains completed
results, tears the pool down, respawns it (shrunk after repeat deaths),
and requeues the unmerged chunks with a bounded per-chunk retry budget
(JAXMC_PARALLEL_RETRIES, default 2) and backoff.  A chunk that raises a
transient error is retried INLINE in the parent at its merge point —
chunks are pure, and the parent replay keeps the slim-record invariant.
Only when a chunk's retries are exhausted (or the pool cannot respawn)
does the run degrade to serial expansion for the remainder, recorded as
the `parallel.degraded` gauge/event.  Counts stay bit-identical to the
serial engine through every recovery: chunks always MERGE in submission
order, and re-executed chunks produce the same records (full records
where the dead worker would have sent slim repeats — the parent dedup
treats both identically).

Checkpoints (ISSUE 4): written at level barriers through engine/ckpt.py
in the SAME payload format as the serial engine, so either engine
resumes the other's checkpoint; a state-limit truncation checkpoints
mid-level with the in-flight state requeued at the head, exactly like
the serial engine.  The PR-3 "checkpoint requested -> serial fallback"
is gone.

Falls back to the serial engine (identical behavior, a
`parallel.fallback` telemetry event, no stdout difference) when: workers
<= 1, the platform has no fork start method, or the model carries
stepwise refinement properties (their checkers are evaluated
edge-at-a-time in the parent today).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..sem.eval import TLCAssertFailure, eval_expr
from ..sem.enumerate import Walker, enumerate_init, enumerate_next, label_str
from ..sem.modules import Model, satisfies_constraints
from ..sem.values import EvalError
from .explore import (CheckResult, Explorer, Violation, _state_key,
                      make_canonicalizer, state_fingerprint)

# worker-side pure-verdict / sent-key cache cap. Each entry holds full
# state tuples, and EVERY worker keeps its own copy — an over-generous
# cap would multiply resident memory by the worker count on models that
# barely fit in RAM serially. 256k entries retains most of the dup-reuse
# win (dups cluster within/between adjacent levels)
_CACHE_CAP = 1 << 18


def default_workers() -> int:
    """`JAXMC_WORKERS` if set, else min(os.cpu_count(), 8)."""
    env = os.environ.get("JAXMC_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(os.cpu_count() or 1, 8))


def fork_available() -> bool:
    import multiprocessing
    return "fork" in multiprocessing.get_all_start_methods()


# ---------------------------------------------------------------- worker

class _WorkerState:
    """Everything a worker needs, built in the parent and inherited over
    fork (copy-on-write; nothing here is pickled)."""

    __slots__ = ("model", "vars", "walker", "base_ctx", "canon",
                 "view_expr", "prints", "verdicts", "sent", "memo_sent",
                 "key_is_concrete")

    def __init__(self, model: Model):
        self.model = model
        self.vars = model.vars
        self.walker = Walker("next", model.vars)
        self.prints: List[Any] = []
        self.base_ctx = model.ctx(on_print=self.prints.append)
        self.canon = make_canonicalizer(model)
        self.view_expr = getattr(model, "view", None)
        # without SYMMETRY/VIEW the fingerprint IS the concrete value
        # tuple, so a full record need not carry the state twice
        self.key_is_concrete = self.canon is None and self.view_expr is None
        # concrete-state-key -> (fingerprint key, cons_ok, inv, inv_prints)
        # — all pure functions of the concrete successor, so caching them
        # per worker cuts repeat verdicts to ~distinct-per-worker instead
        # of per-generated
        self.verdicts: Dict[tuple, tuple] = {}
        # fingerprints this worker has already emitted a full record for
        # (worker lifetime: a worker's chunks merge in its processing
        # order, so the full record always precedes its slim repeats) —
        # the main IPC-volume cut: repeat successors ship as key-only
        self.sent: set = set()
        # delta baseline = the PRE-FORK memo counters: workers inherit the
        # parent's store, and a (0, 0) baseline would re-add the parent's
        # own pre-fork hits/misses once per worker at the first chunk
        self.memo_sent = model._memo.stats() if model._memo is not None \
            else (0, 0)

    def fingerprint(self, st: Dict[str, Any]):
        return state_fingerprint(self.model, self.canon, self.view_expr,
                                 self.vars, st)

    def check_invariants(self, st) -> Tuple[Any, List[Any]]:
        """(None | ("inv", name) | ("assert", msg), prints)."""
        model = self.model
        if not model.invariants:
            return None, ()
        inv_prints: List[Any] = []
        ctx = model.ctx(state=st, on_print=inv_prints.append)
        from ..sem.eval import _bool
        try:
            for name, expr in model.invariants:
                if not _bool(eval_expr(expr, ctx), f"invariant {name}"):
                    return ("inv", name), inv_prints
        except TLCAssertFailure as ex:
            return ("assert", str(ex.out)), inv_prints
        return None, inv_prints

    def verdict(self, succ: Dict[str, Any]):
        ck = _state_key(succ, self.vars)
        try:
            hit = self.verdicts.get(ck)
        except TypeError:  # unhashable value (cannot happen for states,
            hit = None     # but never let the cache break a run)
            ck = None
        if hit is not None:
            return hit
        # without SYMMETRY/VIEW the fingerprint IS the concrete key —
        # don't build the same tuple twice on the miss path
        key = ck if ck is not None and self.key_is_concrete \
            else self.fingerprint(succ)
        cons_ok = satisfies_constraints(self.model, succ)
        if cons_ok:
            inv, inv_prints = self.check_invariants(succ)
        else:
            inv, inv_prints = None, ()  # discarded states are never checked
        out = (key, cons_ok, inv, list(inv_prints) if inv_prints else ())
        if ck is not None:
            if len(self.verdicts) >= _CACHE_CAP:
                self.verdicts.clear()
            self.verdicts[ck] = out
        return out


_W: Optional[_WorkerState] = None


def _init_worker(state: _WorkerState) -> None:
    global _W
    _W = state


def _expand_chunk(chunk):
    """Expand a chunk of (sid, value-tuple) pairs.  Returns
    (wall_s, memo_delta, per-state records); each per-state record is
    (sid, n_succ, assert_msg, error_msg, state_prints,
    successor-records) with successor records one of:
      ("x",)                                action-constraint filtered
      ("s", key)                            repeat of a key this worker
                                            already sent a full record for
                                            (merges strictly earlier)
      ("d", key)                            CONSTRAINT-discard (if new)
      ("f", key, label, inv, prints)        kept successor; the state IS
                                            the key values (no SYM/VIEW)
      ("F", vals, key, label, inv, prints)  kept successor under SYM/VIEW
                                            (concrete values + canonical
                                            fingerprint)
    """
    w = _W
    t0 = time.perf_counter()
    model = w.model
    vars = w.vars
    sent = w.sent
    out = []
    for sid, vals in chunk:
        st = dict(zip(vars, vals))
        recs: List[tuple] = []
        n_succ = 0
        assert_msg = None
        error_msg = None
        p0 = len(w.prints)
        it = enumerate_next(model.next, w.base_ctx, vars, st,
                            walker=w.walker)
        while True:
            try:
                succ, label = next(it)
            except StopIteration:
                break
            except TLCAssertFailure as ex:
                # raised while ENUMERATING the next successor: nothing
                # was counted for it yet (matches the serial loop)
                assert_msg = str(ex.out)
                break
            except EvalError as ex:
                # an eval error must not vaporize this chunk's earlier
                # records (a violation recorded before it would be lost
                # and the run would crash where serial reports the
                # violation): capture per state, parent re-raises at the
                # serial engine's crash point
                error_msg = str(ex)
                break
            n_succ += 1
            try:
                if model.action_constraints and \
                        not _action_constraints_ok(w, st, succ):
                    recs.append(("x",))
                    continue
                key, cons_ok, inv, inv_prints = w.verdict(succ)
            except TLCAssertFailure as ex:
                # Assert inside an action constraint, CONSTRAINT, or
                # VIEW fingerprint eval: the serial engine has already
                # counted this successor (generated++ precedes the
                # raising eval), so emit a counted-only record before
                # reporting the assert
                recs.append(("x",))
                assert_msg = str(ex.out)
                break
            except EvalError as ex:
                recs.append(("x",))  # counted before the eval raised
                error_msg = str(ex)
                break
            if key in sent:
                recs.append(("s", key))
                continue
            if len(sent) >= _CACHE_CAP:
                sent.clear()  # re-emitting full records is safe
            sent.add(key)
            if not cons_ok:
                recs.append(("d", key))
            elif w.key_is_concrete:
                recs.append(("f", key, label_str(label), inv,
                             inv_prints))
            else:
                recs.append(("F",
                             tuple(succ[v] for v in vars), key,
                             label_str(label), inv, inv_prints))
        state_prints = w.prints[p0:]
        del w.prints[p0:]
        out.append((sid, n_succ, assert_msg, error_msg, state_prints,
                    recs))
    mst = model._memo
    dh = dm = 0
    if mst is not None:
        h, m = mst.stats()
        h0, m0 = w.memo_sent
        dh, dm = h - h0, m - m0
        w.memo_sent = (h, m)
    return (time.perf_counter() - t0, (dh, dm), out)


def _action_constraints_ok(w: _WorkerState, st, succ) -> bool:
    from ..sem.eval import _bool
    ctx = w.model.ctx(state=st, primes=succ, on_print=w.prints.append)
    for name, expr in w.model.action_constraints:
        if not _bool(eval_expr(expr, ctx), f"action constraint {name}"):
            return False
    return True


def _worker_main(task_q, result_q) -> None:
    """Pool worker loop.  The model/walker state (_W) is inherited over
    fork.  Each chunk is ANNOUNCED before expansion ("start" message)
    so the parent can attribute a dead pid to the chunk it held; every
    escape from a chunk is reported as a "fail" message, never fatal —
    the parent decides retry vs degrade.  The worker_kill/chunk_error
    fault sites live here and ONLY here: the parent-inline path must
    never kill or fail the run's only process.

    Every message carries the worker's span lineage (ISSUE 16): the
    fork child re-derives its trace context lazily — same trace_id as
    the parent, its own span parented on the parent's process span — so
    the parent can place worker pids (including post-respawn ones) in
    the fleet timeline without the workers writing any artifact."""
    from .. import faults
    from ..obs import context as trace_context
    lin = trace_context.get().lineage()
    while True:
        task = task_q.get()
        if task is None:
            return
        idx, depth, chunk = task
        result_q.put(("start", idx, os.getpid(), lin))
        try:
            faults.kill_self("worker_kill", level=depth)
            faults.inject("chunk_error", level=depth)
            out = _expand_chunk(chunk)
        except BaseException as ex:  # noqa: BLE001 — report, keep serving
            result_q.put(("fail", idx, os.getpid(),
                          f"{type(ex).__name__}: {ex}", lin))
            continue
        result_q.put(("done", idx, os.getpid(), out, lin))


class _WorkerPool:
    """A context-managed fork pool with observable worker liveness.

    `multiprocessing.Pool` silently replaces a dead worker and never
    redelivers the task it held; this pool instead exposes exit codes
    (`dead()`), hands the parent every buffered result (`drain()`), and
    guarantees teardown — `shutdown()` is idempotent, runs from the
    engine's `finally`, and leaves no orphan processes behind even when
    the engine raises before or during a level (the PR-3
    `pool.terminate()` error path could leak the pool)."""

    def __init__(self, mp_ctx, size: int, wstate: _WorkerState):
        import collections
        import threading
        # delta baseline: re-read the memo counters at THIS fork point so
        # worker deltas never re-add the parent's own pre-fork hits
        if wstate.model._memo is not None:
            wstate.memo_sent = wstate.model._memo.stats()
        _init_worker(wstate)  # forked children inherit via the global
        self.size = size
        self.task_q = mp_ctx.Queue()
        self.result_q = mp_ctx.Queue()
        self.procs: List[Any] = []
        # The parent NEVER touches result_q directly: a worker SIGKILLed
        # mid-put can leave a truncated length-prefixed frame in the
        # pipe, and Queue.get's recv would then block PAST any timeout
        # (mp timeouts only cover the readability poll).  A daemon
        # reader thread absorbs that risk: it alone may wedge on the
        # torn frame; the parent reads from the thread-fed buffer with a
        # real timeout, still sees the dead worker via exit codes, and
        # abandons the thread at shutdown.
        self._buf = collections.deque()
        self._cv = threading.Condition()
        self._stop = False
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        try:
            for _ in range(size):
                p = mp_ctx.Process(target=_worker_main,
                                   args=(self.task_q, self.result_q),
                                   daemon=True)
                p.start()
                self.procs.append(p)
            self._reader.start()
        except BaseException:
            self.shutdown()
            raise

    def _read_loop(self) -> None:
        import queue as _q
        while not self._stop:
            try:
                msg = self.result_q.get(timeout=0.2)
            except _q.Empty:
                continue
            except (EOFError, OSError):
                return  # queue closed under us (shutdown)
            except Exception:  # noqa: BLE001 — a torn frame's unpickle
                continue       # error must not kill the reader
            with self._cv:
                self._buf.append(msg)
                self._cv.notify()

    def __enter__(self) -> "_WorkerPool":
        return self

    def __exit__(self, *a) -> bool:
        self.shutdown()
        return False

    def submit(self, task) -> None:
        self.task_q.put(task)

    def get(self, timeout: float):
        import queue as _q
        with self._cv:
            if not self._buf:
                self._cv.wait(timeout)
            if not self._buf:
                raise _q.Empty()
            return self._buf.popleft()

    def drain(self) -> List[tuple]:
        """Everything currently buffered (salvaged before a teardown so
        completed chunks are never re-executed).  Gives the reader
        thread a short grace window to flush messages already in the
        pipe from still-healthy workers."""
        time.sleep(0.1)
        with self._cv:
            out = list(self._buf)
            self._buf.clear()
        return out

    def dead(self) -> List[Any]:
        return [p for p in self.procs if p.exitcode is not None]

    def shutdown(self) -> None:
        self._stop = True  # reader thread is a daemon: abandoned if it
        # is wedged on a torn frame, joined-by-exit otherwise
        for p in self.procs:
            if p.exitcode is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.time() + 5.0
        for p in self.procs:
            p.join(max(0.1, deadline - time.time()))
            if p.exitcode is None:
                try:  # a worker ignoring SIGTERM gets SIGKILL
                    p.kill()
                    p.join(1.0)
                except OSError:
                    pass
        for q in (self.task_q, self.result_q):
            try:
                q.close()
                q.cancel_join_thread()  # never hang exit on a feeder
            except OSError:
                pass
        self.procs = []


# ---------------------------------------------------------------- engine

class ParallelExplorer(Explorer):
    """Worker-parallel exact BFS with serial-identical results.

    `workers` defaults to JAXMC_WORKERS, else min(os.cpu_count(), 8);
    `chunk` (frontier states per worker task) defaults to an adaptive
    split targeting ~4 tasks per worker per level, capped so task pickles
    stay small (env JAXMC_PARALLEL_CHUNK pins it)."""

    def __init__(self, model: Model, workers: Optional[int] = None,
                 chunk: Optional[int] = None, **kw):
        super().__init__(model, **kw)
        self.workers = default_workers() if workers is None \
            else max(1, int(workers))
        if chunk is None:
            env = os.environ.get("JAXMC_PARALLEL_CHUNK")
            chunk = int(env) if env else None
        self.chunk = chunk
        # crash-safe pool state (owned by _run_parallel; kept here so
        # teardown/telemetry accessors are safe on the fallback path)
        self._pool: Optional[_WorkerPool] = None
        self._pool_size = self.workers
        self._respawns = 0
        self._degraded: Optional[str] = None
        self._worker_lineage: Dict[int, Dict] = {}  # pid -> trace span

    # -- engine selection ------------------------------------------------
    def _fallback_reason(self, refiners) -> Optional[str]:
        # NOTE: checkpoint/resume no longer falls back (ISSUE 4): the
        # engine checkpoints at level barriers through engine/ckpt.py in
        # the serial engine's own payload format
        if self.workers <= 1:
            return "workers<=1"
        if not fork_available():
            return "no fork start method on this platform"
        if refiners:
            return "stepwise refinement properties"
        return None

    def run(self) -> CheckResult:
        from .. import obs
        from .refinement import build_refinement_checkers
        refiners, _ = build_refinement_checkers(self.model)
        reason = self._fallback_reason(refiners)
        if reason is not None:
            tel = obs.current()
            tel.event("parallel.fallback", reason=reason)
            tel.gauge("parallel.fallback_reason", reason)
            return Explorer.run(self)
        return self._run_parallel()

    def _chunks(self, frontier: List[int]):
        n = len(frontier)
        size = self.chunk
        if size is None:
            size = max(1, min(256, -(-n // (self.workers * 4))))
        return [frontier[i:i + size] for i in range(0, n, size)]

    # -- crash-safe pool plumbing ----------------------------------------
    def _ensure_pool(self) -> None:
        """Fork the worker pool (lazily, and again after a death).
        Workers inherit the parent's inline worker state — its `sent`
        keys were all merged into `seen`, so slim repeats from any
        worker stay resolvable."""
        if self._pool is None:
            self._pool = _WorkerPool(self._mp, self._pool_size,
                                     self._wstate)

    def _note_degraded(self, tel, reason: str) -> None:
        """Record the one-way degrade to serial expansion (telemetry +
        log); expansion correctness is unchanged — the inline path runs
        the same records through the same merge."""
        if self._degraded is None:
            self._degraded = reason
            tel.gauge("parallel.degraded", reason)
            tel.event("parallel.degraded", reason=reason)
            tel.counter("parallel.degradations")
            self.log(f"-- parallel: degrading to serial expansion "
                     f"({reason})")

    def _level_results(self, payloads, depth, tel, max_retries):
        """Yield (chunk_wall, memo_delta, records) for every chunk of
        one level IN SUBMISSION ORDER, surviving worker deaths and
        transient chunk errors.

        Recovery rules (all exact — chunks are pure functions):
        - a chunk whose worker DIED is requeued to a respawned pool,
          with a per-chunk retry budget and backoff between respawns;
          repeat deaths shrink the pool (half, floor 1) on the theory
          that the box cannot hold the full worker count;
        - a chunk that raised a TRANSIENT error is re-executed inline
          in the parent at its merge point (the parent's worker state
          keeps the slim-record invariant: every key it has emitted is
          already merged);
        - when a chunk's budget is exhausted, or the pool cannot be
          respawned, the level (and the rest of the run) degrades to
          inline expansion — `parallel.degraded` telemetry, counts
          unchanged."""
        import queue as _queue
        n = len(payloads)
        done: Dict[int, tuple] = {}
        must_inline: set = set()
        retries: Dict[int, int] = {}
        in_flight: Dict[int, int] = {}  # pid -> chunk idx
        yielded = 0
        self._ensure_pool()
        for i, p in enumerate(payloads):
            self._pool.submit((i, depth, p))

        def note_lineage(pid, lin):
            # first sight of a worker pid: one trace event placing its
            # span in the fleet timeline (same trace_id over fork, span
            # parented on this process's span) — respawned workers get
            # a fresh pid+span under the ORIGINAL trace_id
            if lin and pid not in self._worker_lineage:
                self._worker_lineage[pid] = lin
                tel.event("parallel.worker_span", pid=pid,
                          span=lin.get("span"), parent=lin.get("parent"),
                          level=depth)

        def absorb(msg):
            kind = msg[0]
            if kind == "start":
                in_flight[msg[2]] = msg[1]
                note_lineage(msg[2], msg[3] if len(msg) > 3 else None)
            elif kind == "done":
                done[msg[1]] = msg[3]
                in_flight.pop(msg[2], None)
            elif kind == "fail":
                idx = msg[1]
                in_flight.pop(msg[2], None)
                retries[idx] = retries.get(idx, 0) + 1
                tel.counter("parallel.chunk_retries")
                tel.event("parallel.chunk_error", level=depth, chunk=idx,
                          error=msg[3], retry=retries[idx])
                must_inline.add(idx)

        while yielded < n:
            if yielded in done:
                yield done.pop(yielded)
                yielded += 1
                continue
            if yielded in must_inline or self._pool is None:
                # bounded retry, replayed in the parent at the merge
                # point; memo deltas land in the parent store directly,
                # so the consumer must not re-merge them
                must_inline.discard(yielded)
                wall, _delta, out = _expand_chunk(payloads[yielded])
                yield (wall, (0, 0), out)
                yielded += 1
                continue
            try:
                absorb(self._pool.get(0.25))
                continue
            except _queue.Empty:
                pass
            dead = self._pool.dead()
            if not dead:
                continue
            # ---- a worker died (OOM kill, crash, injected fault) ----
            dead_pids = [p.pid for p in dead]
            for msg in self._pool.drain():  # salvage completed chunks
                absorb(msg)
            lost = sorted(idx for pid, idx in in_flight.items()
                          if pid in dead_pids and idx not in done)
            tel.counter("parallel.worker_deaths", len(dead))
            tel.event("parallel.worker_death", level=depth,
                      pids=dead_pids, lost_chunks=lost)
            for idx in lost:
                retries[idx] = retries.get(idx, 0) + 1
            in_flight.clear()
            self._pool.shutdown()
            self._pool = None
            exhausted = sorted(i for i, r in retries.items()
                               if r > max_retries and i >= yielded
                               and i not in done)
            if exhausted:
                self._note_degraded(
                    tel, f"chunk retry budget exhausted after repeated "
                         f"worker deaths (level {depth}, chunks "
                         f"{exhausted})")
                continue  # pool stays down -> the loop expands inline
            # bounded backoff, then respawn — shrunk after repeat
            # deaths: a box that keeps killing N workers may hold N/2
            self._respawns += 1
            if self._respawns > 1:
                self._pool_size = max(1, self._pool_size // 2)
            time.sleep(min(0.05 * (2 ** (self._respawns - 1)), 2.0))
            tel.counter("parallel.respawns")
            tel.gauge("parallel.pool_size", self._pool_size)
            try:
                self._ensure_pool()
            except OSError as ex:
                self._note_degraded(tel, f"pool respawn failed: {ex}")
                continue
            todo = [i for i in range(yielded, n)
                    if i not in done and i not in must_inline]
            tel.counter("parallel.requeues", len(todo))
            for i in todo:
                self._pool.submit((i, depth, payloads[i]))

    # -- the parallel search --------------------------------------------
    def _run_parallel(self) -> CheckResult:
        import multiprocessing
        from .. import faults, obs
        from . import ckpt as _ckpt
        model = self.model
        vars = model.vars
        t0 = time.time()
        tel = obs.current()
        base_ctx = self._ctx()

        seen: Dict[tuple, int] = {}
        states: List[Dict[str, Any]] = []
        parents: List[Optional[int]] = []
        labels: List[str] = []
        depth_of: List[int] = []
        generated = 0
        diameter = 0
        last_progress = time.time()

        canon = make_canonicalizer(model)
        VIOL = -1  # same discard sentinel as the serial engine
        view_expr = getattr(model, "view", None)

        def add_state(st, parent, label, depth):
            # same flow as the serial engine's add_state (only init
            # states pass through here; successors merge via worker
            # records above)
            key = state_fingerprint(model, canon, view_expr, vars, st)
            nid = len(states)
            sid = seen.setdefault(key, nid)
            if sid != nid:
                return (None if sid == VIOL else sid), False
            if not self._satisfies_constraints(st):
                seen[key] = VIOL
                return None, True
            states.append(st)
            parents.append(parent)
            labels.append(label)
            depth_of.append(depth)
            return nid, True

        # refiners are [] here (non-empty fell back to serial), so the
        # shared setup emits exactly the serial engine's warning lines
        from .explore import liveness_setup
        live_obligations, collect_edges, warnings = \
            liveness_setup(model, [], view_expr)
        edges: List[Tuple[int, int]] = []

        lv = {"depth": 0, "frontier": 0, "generated": 0, "new": 0,
              "t0": time.time(), "chunk_wall": 0.0, "merge_wall": 0.0}

        def flush_level(queue_len):
            if lv["frontier"] == 0 and lv["generated"] == 0:
                return
            tel.level(lv["depth"], frontier=lv["frontier"],
                      generated=lv["generated"], new=lv["new"],
                      distinct=len(states), seen=len(seen),
                      queue=queue_len,
                      wall_s=round(time.time() - lv["t0"], 6),
                      workers=self.workers,
                      chunk_wall_s=round(lv["chunk_wall"], 6),
                      merge_wall_s=round(lv["merge_wall"], 6))
            lv.update(frontier=0, generated=0, new=0, t0=time.time(),
                      chunk_wall=0.0, merge_wall=0.0)

        def result(ok, violation=None, truncated=False, queue_len=0,
                   drained=False):
            if truncated and live_obligations:
                warnings.append("temporal properties NOT checked: the "
                                "search was truncated (behavior graph "
                                "incomplete)")
            flush_level(queue_len)
            mst = model._memo
            if mst is not None:
                tel.gauge("memo.hits", mst.hits)
                tel.gauge("memo.misses", mst.misses)
            tel.gauge("fingerprint.occupancy", len(seen))
            tel.gauge("parallel.workers", self.workers)
            trunc_reason = None
            if truncated:
                # name the exhausted resource (ISSUE 12 satellite)
                trunc_reason = ("drain" if drained else
                                f"max_states: distinct {len(states)} "
                                f">= limit {self.max_states}")
                tel.gauge("truncation.reason", trunc_reason)
            return CheckResult(ok=ok, distinct=len(states),
                               generated=generated, diameter=diameter,
                               violation=violation,
                               wall_s=time.time() - t0,
                               prints=self.prints, truncated=truncated,
                               warnings=warnings, drained=drained,
                               trunc_reason=trunc_reason)

        # checkpoint plumbing: level-barrier (and truncation) writes in
        # the serial engine's payload format, with the serial engine's
        # adaptive interval stretch (write cost capped at ~5% of wall)
        ck_state = {"every": self.checkpoint_every,
                    "last": time.time()}

        def write_checkpoint(queue, generated_at, prints_at=None):
            payload = _ckpt.interp_payload(
                model, vars, states, parents, labels, depth_of,
                queue, generated_at, diameter, seen, edges,
                collect_edges,
                self.prints if prints_at is None
                else self.prints[:prints_at])
            _ckpt.write_periodic(
                self.checkpoint_path, "interp",
                {"module": model.module.name, "engine": "parallel"},
                payload, tel, self.log, ck_state,
                span_attrs={"states": len(states), "queue": len(queue)})

        # ---- initial states, or resume (exactly as the serial engine) --
        frontier: List[int] = []
        carry: List[int] = []  # resumed queue states one level deeper
        if self.resume_from:
            # same loader + validations as the serial engine: integrity
            # defects surface as CkptError (exit 2), never a traceback
            ck = _ckpt.load_interp_checkpoint(self.resume_from, model,
                                              vars, collect_edges)
            self.prints.extend(ck.get("prints", []))
            states.extend(ck["states"])
            parents.extend(ck["parents"])
            labels.extend(ck["labels"])
            depth_of.extend(ck["depth_of"])
            generated = ck["generated"]
            diameter = ck["diameter"]
            seen.update(ck["seen_items"])
            if collect_edges:
                edges.extend(ck["edges"])
            q = list(ck["queue"])
            if q:
                # the queue spans at most two adjacent depths (BFS
                # invariant): replay the depth-d prefix as this level's
                # frontier and keep the depth-d+1 suffix AHEAD of this
                # level's discoveries — the serial engine's exact pop
                # order, so resumed counts stay bit-identical
                rd = depth_of[q[0]]
                frontier = [s for s in q if depth_of[s] == rd]
                carry = [s for s in q if depth_of[s] != rd]
            self.log(f"Resumed from {self.resume_from}: {len(states)} "
                     f"distinct states, {len(q)} on queue.")
        else:
            try:
                inits = enumerate_init(model.init, base_ctx, vars)
            except TLCAssertFailure as ex:
                return result(False, Violation("assert", "Init", [],
                                               str(ex.out)))
            init_count = 0
            for st in inits:
                sid, new = add_state(st, None, "Initial predicate", 0)
                if not new:
                    continue
                generated += 1
                if sid is None:
                    continue  # discarded by CONSTRAINT
                init_count += 1
                bad = self._check_state_preds(st)
                if bad is not None:
                    return result(False, Violation(
                        "invariant", bad,
                        self._trace_to(sid, parents, states, labels)))
                frontier.append(sid)
            self.log(f"Finished computing initial states: {init_count} "
                     f"distinct state{'s' if init_count != 1 else ''} "
                     f"generated.")

        d0 = depth_of[frontier[0]] if frontier else 0
        self.log(f"Progress({d0}): {generated} states generated, "
                 f"{len(states)} distinct states found, "
                 f"{len(frontier) + len(carry)} states left on queue."
                 f"{obs.eta_suffix(len(states))}")

        # ---- the level-synchronous pool loop ----
        self._mp = multiprocessing.get_context("fork")
        wstate = _WorkerState(model)
        # the parent can run the worker body inline (global worker state
        # in this process too): frontiers smaller than the fan-out are
        # expanded without the per-level IPC barrier — same records, same
        # replay, zero round-trip latency on shallow/narrow levels.
        # Chaos faults targeting pool workers force the pool ON so a
        # tiny model still exercises the crash path the fault asks for.
        _init_worker(wstate)
        self._wstate = wstate
        self._pool = None
        self._pool_size = self.workers
        self._respawns = 0
        self._degraded = None
        faults.ensure_shared_state()  # one fault budget for all forks
        inline_below = 0 if faults.targets("worker_kill", "chunk_error") \
            else self.workers * 4
        max_retries = int(os.environ.get("JAXMC_PARALLEL_RETRIES", "2"))
        n_chunks_total = 0
        from .. import drain as _drain
        try:
            depth = d0
            while frontier or carry:
                if _drain.requested():
                    # cooperative drain at the level barrier: the queue
                    # (this frontier, then the resumed-carry states one
                    # level deeper) checkpoints untouched — the serial
                    # engine's own resume split re-derives the depths
                    why = _drain.reason()
                    self.log(f"-- drain requested ({why}): stopping at "
                             f"the level barrier")
                    if self.checkpoint_path:
                        write_checkpoint(list(frontier) + list(carry),
                                         generated)
                    tel.event("drain", reason=why, engine="parallel")
                    warnings.append(
                        f"run drained before completion ({why})"
                        + (f"; resume with --resume "
                           f"{self.checkpoint_path}"
                           if self.checkpoint_path else "; no "
                           "checkpoint was configured — progress was "
                           "discarded"))
                    return result(True, truncated=True, drained=True,
                                  queue_len=len(frontier) + len(carry))
                lv["depth"] = depth
                # resumed depth+1 queue states stay AHEAD of this
                # level's discoveries (serial pop order)
                next_frontier: List[int] = carry
                carry = []
                chunks = self._chunks(frontier)
                n_chunks_total += len(chunks)
                payloads = [[(sid,
                              tuple(states[sid][v] for v in vars))
                             for sid in c] for c in chunks]
                remaining = len(frontier)
                fpos = -1  # index of the merging state in frontier order
                if self._degraded is not None or \
                        len(frontier) < inline_below:
                    # parent-inline expansion: memo deltas are already in
                    # the parent store, so they are NOT re-merged below
                    results = (_expand_chunk(p) for p in payloads)
                    inline = True
                else:
                    results = self._level_results(payloads, depth, tel,
                                                  max_retries)
                    inline = False
                for chunk_wall, memo_delta, chunk_out in results:
                    lv["chunk_wall"] += chunk_wall
                    mst = model._memo
                    if mst is not None and not inline:
                        mst.merge_stats(*memo_delta)
                    m0 = time.perf_counter()
                    for (sid, n_succ, assert_msg, error_msg,
                         state_prints, recs) in chunk_out:
                        remaining -= 1
                        fpos += 1
                        lv["frontier"] += 1
                        diameter = max(diameter, depth)
                        # truncation-checkpoint snapshots: roll back to
                        # this state's merge start so resume re-expands
                        # it exactly once (the serial engine's rule)
                        gen_at_state = generated
                        prints_at_state = len(self.prints)
                        self.prints.extend(state_prints)
                        for rec in recs:
                            generated += 1
                            lv["generated"] += 1
                            kind = rec[0]
                            if kind == "x":
                                continue
                            if kind == "s":
                                ex_sid = seen[rec[1]]
                                if ex_sid != VIOL and collect_edges:
                                    edges.append((sid, ex_sid))
                                continue
                            key = rec[2] if kind == "F" else rec[1]
                            ex_sid = seen.get(key)
                            if ex_sid is not None:
                                # duplicate fingerprint: the stored
                                # verdict wins (serial dedup-first order)
                                if ex_sid != VIOL and collect_edges:
                                    edges.append((sid, ex_sid))
                                continue
                            if kind == "d":
                                seen[key] = VIOL
                                continue
                            if kind == "f":
                                _, _, label, inv, inv_prints = rec
                                succ = dict(zip(vars, key))
                            else:
                                _, vals, _, label, inv, inv_prints = rec
                                succ = dict(zip(vars, vals))
                            nid = len(states)
                            seen[key] = nid
                            states.append(succ)
                            parents.append(sid)
                            labels.append(label)
                            depth_of.append(depth + 1)
                            if collect_edges:
                                edges.append((sid, nid))
                            lv["new"] += 1
                            self.prints.extend(inv_prints)
                            if inv is not None:
                                if inv[0] == "inv":
                                    return result(False, Violation(
                                        "invariant", inv[1],
                                        self._trace_to(nid, parents,
                                                       states, labels)))
                                trace = self._trace_to(sid, parents,
                                                       states, labels)
                                return result(False, Violation(
                                    "assert", "Assert", trace, inv[1]))
                            next_frontier.append(nid)
                            if self.max_states and \
                                    len(states) >= self.max_states:
                                self.log("-- state limit reached, "
                                         "search truncated")
                                if self.checkpoint_path:
                                    # mid-level write: the in-flight
                                    # state re-queued at the head with
                                    # generated/prints rolled back to
                                    # its merge start (serial rule)
                                    write_checkpoint(
                                        [sid] + frontier[fpos + 1:]
                                        + next_frontier,
                                        gen_at_state, prints_at_state)
                                return result(
                                    True, truncated=True,
                                    queue_len=remaining
                                    + len(next_frontier))
                        if assert_msg is not None:
                            trace = self._trace_to(sid, parents, states,
                                                   labels)
                            return result(False, Violation(
                                "assert", "Assert", trace, assert_msg))
                        if error_msg is not None:
                            # the serial engine's crash point: the eval
                            # error surfaced expanding THIS state, after
                            # its earlier successors were processed
                            raise EvalError(error_msg)
                        if n_succ == 0 and model.check_deadlock:
                            return result(False, Violation(
                                "deadlock", "deadlock",
                                self._trace_to(sid, parents, states,
                                               labels)))
                        now = time.time()
                        if now - last_progress >= self.progress_every:
                            last_progress = now
                            self.log(
                                f"Progress({depth}): {generated} states "
                                f"generated, {len(states)} distinct "
                                f"states found, "
                                f"{remaining + len(next_frontier)} "
                                f"states left on queue."
                                f"{obs.eta_suffix(len(states))}")
                    lv["merge_wall"] += time.perf_counter() - m0
                flush_level(len(next_frontier))
                frontier = next_frontier
                depth += 1
                # ---- level barrier: checkpoint + chaos kill site ----
                now = time.time()
                if self.checkpoint_path and \
                        now - ck_state["last"] >= ck_state["every"]:
                    ck_state["last"] = now
                    write_checkpoint(list(frontier), generated)
                faults.kill_self("run_kill", level=depth,
                                 engine="parallel")
        finally:
            if self._pool is not None:
                self._pool.shutdown()
                self._pool = None
            # in the finally: a truncated or violating run's early
            # return must still record its chunk count
            tel.counter("parallel.chunks", n_chunks_total)

        # completed search: the FINAL checkpoint (serve warm-resume
        # source; engine/explore.py documents the contract)
        if self.checkpoint_path and self.final_checkpoint:
            write_checkpoint([], generated)

        # ---- temporal properties over the completed behavior graph ----
        if live_obligations:
            from .liveness import LivenessChecker
            lc = LivenessChecker(model, states, edges, parents, labels)
            bad, live_warns = lc.check(live_obligations)
            warnings.extend(live_warns)
            if bad is not None:
                pname, trace, msg = bad
                return result(False, Violation("property", pname, trace,
                                               msg))

        self.log(f"Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {len(states)} distinct "
                 f"states found, 0 states left on queue.")
        self.log(f"The depth of the complete state graph search is "
                 f"{diameter + 1}.")
        return result(True)
