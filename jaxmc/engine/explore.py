r"""Host BFS model-checking engine (the exact oracle path, BACKEND=interp).

Reproduces TLC's observable behavior (SURVEY.md §3.2): enumerate Init states,
breadth-first apply Next, dedup on full states, check invariants and
constraints on every new distinct state, detect deadlock, report progress in
TLC's format (testout1:3-9) and shortest counterexample traces with action
provenance (README.md:268-318).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..sem.values import Fcn, ModelValue, fmt, sort_key
from ..sem.eval import TLCAssertFailure, eval_expr, _bool
from ..sem.enumerate import (Walker, enumerate_init, enumerate_next,
                             label_str)
from ..sem.modules import Model


@dataclass
class Violation:
    kind: str  # 'invariant' | 'assert' | 'deadlock' | 'constraint-eval' | 'error'
    name: str
    trace: List[Tuple[Dict[str, Any], str]]  # (state, action label)
    message: str = ""


@dataclass
class CheckResult:
    ok: bool
    distinct: int
    generated: int
    diameter: int
    violation: Optional[Violation] = None
    wall_s: float = 0.0
    prints: List[Any] = field(default_factory=list)
    truncated: bool = False
    warnings: List[str] = field(default_factory=list)
    # a cooperative drain (jaxmc/drain.py: SIGTERM, serve daemon
    # shutdown) stopped the search at a safe boundary after writing a
    # checkpoint; implies truncated=True — the explored prefix is clean
    # but incomplete, and the run is resumable
    drained: bool = False
    # truncation ATTRIBUTION (ISSUE 12 satellite): which resource ran
    # out — "max_states: distinct N >= limit M", a named tier/cap with
    # the observed need, a drain reason — so `obs diff` can tell a
    # capacity regression from a deliberate limit.  None on complete
    # runs.
    trunc_reason: Optional[str] = None
    # dedup-key mode the run actually used ("exact" | "fingerprint")
    # and, in fingerprint mode, the reported collision-probability
    # bound (< n^2 * 2^-129 over n admitted keys) — TLC reports the
    # same estimate for its 64-bit fingerprints
    seen_mode: str = "exact"
    collision_p: Optional[float] = None
    # hierarchical seen-set summary when the run spilled (tiers.py
    # stats(): host/disk keys, spills, compactions, probe wall)
    tiers: Optional[Dict[str, Any]] = None

    @property
    def states_per_sec(self) -> float:
        return self.generated / self.wall_s if self.wall_s > 0 else 0.0


def _state_key(state: Dict[str, Any], vars: Tuple[str, ...]):
    return tuple(state[v] for v in vars)


def state_fingerprint(model: Model, canon, view_expr,
                      vars: Tuple[str, ...], st: Dict[str, Any]):
    """The ONE dedup fingerprint for the exact engines: the canonical
    (SYMMETRY-least) state's value tuple, or the VIEW expression's VALUE
    when the cfg declares one (TLC fingerprints the view, not the state).
    The serial engine, the parallel engine's parent merge, and the
    parallel workers must all agree on this — a change here changes all
    three together (tests/test_parallel.py pins the parity)."""
    cst = canon(st) if canon is not None else st
    if view_expr is not None:
        return ("$view", eval_expr(view_expr, model.ctx(state=cst)))
    return _state_key(cst, vars)


def _apply_perm(v, pd):
    """Apply a model-value permutation (dict ModelValue->ModelValue) to a
    value tree."""
    if isinstance(v, ModelValue):
        return pd.get(v, v)
    if isinstance(v, frozenset):
        return frozenset(_apply_perm(x, pd) for x in v)
    if isinstance(v, Fcn):
        return Fcn({_apply_perm(k, pd): _apply_perm(x, pd)
                    for k, x in v.d.items()})
    from ..sem.values import FcnSetV
    if isinstance(v, FcnSetV):
        return frozenset(_apply_perm(x, pd) for x in v.materialize())
    return v


def make_canonicalizer(model: Model):
    """cfg SYMMETRY (TLC.tla:13-14 Permutations): canonicalize each state
    to the least representative under the declared permutation set, the
    standard symmetry reduction (SURVEY.md §5). Returns None when no
    symmetry is declared or every permutation is the identity."""
    from ..sem.symmetry import symmetry_group
    perms = symmetry_group(model)
    if not perms:
        return None

    def canon(state: Dict[str, Any]) -> Dict[str, Any]:
        best = state
        best_key = sort_key(tuple(state[v] for v in model.vars))
        for pd in perms:
            cand = {v: _apply_perm(state[v], pd) for v in model.vars}
            k = sort_key(tuple(cand[v] for v in model.vars))
            if k < best_key:
                best, best_key = cand, k
        return best

    return canon


def liveness_setup(model: Model, refiners, view_expr):
    """Temporal-obligation collection + the warning lines both exact
    engines must emit IDENTICALLY (the parity suite pins warnings
    byte-for-byte).  Returns (live_obligations, collect_edges,
    warnings).  collect_obligations also adopts the fairness halves of
    spec-shaped PROPERTYs (clearing liveness_skipped), so it runs BEFORE
    the refiner warning pass."""
    from .liveness import collect_obligations
    warnings: List[str] = []
    live_obligations, unsupported, collect_edges = \
        collect_obligations(model, refiners)
    for rc in refiners:
        if rc.liveness_skipped:
            warnings.append(
                f"property {rc.name}: refinement checked stepwise; its "
                f"fairness conjuncts are NOT checked")
    if unsupported:
        warnings.append(
            "temporal properties NOT checked (unsupported form): "
            + ", ".join(unsupported))
    if view_expr is not None and live_obligations:
        # the behavior graph under VIEW links view-collapsed
        # representatives — liveness verdicts over it would be wrong
        # (TLC likewise refuses VIEW together with liveness)
        warnings.append(
            "temporal properties NOT checked: cfg VIEW collapses "
            "the behavior graph (TLC also rejects VIEW with "
            "liveness): "
            + ", ".join(sorted({ob.prop_name
                                for ob in live_obligations})))
        live_obligations = []
        collect_edges = False
    return live_obligations, collect_edges, warnings


class Explorer:
    def __init__(self, model: Model, log: Callable[[str], None] = None,
                 max_states: Optional[int] = None,
                 progress_every: float = 30.0,
                 trace_parents: bool = True,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: float = 600.0,
                 resume_from: Optional[str] = None,
                 final_checkpoint: bool = False,
                 por: bool = False):
        from .. import obs
        self.model = model
        # partial-order reduction (ISSUE 15, opt-in --por): expand ONE
        # globally-commuting invisible arm per state when every one of
        # its successors is new (persistent-set filter + BFS cycle
        # proviso) — preserves invariant/deadlock verdicts, NOT raw
        # state counts.  Disabled with a named reason on models whose
        # constructs interact with the reduction (CONSTRAINT, SYMMETRY,
        # VIEW, temporal/refinement PROPERTYs).
        self.por = por
        # default sink: silent on stdout but still mirrored into the
        # telemetry trace (obs.Logger is THE log funnel — cli.py passes
        # a printing one; library callers get the quiet one)
        self.log = log if log is not None else obs.Logger(quiet=True)
        self.max_states = max_states
        self.progress_every = progress_every
        self.trace_parents = trace_parents
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.resume_from = resume_from
        # write one last checkpoint when the search COMPLETES (empty
        # queue, full state table): the serve daemon's warm-resume
        # source — a later identical job resumes it and finishes
        # instantly with the same counts.  Off by default: `check`
        # keeps its exact log-line surface
        self.final_checkpoint = final_checkpoint
        self.prints: List[Any] = []

    def _ctx(self, state=None, primes=None):
        return self.model.ctx(state, primes, on_print=self.prints.append)

    def _check_state_preds(self, state) -> Optional[str]:
        """Returns the name of a violated invariant, else None."""
        if not self.model.invariants:
            return None  # skip the per-state ctx build entirely
        ctx = self._ctx(state=state)
        for name, expr in self.model.invariants:
            if not _bool(eval_expr(expr, ctx), f"invariant {name}"):
                return name
        return None

    def _satisfies_action_constraints(self, state, succ) -> bool:
        ctx = self._ctx(state=state, primes=succ)
        for name, expr in self.model.action_constraints:
            if not _bool(eval_expr(expr, ctx),
                         f"action constraint {name}"):
                return False
        return True

    def _satisfies_constraints(self, state) -> bool:
        from ..sem.modules import satisfies_constraints
        return satisfies_constraints(self.model, state)

    def _trace_to(self, sid, parents, states, labels) -> List[Tuple[Dict, str]]:
        out = []
        while sid is not None:
            out.append((states[sid], labels[sid]))
            sid = parents[sid]
        out.reverse()
        return out

    def run(self) -> CheckResult:
        from .. import obs
        model = self.model
        vars = model.vars
        t0 = time.time()
        tel = obs.current()
        base_ctx = self._ctx()

        # state table
        seen: Dict[tuple, int] = {}
        states: List[Dict[str, Any]] = []
        parents: List[Optional[int]] = []
        labels: List[str] = []
        queue = deque()
        generated = 0
        depth_of: List[int] = []
        diameter = 0
        last_progress = time.time()
        last_checkpoint = time.time()

        # checkpoint cost accounting: each write pickles the FULL state
        # table, so its cost grows with the search — surface it as a
        # checkpoint.write span (the phase rollup used to hide it as
        # anonymous search wall) and stretch the interval when a write
        # gets expensive relative to it (the cheap size/time guard:
        # never spend more than ~5% of the wall checkpointing)
        ck_state = {"every": self.checkpoint_every}

        def write_checkpoint(queue_head=(), generated_at=None,
                             prints_at=None):
            # TLC-style periodic checkpoint (testout1:10; SURVEY.md §5):
            # the full search state, resumable with --resume. A state whose
            # expansion is in flight is re-queued at the head with
            # `generated` rolled back to its pop, so resume re-expands it
            # exactly once and full-run counts stay exact. Written through
            # engine/ckpt.py (checksum + schema header): a clipped or
            # bit-rotted file is refused at resume, never half-trusted
            from . import ckpt as _ckpt
            payload = _ckpt.interp_payload(
                model, vars, states, parents, labels, depth_of,
                list(queue_head) + list(queue),
                generated if generated_at is None else generated_at,
                diameter, seen, edges, collect_edges,
                self.prints if prints_at is None
                else self.prints[:prints_at])
            _ckpt.write_periodic(
                self.checkpoint_path, "interp",
                {"module": model.module.name, "engine": "serial"},
                payload, tel, self.log, ck_state,
                span_attrs={"states": len(states),
                            "queue": len(queue_head) + len(queue)})

        canon = make_canonicalizer(model)

        VIOL = -1  # seen-value for constraint-violating states: TLC (1.57,
        # testout2:265 — 195 distinct) discards them entirely: fingerprinted
        # so they are not re-processed, but never counted as distinct,
        # never invariant-checked, never explored (Specifying Systems §14)

        view_expr = getattr(model, "view", None)

        def _lstr(label) -> str:
            return label if isinstance(label, str) else label_str(label)

        def add_state(st, parent, label, depth):
            """Returns (sid | None, new). sid None = discarded by
            CONSTRAINT; new is True the first time any state (kept or
            discarded) is seen.  MIRRORED in engine/parallel.py (its
            add_state + merge replay): any change to this dedup/discard
            flow must land there too or the engines' bit-identical
            parity breaks (tests/test_parallel.py pins it)."""
            # the POR proviso check may have fingerprinted this very
            # successor object already — reuse its key (por_keys is
            # empty on unreduced runs; defined below, bound at call
            # time)
            key = por_keys.pop(id(st), None) if por_keys else None
            if key is None:
                key = state_fingerprint(model, canon, view_expr, vars,
                                        st)
            # single-hash insert: tentatively claim the next sid; a dup
            # returns the existing mapping without a second key hash (the
            # fingerprint tuple is hashed once per generated state instead
            # of once for the probe plus once for the store)
            nid = len(states)
            sid = seen.setdefault(key, nid)
            if sid != nid:
                return (None if sid == VIOL else sid), False
            if not self._satisfies_constraints(st):
                seen[key] = VIOL
                return None, True
            states.append(st)
            parents.append(parent)
            labels.append(label)
            depth_of.append(depth)
            return nid, True

        from .refinement import build_refinement_checkers
        refiners, live_only = build_refinement_checkers(model)
        # temporal obligations are checked over the behavior graph after
        # the search completes (engine/liveness.py) — collect the full
        # edge log only when some property needs it ('always'
        # obligations only iterate states; don't pay the RAM +
        # checkpoint size otherwise)
        live_obligations, collect_edges, warnings = \
            liveness_setup(model, refiners, view_expr)
        edges: List[Tuple[int, int]] = []

        # ---- partial-order reduction setup (ISSUE 15) ----
        por_active = False
        por_stats = {"ample": 0, "full": 0}
        por_arms = por_safe = por_ctxs = por_walkers = None
        if self.por:
            from ..analyze.independence import (independence_report,
                                                por_refusal)
            from ..compile.ground import split_arms
            por_reason = por_refusal(model)
            if por_reason is None and canon is not None:
                por_reason = "symmetry canonicalizer active"
            if por_reason is None:
                por_arms = split_arms(model)
                irep = independence_report(model, por_arms)
                tel.gauge("analyze.independence_pairs",
                          irep.commuting_pairs())
                tel.gauge("analyze.independence_safe",
                          len(irep.por_safe))
                if not irep.por_safe:
                    por_reason = ("no arm commutes with every other "
                                  "arm invisibly")
            if por_reason is not None:
                warnings.append(f"--por requested but reduction "
                                f"disabled: {por_reason} (running "
                                f"unreduced)")
                tel.gauge("por.disabled_reason", por_reason)
            else:
                por_active = True
                por_safe = sorted(irep.por_safe)
                por_ctxs = [base_ctx.with_bound(a.bound) if a.bound
                            else base_ctx for a in por_arms]
                por_walkers = [Walker("next", vars) for _ in por_arms]
                self.log(f"-- por: {len(por_safe)}/{len(por_arms)} "
                         f"arms eligible as singleton ample sets")

        def _arm_succs(i, st):
            arm = por_arms[i]
            fallback = arm.label or "Next"
            out = []
            for succ, label in enumerate_next(arm.expr, por_ctxs[i],
                                              vars, st,
                                              walker=por_walkers[i]):
                out.append((succ, _lstr(label) if label is not None
                            else fallback))
            return out

        # keys computed by the ample proviso check, reused by add_state
        # (the single-hash-per-state discipline the serial hot loop is
        # built around); repopulated per _por_expand call — entries
        # only ever describe the CURRENTLY-returned successor objects,
        # so a recycled id() can never resurrect a stale key
        por_keys: Dict[int, Any] = {}

        def _por_expand(st):
            """The persistent-set filter: the FIRST eligible arm whose
            successor set is nonempty and entirely NEW (keys outside
            `seen` — the BFS cycle proviso) becomes the singleton ample
            set; otherwise every arm expands, in original arm order
            (byte-identical to the unreduced walk's stream).

            Verdict preservation for SKIPPED arms (why an Assert or a
            guard violation in arm B cannot be lost): every ample arm
            commutes with EVERY arm, so no ample-only chain writes
            B's read set — B's enabledness and full evaluation
            (including any Assert outcome) are INVARIANT along the
            chain — and the all-successors-new proviso forces each
            chain to end in a full expansion (the seen set is finite
            and grows), which evaluates B with bit-identical inputs.
            Only TLC PRINT side effects of skipped interleavings are
            lost (documented in the README)."""
            por_keys.clear()
            cached = {}
            for i in por_safe:
                ss = _arm_succs(i, st)
                keys = [state_fingerprint(model, canon, view_expr,
                                          vars, s) for s, _l in ss]
                cached[i] = (ss, keys)
                if ss and all(k not in seen for k in keys):
                    por_stats["ample"] += 1
                    for (s, _l), k in zip(ss, keys):
                        por_keys[id(s)] = k
                    return ss
            out = []
            for i in range(len(por_arms)):
                hit = cached.get(i)
                if hit is None:
                    out.extend(_arm_succs(i, st))
                    continue
                ss, keys = hit
                # the proviso trials already hashed these successors:
                # keep their keys for add_state too
                for (s, _l), k in zip(ss, keys):
                    por_keys[id(s)] = k
                out.extend(ss)
            por_stats["full"] += 1
            return out

        # per-level BFS telemetry: record level d when its last state has
        # been expanded (the queue is depth-ordered, so the first pop of
        # depth d+1 closes level d); `lv` accumulates the in-flight level
        lv = {"depth": 0, "frontier": 0, "generated": 0, "new": 0,
              "t0": time.time()}

        def flush_level():
            if lv["frontier"] == 0 and lv["generated"] == 0:
                return
            tel.level(lv["depth"], frontier=lv["frontier"],
                      generated=lv["generated"], new=lv["new"],
                      distinct=len(states), seen=len(seen),
                      queue=len(queue),
                      wall_s=round(time.time() - lv["t0"], 6))
            lv.update(frontier=0, generated=0, new=0, t0=time.time())

        def result(ok, violation=None, truncated=False, drained=False,
                   trunc_reason=None):
            if truncated and live_obligations:
                warnings.append("temporal properties NOT checked: the "
                                "search was truncated (behavior graph "
                                "incomplete)")
            flush_level()
            mst = model._memo
            if mst is not None:
                tel.gauge("memo.hits", mst.hits)
                tel.gauge("memo.misses", mst.misses)
            tel.gauge("fingerprint.occupancy", len(seen))
            if self.por:
                tel.gauge("por.enabled", por_active)
                if por_active:
                    total = por_stats["ample"] + por_stats["full"]
                    tel.counter("por.ample_states", por_stats["ample"])
                    tel.counter("por.full_states", por_stats["full"])
                    tel.gauge("por.ample_ratio",
                              round(por_stats["ample"] / total, 4)
                              if total else 0.0)
                    # the REDUCED run's distinct count — obs diff reads
                    # it against an unreduced baseline's result.distinct
                    tel.gauge("por.reduced_states", len(states))
            if truncated and trunc_reason is None:
                # name the exhausted resource (ISSUE 12 satellite) —
                # the serial engine truncates on max_states or a drain
                trunc_reason = (f"drain" if drained else
                                f"max_states: distinct {len(states)} "
                                f">= limit {self.max_states}")
            if trunc_reason:
                tel.gauge("truncation.reason", trunc_reason)
            return CheckResult(ok=ok, distinct=len(states),
                               generated=generated, diameter=diameter,
                               violation=violation, wall_s=time.time() - t0,
                               prints=self.prints, truncated=truncated,
                               warnings=warnings, drained=drained,
                               trunc_reason=trunc_reason)

        def drain_out():
            # cooperative drain (jaxmc/drain.py): checkpoint at this
            # safe boundary (nothing in flight — the drained state goes
            # back on the queue untouched) and stop with the named
            # reason; the caller's finally blocks close spans/watchdog
            from .. import drain as _drain
            why = _drain.reason()
            self.log(f"-- drain requested ({why}): stopping at a safe "
                     f"boundary")
            if self.checkpoint_path:
                write_checkpoint()
            tel.event("drain", reason=why, engine="serial")
            warnings.append(
                f"run drained before completion ({why})"
                + (f"; resume with --resume {self.checkpoint_path}"
                   if self.checkpoint_path else "; no checkpoint was "
                   "configured — progress was discarded"))
            return result(True, truncated=True, drained=True)

        # ---- resume from a checkpoint ----
        if self.resume_from:
            # integrity (checksum/truncation/format) and module/vars
            # validation live in engine/ckpt.py; every defect is a
            # CkptError (exit 2 at the CLI), never a traceback or a
            # silently-wrong resume.
            # dedup keys must be symmetry-canonical, matching add_state.
            # seen_items stores (key, sid-or-VIOL) directly so resume is a
            # linear dict fill — no re-canonicalization, and discarded
            # (constraint-violating) fingerprints survive the checkpoint.
            from .ckpt import load_interp_checkpoint
            ck = load_interp_checkpoint(self.resume_from, model, vars,
                                        collect_edges)
            self.prints.extend(ck.get("prints", []))
            states.extend(ck["states"])
            parents.extend(ck["parents"])
            labels.extend(ck["labels"])
            depth_of.extend(ck["depth_of"])
            queue.extend(ck["queue"])
            generated = ck["generated"]
            diameter = ck["diameter"]
            seen.update(ck["seen_items"])
            if collect_edges:
                edges.extend(ck["edges"])
            self.log(f"Resumed from {self.resume_from}: {len(states)} "
                     f"distinct states, {len(queue)} on queue.")

        # ---- initial states ----
        try:
            inits = [] if self.resume_from else                 enumerate_init(model.init, base_ctx, vars)
        except TLCAssertFailure as ex:
            return result(False, Violation("assert", "Init", [], str(ex.out)))
        init_count = 0
        for st in inits:
            sid, new = add_state(st, None, "Initial predicate", 0)
            if not new:
                continue
            generated += 1
            if sid is None:
                continue  # discarded by CONSTRAINT
            init_count += 1
            bad = self._check_state_preds(st)
            if bad is not None:
                return result(False, Violation(
                    "invariant", bad,
                    self._trace_to(sid, parents, states, labels)))
            for rc in refiners:
                if not rc.check_init(st):
                    return result(False, Violation(
                        "property", rc.name,
                        self._trace_to(sid, parents, states, labels),
                        f"initial state violates {rc.name}'s initial "
                        f"predicate"))
            queue.append(sid)
        if not self.resume_from:
            self.log(f"Finished computing initial states: {init_count} "
                     f"distinct state{'s' if init_count != 1 else ''} "
                     f"generated.")

        # first progress record IMMEDIATELY (ISSUE 2): a short run used
        # to produce zero progress lines because the first one waited a
        # full --progress-every interval
        d0 = depth_of[queue[0]] if queue else 0
        self.log(f"Progress({d0}): {generated} states generated, "
                 f"{len(states)} distinct states found, "
                 f"{len(queue)} states left on queue."
                 f"{obs.eta_suffix(len(states), tel)}")

        # ---- BFS ----
        # one reusable walker for the whole search: the action AST is
        # split (call-by-name decisions, substituted bodies) once per run
        # instead of once per state (sem/enumerate.py Walker)
        next_walker = Walker("next", vars)
        from .. import drain as _drain
        while queue:
            if _drain.requested():
                return drain_out()
            sid = queue.popleft()
            st = states[sid]
            depth = depth_of[sid]
            if depth > lv["depth"]:
                flush_level()
                lv["depth"] = depth
                # chaos harness: simulated hard crash entering a level
                # (the kill/resume parity suite SIGKILLs here and pins
                # the resumed counts bit-identical to an uninterrupted
                # run). No-op unless JAXMC_FAULTS configures run_kill.
                from .. import faults
                faults.kill_self("run_kill", level=depth,
                                 engine="serial")
            lv["frontier"] += 1
            diameter = max(diameter, depth)
            succ_count = 0
            gen_at_pop = generated
            prints_at_pop = len(self.prints)
            try:
                pairs = _por_expand(st) if por_active else \
                    enumerate_next(model.next, base_ctx, vars, st,
                                   walker=next_walker)
                for succ, label in pairs:
                    succ_count += 1
                    generated += 1
                    lv["generated"] += 1
                    if model.action_constraints and not \
                            self._satisfies_action_constraints(st, succ):
                        continue
                    nid, new = add_state(succ, sid, _lstr(label),
                                         depth + 1)
                    if nid is None:
                        continue  # discarded by CONSTRAINT (not checked)
                    if collect_edges:
                        edges.append((sid, nid))
                    for rc in refiners:
                        if not rc.check_edge(st, succ):
                            trace = self._trace_to(sid, parents, states,
                                                   labels)
                            trace.append((succ, _lstr(label)))
                            msg = (f"step is not a [{rc.name}-Next]_v "
                                   f"step of the refined specification")
                            if rc.last_error:
                                msg += (f"; while evaluating the property: "
                                        f"{rc.last_error}")
                            return result(False, Violation(
                                "property", rc.name, trace, msg))
                    if not new:
                        continue
                    lv["new"] += 1
                    bad = self._check_state_preds(succ)
                    if bad is not None:
                        return result(False, Violation(
                            "invariant", bad,
                            self._trace_to(nid, parents, states, labels)))
                    queue.append(nid)
                    if self.max_states and len(states) >= self.max_states:
                        self.log("-- state limit reached, search truncated")
                        if self.checkpoint_path:
                            write_checkpoint(queue_head=[sid],
                                             generated_at=gen_at_pop,
                                             prints_at=prints_at_pop)
                        return result(True, truncated=True)
            except TLCAssertFailure as ex:
                trace = self._trace_to(sid, parents, states, labels)
                return result(False, Violation("assert", "Assert", trace,
                                               str(ex.out)))
            if succ_count == 0 and model.check_deadlock:
                return result(False, Violation(
                    "deadlock", "deadlock",
                    self._trace_to(sid, parents, states, labels)))
            now = time.time()
            if now - last_progress >= self.progress_every:
                last_progress = now
                self.log(f"Progress({depth}): {generated} states generated, "
                         f"{len(states)} distinct states found, "
                         f"{len(queue)} states left on queue."
                         f"{obs.eta_suffix(len(states), tel)}")
            if self.checkpoint_path and \
                    now - last_checkpoint >= ck_state["every"]:
                last_checkpoint = now
                write_checkpoint()

        # completed search: persist the FINAL checkpoint when asked (the
        # serve daemon's warm-resume source — resuming it replays the
        # stored totals over an empty queue and finishes immediately)
        if self.checkpoint_path and self.final_checkpoint:
            write_checkpoint()

        # ---- temporal properties over the completed behavior graph ----
        if live_obligations:
            from .liveness import LivenessChecker
            lc = LivenessChecker(model, states, edges, parents, labels)
            bad, live_warns = lc.check(live_obligations)
            warnings.extend(live_warns)
            if bad is not None:
                pname, trace, msg = bad
                return result(False, Violation("property", pname, trace,
                                               msg))

        self.log(f"Model checking completed. No error has been found.")
        self.log(f"{generated} states generated, {len(states)} distinct "
                 f"states found, 0 states left on queue.")
        self.log(f"The depth of the complete state graph search is "
                 f"{diameter + 1}.")
        return result(True)


def format_trace(violation: Violation) -> str:
    lines = []
    if violation.kind == "invariant":
        lines.append(f"Error: Invariant {violation.name} is violated.")
    elif violation.kind == "property":
        lines.append(f"Error: Property {violation.name} is violated"
                     + (f" ({violation.message})." if violation.message
                        else "."))
    elif violation.kind == "assert":
        lines.append(f"Error: Assertion failed: {violation.message}")
    elif violation.kind == "deadlock":
        lines.append("Error: Deadlock reached.")
    else:  # engine errors (capacity overflow, ...) — never print silently
        lines.append(f"Error: {violation.name}"
                     + (f": {violation.message}" if violation.message
                        else "."))
    if not violation.trace:
        return "\n".join(lines)
    lines.append("The behavior up to this point is:")
    for i, (st, label) in enumerate(violation.trace):
        head = "Initial predicate" if i == 0 else f"Action {label}"
        lines.append(f"State {i + 1}: <{head}>")
        for k in sorted(st.keys()):
            lines.append(f"  {k} = {fmt(st[k])}")
        lines.append("")
    return "\n".join(lines)
