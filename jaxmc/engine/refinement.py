r"""Action-level refinement checking (SURVEY.md §3.4, §7.7).

A cfg PROPERTY naming a specification formula — V!Spec through an instance
(MCPaxos.cfg:11 via Paxos.tla:195), a sibling spec of the same module
(HourClock2.cfg PROPERTY HC2), or a hand-built refinement
(MCWriteThroughCache.cfg PROPERTY LM_Inner_ISpec, MCAlternatingBit.cfg
ABCSpec) — is checked stepwise:

  * every initial state must satisfy the property's initial predicate;
  * every explored edge (s, s') must be a [PropertyNext]_sub step: either
    PropertyNext holds with state := s, primes := s', or the step
    stutters (the refined spec's subscript is unchanged).

With full primed assignments available, PropertyNext evaluates as a plain
boolean — no action enumeration needed. Substituted instance variables
evaluate through the outer state via the primed-definition rule in
sem/eval.py. WF/SF conjuncts of the property are liveness obligations and
stay reported as unchecked (the behavior-graph/SCC machinery is the
round-2+ item, ROADMAP.md).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..front import tla_ast as A
from ..sem.values import EvalError, tla_eq
from ..sem.eval import Ctx, OpClosure, eval_expr, _bool
from ..sem.modules import InstanceNamespace, Model, _split_spec


class NotASpecFormula(Exception):
    pass


class RefinementChecker:
    """One checked PROPERTY that resolves to a specification formula."""

    def __init__(self, model: Model, name: str, expr: A.Node):
        self.model = model
        self.name = name
        self.instances: List[InstanceNamespace] = []
        body, defs = self._resolve(expr, model.defs)
        try:
            self.init, self.next, self.sub, self.fair = \
                _split_spec(body, defs)
        except EvalError as ex:
            raise NotASpecFormula(str(ex))
        self.liveness_skipped = bool(self.fair)
        self.last_error = None

    def _resolve(self, expr: A.Node, defs):
        """Chase Ident -> OpClosure bodies and instance paths down to the
        spec formula; record instance namespaces entered on the way."""
        seen = set()
        while True:
            if isinstance(expr, A.Ident):
                d = defs.get(expr.name)
                if isinstance(d, OpClosure) and not d.params \
                        and expr.name not in seen:
                    seen.add(expr.name)
                    expr = d.body
                    continue
                raise NotASpecFormula(f"{expr.name} is not a definition")
            if isinstance(expr, A.OpApp) and expr.path and not expr.args:
                cur_defs = defs
                ok = True
                for iname, iargs in expr.path:
                    if iargs:
                        ok = False
                        break
                    inst = cur_defs.get(iname)
                    if not isinstance(inst, InstanceNamespace):
                        ok = False
                        break
                    self.instances.append(inst)
                    cur_defs = inst.module.defs
                if not ok:
                    raise NotASpecFormula("unresolvable instance path")
                d = cur_defs.get(expr.name)
                if not isinstance(d, OpClosure):
                    raise NotASpecFormula(f"{expr.name} not found in "
                                          f"instance")
                # build the effective defs via a dummy enter to pick up
                # substitutions at eval time; keep inner module defs for
                # _split_spec name resolution
                defs = self._entered_defs()
                expr = d.body
                continue
            return expr, defs

    def _entered_defs(self):
        ctx = self.model.ctx()
        for inst in self.instances:
            ctx = inst.enter(ctx, [])
        return ctx.defs

    def _ctx(self, state, primes) -> Ctx:
        ctx = self.model.ctx(state=state, primes=primes)
        for inst in self.instances:
            ctx = inst.enter(ctx, [])
            # keep outer state/primes visible through the chain
            ctx = Ctx(ctx.defs, ctx.bound, state, primes, self.model.vars,
                      ctx.on_print, ctx.memo)
        return ctx

    def check_init(self, state: Dict[str, Any]) -> bool:
        ctx = self._ctx(state, None)
        return _bool(eval_expr(self.init, ctx),
                     f"property {self.name} init")

    def check_edge(self, s: Dict[str, Any], s2: Dict[str, Any]) -> bool:
        """Is (s, s') a [Next]_sub step of the property spec? On failure,
        self.last_error carries any underlying evaluation error so a
        broken property is distinguishable from a real violation."""
        self.last_error = None
        ctx = self._ctx(s, s2)
        try:
            if _bool(eval_expr(self.next, ctx),
                     f"property {self.name} next"):
                return True
        except EvalError as ex:
            # an inapplicable disjunct crashed (CHOOSE with no witness,
            # partial function application): record and fall through to
            # the stuttering test
            self.last_error = str(ex)
        # stuttering: [N]_sub allows sub' = sub — evaluate the box
        # subscript (the exact tuple the refined spec observes) under both
        # states through the refinement mapping
        if self.sub is None:
            return all(tla_eq(s[v], s2[v]) for v in self.model.vars)
        try:
            now = eval_expr(self.sub, ctx)
            nxt = eval_expr(A.Prime(self.sub), ctx)
            return tla_eq(now, nxt)
        except EvalError as ex:
            self.last_error = (self.last_error or "") + f"; subscript: {ex}"
            return False


def build_refinement_checkers(model: Model):
    """Partition cfg PROPERTY entries into stepwise-checkable specification
    formulas and liveness-only formulas (returned as unchecked names)."""
    checkers: List[RefinementChecker] = []
    unchecked: List[str] = []
    for nm, expr in model.properties:
        try:
            checkers.append(RefinementChecker(model, nm, expr))
        except (NotASpecFormula, EvalError):
            unchecked.append(nm)
    return checkers, unchecked
