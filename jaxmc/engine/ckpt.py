r"""Shared checkpoint format: checksum + schema-versioned header.

One writer/loader for every engine's checkpoint (the serial Explorer,
the parallel engine's level-barrier checkpoints, and the device modes'
`_write_ck`), replacing the bare-pickle files of PR <= 3.  TLC treats
periodic checkpointing as table stakes for long runs (SURVEY.md §5,
testout1:10); what the bare pickles lacked was INTEGRITY: a clipped or
bit-rotted file unpickled into garbage (or half-garbage) and the resume
either crashed with a stack trace or silently continued from a wrong
state.  The format here makes every failure mode a one-line refusal:

    JMCKPT1\n  <4-byte big-endian header length>  <JSON header>  <pickle>

The header carries the container schema version, the engine `kind`
("interp" for the host engines' shared state-table format, "device" for
the lane-encoded device formats), the payload byte length, and the
payload's sha256.  `load_checkpoint` verifies all four before a single
pickle byte is trusted and raises `CkptError` — a ValueError subclass
with an actionable one-liner — on any mismatch.  cli.py maps CkptError
to exit status 2 (usage/error), never a traceback.

Writes are atomic (sibling tmp file + fsync + os.replace), so a crash
mid-write leaves the previous checkpoint intact.  The ckpt_corrupt
fault site (jaxmc/faults.py) damages the file AFTER the rename — the
test harness for post-write disk corruption.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
from typing import Any, Dict, Optional, Tuple

from .. import faults

MAGIC = b"JMCKPT1\n"
CKPT_SCHEMA = 1  # container schema (payload schemas are the engines')

_REMEDY = ("fall back to an older checkpoint or restart the run from "
           "scratch")


class CkptError(ValueError):
    """A checkpoint cannot be written/read/trusted. The message is a
    complete one-line diagnosis + remedy; cli.py maps it to exit 2."""


def write_checkpoint(path: str, kind: str, meta: Dict[str, Any],
                     payload: Dict[str, Any]) -> int:
    """Atomically write `payload` under a checksummed header.  Returns
    the total bytes written (telemetry).  Raises CkptError on I/O
    failure (disk full mid-checkpoint must not kill the search — the
    engines catch and keep running on the previous checkpoint)."""
    body = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    header = {"schema": CKPT_SCHEMA, "kind": kind,
              "sha256": hashlib.sha256(body).hexdigest(),
              "payload_bytes": len(body), "meta": meta}
    hb = json.dumps(header, sort_keys=True).encode("utf-8")
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as fh:
            fh.write(MAGIC)
            fh.write(struct.pack(">I", len(hb)))
            fh.write(hb)
            fh.write(body)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError as ex:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise CkptError(f"cannot write checkpoint {path}: {ex}")
    faults.corrupt_file("ckpt_corrupt", path, kind=kind)
    return len(MAGIC) + 4 + len(hb) + len(body)


def _read_header_at(path: str) -> Tuple[Dict[str, Any], int]:
    """(header, payload byte offset).  The offset is the ACTUAL file
    position after the header bytes — never re-derived by re-serializing
    the parsed JSON, which could differ byte-for-byte from what the
    writer produced."""
    try:
        with open(path, "rb") as fh:
            magic = fh.read(len(MAGIC))
            if magic != MAGIC:
                raise CkptError(
                    f"cannot resume: {path} is not a jaxmc checkpoint "
                    f"(bad header — written by an incompatible jaxmc "
                    f"version or another tool?); re-run with a file "
                    f"written by --checkpoint")
            raw = fh.read(4)
            if len(raw) != 4:
                raise CkptError(
                    f"cannot resume: {path} is truncated inside the "
                    f"header; {_REMEDY}")
            (hlen,) = struct.unpack(">I", raw)
            hb = fh.read(hlen)
            offset = fh.tell()
    except FileNotFoundError:
        raise CkptError(
            f"cannot resume: no checkpoint at {path}; pass a file "
            f"written by --checkpoint")
    except OSError as ex:
        raise CkptError(f"cannot resume: {path} is unreadable ({ex})")
    if len(hb) != hlen:
        raise CkptError(
            f"cannot resume: {path} is truncated inside the header; "
            f"{_REMEDY}")
    try:
        header = json.loads(hb.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        raise CkptError(
            f"cannot resume: {path} has a corrupt header; {_REMEDY}")
    if not isinstance(header, dict) or "sha256" not in header:
        raise CkptError(
            f"cannot resume: {path} has a malformed header; {_REMEDY}")
    if header.get("schema") != CKPT_SCHEMA:
        raise CkptError(
            f"cannot resume: {path} uses checkpoint schema "
            f"{header.get('schema')!r}, this build reads "
            f"{CKPT_SCHEMA!r}; re-checkpoint with a matching jaxmc")
    return header, offset


def read_header(path: str) -> Dict[str, Any]:
    """Parse and sanity-check the header only (no payload read)."""
    return _read_header_at(path)[0]


def load_checkpoint(path: str, kind: Optional[str] = None
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Verify integrity end to end and return (header, payload).  Every
    defect is a CkptError naming the file, the defect, and the remedy —
    a corrupt checkpoint must never unpickle."""
    header, offset = _read_header_at(path)
    if kind is not None and header.get("kind") != kind:
        raise CkptError(
            f"cannot resume: {path} was written by the "
            f"{header.get('kind')!r} engine, this run expects {kind!r} "
            f"(re-run with the backend/flags of the writing run)")
    want = int(header.get("payload_bytes", -1))
    with open(path, "rb") as fh:
        fh.seek(offset)
        body = fh.read()
    if len(body) != want:
        raise CkptError(
            f"cannot resume: {path} is truncated ({len(body)} of {want} "
            f"payload bytes — the file was clipped after it was "
            f"written); {_REMEDY}")
    if hashlib.sha256(body).hexdigest() != header["sha256"]:
        raise CkptError(
            f"cannot resume: {path} failed its integrity check (sha256 "
            f"mismatch — the file is corrupt); {_REMEDY}")
    try:
        payload = pickle.loads(body)
    except Exception as ex:  # noqa: BLE001 — any unpickle defect
        raise CkptError(
            f"cannot resume: {path} passed its checksum but failed to "
            f"unpickle ({type(ex).__name__}: {ex}) — it was written by "
            f"an incompatible jaxmc build; {_REMEDY}")
    if not isinstance(payload, dict):
        raise CkptError(
            f"cannot resume: {path} does not hold a jaxmc checkpoint "
            f"payload; {_REMEDY}")
    return header, payload


def write_periodic(path: str, kind: str, meta: Dict[str, Any],
                   payload: Dict[str, Any], tel, log,
                   ck_state: Dict[str, Any],
                   span_attrs: Optional[Dict[str, Any]] = None) -> bool:
    """The engines' shared PERIODIC checkpoint write: span + the
    adaptive interval stretch (write cost capped at ~5% of wall, the
    serial engine's PR-3 rule) + the TLC-style log line — and, crucially,
    NON-FATAL: a failed write (disk full, permissions) logs a warning
    and returns False so the search keeps running on the previous
    checkpoint instead of dying with all in-memory progress.  Resume-
    side defects stay fatal (load_checkpoint raises).  `ck_state` is the
    engine's {"every": seconds, ...} dict, mutated in place."""
    import time
    t_ck = time.time()
    try:
        with tel.span("checkpoint.write", **(span_attrs or {})):
            write_checkpoint(path, kind, meta, payload)
    except CkptError as ex:
        tel.counter("checkpoint.write_failures")
        log(f"WARNING: checkpoint write failed ({ex}); the run "
            f"continues on the previous checkpoint")
        return False
    write_s = time.time() - t_ck
    if write_s * 20.0 > ck_state["every"]:
        ck_state["every"] = write_s * 20.0
        log(f"Checkpoint write took {write_s:.1f}s; interval "
            f"stretched to {ck_state['every']:.0f}s")
    log(f"Checkpointing run to {path}")
    return True


# ------------------------------------------- the interp payload contract

def interp_payload(model, vars, states, parents, labels, depth_of,
                   queue, generated, diameter, seen, edges, collect_edges,
                   prints) -> Dict[str, Any]:
    """The host engines' shared checkpoint payload: the serial Explorer,
    the parallel engine's level barriers, and the device path's host
    snapshot all write THIS shape, so any of them can resume any
    other's checkpoint."""
    return dict(module=model.module.name, vars=list(vars),
                states=list(states), parents=list(parents),
                labels=list(labels), depth_of=list(depth_of),
                queue=list(queue), generated=generated,
                diameter=diameter, seen_items=list(seen.items()),
                edges=list(edges) if collect_edges else None,
                prints=list(prints))


def load_interp_checkpoint(path: str, model, vars,
                           collect_edges: bool) -> Dict[str, Any]:
    """Load + validate an interp-format checkpoint against THIS model
    and this run's needs.  Returns the payload dict; raises CkptError
    with the defect (wrong module/vars, missing edge log, ...)."""
    _, ck = load_checkpoint(path, kind="interp")
    if "states" not in ck or "seen_items" not in ck:
        raise CkptError(
            f"cannot resume: {path} was written by an incompatible "
            f"jaxmc version (missing state-table fields); {_REMEDY}")
    if ck.get("module") != model.module.name or \
            ck.get("vars") != list(vars):
        raise CkptError(
            f"cannot resume: checkpoint {path} is for module "
            f"{ck.get('module')!r} with variables {ck.get('vars')}, not "
            f"{model.module.name!r} — point --resume at a checkpoint "
            f"written for this spec")
    if collect_edges and ck.get("edges") is None:
        # liveness needs the FULL edge log; a checkpoint written
        # without one cannot support temporal checking
        raise CkptError(
            "cannot resume with temporal properties: the checkpoint "
            "has no edge log (it was written without PROPERTY "
            "obligations); re-run from scratch")
    return ck
