r"""Multi-chip mesh bench + parity harness: `python -m jaxmc.meshbench`.

ISSUE 8 closes ROADMAP item 1's measurement gap: the mesh-sharded
engine (tpu/mesh.py — owner-routed a2a dedup, device-resident level
loop) needs (a) PARITY legs proving bit-identical counts against the
manifest pins at several device counts, and (b) a SCALING CURVE
(states/sec/chip over D) published as a MULTICHIP_r* artifact.  Both
run per-D in fresh subprocesses because the device count is fixed at
jax init: each child forces `XLA_FLAGS=--xla_force_host_platform_
device_count=D` virtual CPU devices (real chips when
JAXMC_MESHBENCH_PLATFORM names an accelerator platform with enough
devices).

Subcommands
  check   D in {2,4} (default) parity legs over the repo-local rungs
          (viewtoy_scaled / symtoy_scaled + MCraft_micro when the
          reference corpus is mounted): counts must equal the corpus
          manifest pins, host_syncs may never exceed the level count
          (it counts SUPERSTEPS since ISSUE 10, so it is usually well
          below), and each leg's jaxmc.metrics/2 artifact gates like
          every bench-check leg via
          `python -m jaxmc.obs diff --fail-on-regress` against a saved
          baseline (first run snapshots it).  `--merge fullsort` runs
          the same leg under the JAXMC_MESH_RANKMERGE=0 escape hatch
          (the Makefile's rank-merge parity leg).  Wired into
          `make bench-check` via `make multichip-check`.
  bench   D in {1,2,4,8} (default) timed legs over the bench rungs
          (MCraft_3s_bench + transfer_scaled): per D, one warm-up run
          (compile + capacity training + profile persist) then a timed
          fully-warm run — states/sec/chip, per-level exchange bytes,
          shard balance, host_syncs <= levels (supersteps working),
          window_recompiles (must be 0 on the warm run) and the
          measured expand/exchange/merge phase-wall breakdown
          (probe_phase_walls — both merge strategies timed, so the
          rank win is in the artifact).  Writes the MULTICHIP_r*
          artifact (--out) plus per-leg metrics artifacts, gated the
          same way when baselines exist; two MULTICHIP_r* artifacts
          diff directly via `python -m jaxmc.obs diff`.
  child   one (spec, D) leg — internal.

Rungs that need the reference corpus (the MCraft family EXTENDS the
reference raft.tla) emit a parseable `MESHBENCH SKIP` line in builder
containers instead of failing, exactly like bench.py (ISSUE 6).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_RESULT_TAG = "MESHBENCH_RESULT "

# the default rung sets (spec paths relative to the repo root; cfg
# auto-discovered next to the spec unless given)
CHECK_RUNGS = [
    ("specs/viewtoy_scaled.tla", None),
    ("specs/symtoy_scaled.tla", None),
    ("specs/MCraftMicro.tla", "specs/MCraft_micro.cfg"),
]
BENCH_RUNGS = [
    ("specs/MCraftMicro.tla", "specs/MCraft_3s_bench.cfg"),
    ("specs/transfer_scaled.tla", None),
]


def _needs_reference(spec: str, cfg: Optional[str]) -> Optional[str]:
    """A SKIP reason when this rung cannot load in this container."""
    from .corpus import REFERENCE, case_for_cfg
    cfgb = os.path.basename(cfg) if cfg else \
        os.path.basename(os.path.splitext(spec)[0] + ".cfg")
    case = case_for_cfg(cfgb)
    needs = case is not None and (case.root == "ref" or case.includes)
    if needs and not os.path.isdir(os.path.join(REFERENCE, "examples")):
        return (f"reference corpus not mounted at {REFERENCE} "
                f"(driver environment only)")
    return None


def _leg_name(spec: str, cfg: Optional[str]) -> str:
    base = os.path.splitext(os.path.basename(cfg or spec))[0]
    return base


def _run_child(spec: str, cfg: Optional[str], devices: int,
               exchange: Optional[str], timed: bool, out_dir: str,
               store_trace: bool, timeout_s: float,
               merge: Optional[str] = None,
               phase_probe: bool = False,
               log=print) -> Dict:
    name = _leg_name(spec, cfg)
    suffix = f"_{merge}" if merge else ""
    # artifacts (and therefore the saved baselines _gate snapshots) are
    # NAMESPACED by platform (ISSUE 11): a cpu virtual-device baseline
    # must never gate a real-chip run — each backend regates its own
    plat = os.environ.get("JAXMC_MESHBENCH_PLATFORM", "cpu")
    metrics = os.path.join(
        out_dir,
        f"jaxmc_multichip_{plat}_{name}_d{devices}{suffix}.json")
    # pre-ISSUE-11 baselines had no platform segment; those were all
    # measured on cpu virtual devices, so migrate them into the cpu
    # namespace instead of silently re-seeding the gate from current
    # performance (which would wave a regression through once)
    base = metrics.replace(".json", ".baseline.json")
    legacy = os.path.join(
        out_dir,
        f"jaxmc_multichip_{name}_d{devices}{suffix}.baseline.json")
    if plat == "cpu" and not os.path.exists(base) \
            and os.path.exists(legacy):
        os.replace(legacy, base)
        log(f"meshbench: migrated pre-backend baseline -> "
            f"{os.path.basename(base)}")
    cmd = [sys.executable, "-m", "jaxmc.meshbench", "child",
           "--spec", spec, "--devices", str(devices),
           "--metrics-out", metrics]
    if cfg:
        cmd += ["--cfg", cfg]
    if exchange:
        cmd += ["--exchange", exchange]
    if merge:
        cmd += ["--merge", merge]
    if timed:
        cmd += ["--timed"]
    if phase_probe:
        cmd += ["--phase-probe"]
    if store_trace:
        cmd += ["--store-trace"]
    env = dict(os.environ, PYTHONPATH=_REPO)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=_REPO, env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "leg": name, "devices": devices,
                "error": f"timed out after {timeout_s:.0f}s"}
    for line in (p.stdout or "").splitlines():
        if line.startswith(_RESULT_TAG):
            r = json.loads(line[len(_RESULT_TAG):])
            r["leg"] = name
            r["metrics_path"] = metrics
            r["child_wall_s"] = round(time.time() - t0, 3)
            return r
    tail = ((p.stderr or "") + (p.stdout or "")).strip() \
        .splitlines()[-3:] or ["no output"]
    return {"ok": False, "leg": name, "devices": devices,
            "error": f"child rc={p.returncode}: "
                     + " | ".join(t[:160] for t in tail)}


def _gate(metrics_path: str, log=print,
          ignore_phases: Tuple[str, ...] = ()) -> int:
    """Gate one leg's artifact against its saved baseline via
    `python -m jaxmc.obs diff --fail-on-regress` (first run snapshots
    the baseline, like make bench-check).  `ignore_phases` passes
    through to the diff (the backend-check leg excludes its cold-start
    compile walls — see jaxmc/backend/check.py)."""
    base = metrics_path.replace(".json", ".baseline.json")
    if not os.path.exists(metrics_path):
        return 0
    if not os.path.exists(base):
        import shutil
        shutil.copyfile(metrics_path, base)
        log(f"meshbench: baseline saved -> {base}")
        return 0
    from .obs.report import main as obs_main
    log(f"meshbench: gating {os.path.basename(metrics_path)} vs "
        f"saved baseline")
    argv = ["diff", "--fail-on-regress", "--threshold", "25"]
    if ignore_phases:
        argv += ["--ignore-phases", ",".join(ignore_phases)]
    return obs_main(argv + [base, metrics_path])


def cmd_check(args) -> int:
    failures = 0
    from .corpus import case_for_cfg
    for spec, cfg in args.rungs:
        skip = _needs_reference(spec, cfg)
        name = _leg_name(spec, cfg)
        if skip:
            print(f"MESHBENCH SKIP {name}: {skip}")
            continue
        cfgb = os.path.basename(
            cfg or os.path.splitext(spec)[0] + ".cfg")
        case = case_for_cfg(cfgb)
        for D in args.devices:
            # timed=True: the gated artifact measures the fully-warm
            # second run — one-shot cold walls are dominated by
            # compile/caps noise and would flap the 25% diff gate on a
            # loaded box
            r = _run_child(spec, cfg, D, args.exchange, True,
                           args.out_dir, store_trace=False,
                           timeout_s=args.timeout, merge=args.merge)
            if not r.get("ok"):
                print(f"MESHBENCH FAIL {name} D={D}: "
                      f"{r.get('error', r)}")
                failures += 1
                continue
            want = (case.generated, case.distinct) if case else None
            got = (r["generated"], r["distinct"])
            if want and want != got:
                print(f"MESHBENCH FAIL {name} D={D}: counts {got} != "
                      f"pinned {want}")
                failures += 1
                continue
            if r["host_syncs"] > r["levels"]:
                # one scalar-ring read per SUPERSTEP (ISSUE 10):
                # host_syncs may be well below the level count but can
                # never exceed it — more syncs than levels means row
                # traffic leaked into the level loop.  Validate BEFORE
                # the parseable ok-line: a leg must never print both
                # ok and FAIL
                print(f"MESHBENCH FAIL {name} D={D}: host_syncs "
                      f"{r['host_syncs']} > levels {r['levels']} "
                      f"(row traffic leaked into the level loop)")
                failures += 1
                continue
            print(f"MESHBENCH ok {name} D={D} exchange="
                  f"{r['exchange']} merge={r.get('merge')}: "
                  f"{r['generated']} gen / "
                  f"{r['distinct']} distinct "
                  f"({r['states_per_sec']:,.0f} st/s, host_syncs="
                  f"{r['host_syncs']}, levels={r['levels']}, "
                  f"spill={r.get('a2a_spill', 0)})")
            if _gate(r["metrics_path"]):
                failures += 1
    print(f"meshbench check: {'FAIL' if failures else 'ok'} "
          f"({failures} failing legs)")
    return 1 if failures else 0


def cmd_bench(args) -> int:
    from . import obs
    rungs_out: List[Dict] = []
    failures = 0
    for spec, cfg in args.rungs:
        name = _leg_name(spec, cfg)
        skip = _needs_reference(spec, cfg)
        if skip:
            print(f"MESHBENCH SKIP {name}: {skip}")
            rungs_out.append({"rung": name, "spec": spec, "cfg": cfg,
                              "skipped": skip})
            continue
        curve: List[Dict] = []
        for D in args.devices:
            r = _run_child(spec, cfg, D, args.exchange, True,
                           args.out_dir, store_trace=False,
                           timeout_s=args.timeout, merge=args.merge,
                           phase_probe=True)
            if not r.get("ok"):
                print(f"MESHBENCH FAIL {name} D={D}: "
                      f"{r.get('error', r)}")
                failures += 1
                curve.append({"devices": D,
                              "error": r.get("error", "failed")})
                continue
            point = {k: r[k] for k in
                     ("devices", "exchange", "merge", "generated",
                      "distinct", "wall_s", "warmup_wall_s",
                      "states_per_sec",
                      "states_per_sec_per_chip", "window_recompiles",
                      "host_syncs", "levels", "supersteps",
                      "superstep_levels", "exchange_bytes",
                      "exchange_bytes_per_level", "phase_walls")
                     if k in r}
            for k in ("a2a_gamma", "a2a_spill", "a2a_max_bucket",
                      "shard_balance"):
                if k in r:
                    point[k] = r[k]
            curve.append(point)
            print(f"MESHBENCH point {name} D={D}: "
                  f"{r['states_per_sec']:,.0f} st/s "
                  f"({r['states_per_sec_per_chip']:,.0f} /chip), "
                  f"recompiles={r['window_recompiles']}, "
                  f"host_syncs={r['host_syncs']}/{r['levels']} lvls, "
                  f"xbytes/lvl={r['exchange_bytes_per_level']:,}, "
                  f"balance={r.get('shard_balance')}")
            if r["window_recompiles"] != 0:
                print(f"MESHBENCH FAIL {name} D={D}: warm run "
                      f"recompiled {r['window_recompiles']}x inside "
                      f"the window")
                failures += 1
            if r["host_syncs"] > r["levels"]:
                print(f"MESHBENCH FAIL {name} D={D}: host_syncs "
                      f"{r['host_syncs']} > levels {r['levels']}")
                failures += 1
            if _gate(r["metrics_path"]):
                failures += 1
        rungs_out.append({"rung": name, "spec": spec, "cfg": cfg,
                          "curve": curve})
    env = obs.environment_meta()
    art = {
        "schema": "jaxmc.multichip/1",
        "generated_at": time.time(),
        "mode": "mesh-resident",
        "platform": os.environ.get("JAXMC_MESHBENCH_PLATFORM", "cpu"),
        "virtual_devices":
            os.environ.get("JAXMC_MESHBENCH_PLATFORM", "cpu") == "cpu",
        "env": env,
        "devices_swept": list(args.devices),
        "rungs": rungs_out,
        "ok": failures == 0,
    }
    obs.write_json_atomic(args.out, art)
    try:  # ISSUE 17: land the per-chip curve in the run ledger too
        from .obs import ledger as _ledger
        _ledger.import_artifacts([args.out])
    except Exception:  # noqa: BLE001 — the ledger never breaks a gate
        pass
    print(f"meshbench: wrote {args.out} "
          f"({'FAIL' if failures else 'ok'}, {len(rungs_out)} rungs)")
    return 1 if failures else 0


def cmd_child(args) -> int:
    if args.merge:
        # the merge strategy is read from the environment at engine
        # build (tpu/mesh.py): rank is the default, 0 forces fullsort
        os.environ["JAXMC_MESH_RANKMERGE"] = \
            "0" if args.merge == "fullsort" else "1"
    plat = os.environ.get("JAXMC_MESHBENCH_PLATFORM", "cpu")
    if plat == "cpu":
        # must precede ANY jax import in this process
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags.strip() +
            f" --xla_force_host_platform_device_count={args.devices}")
    import numpy as np
    import jax
    if plat == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh
    from . import obs
    from .front.cfg import ModelConfig, parse_cfg
    from .sem.modules import Loader, bind_model
    from .corpus import case_for_cfg
    from .backend.mesh import MeshExplorer

    spec = os.path.join(_REPO, args.spec) \
        if not os.path.isabs(args.spec) else args.spec
    cfgp = args.cfg
    if cfgp is None:
        guess = os.path.splitext(spec)[0] + ".cfg"
        cfgp = guess if os.path.exists(guess) else None
    elif not os.path.isabs(cfgp):
        cfgp = os.path.join(_REPO, cfgp)
    if cfgp:
        with open(cfgp, encoding="utf-8") as fh:
            mc = parse_cfg(fh.read())
    else:
        mc = ModelConfig(specification="Spec")
    case = case_for_cfg(os.path.basename(cfgp)) if cfgp else None
    if case is not None and case.no_deadlock:
        mc.check_deadlock = False
    search = [os.path.dirname(spec)]
    if case is not None:
        search += case.include_dirs()
    model = bind_model(Loader(search).load_path(spec), mc)

    devs = jax.devices()
    if len(devs) < args.devices:
        print(f"error: need {args.devices} devices, have {len(devs)}",
              file=sys.stderr)
        return 2
    mesh = Mesh(np.array(devs[:args.devices]), ("d",))

    tel = obs.Telemetry(meta={"backend": "jax-mesh",
                              "devices": args.devices})
    with obs.use(tel):
        mesh_caps = dict(case.mesh_caps) \
            if case is not None and case.mesh_caps else None
        me = MeshExplorer(model, mesh=mesh,
                          exchange=args.exchange or None,
                          store_trace=args.store_trace,
                          mesh_caps=mesh_caps)
        t0 = time.time()
        r = me.run()
        warm_wall = time.time() - t0
        result, wall = r, warm_wall
        window_recompiles = sum(1 for lv in tel.levels
                                if lv.get("fresh_compile"))
        lvl0, sync0, xb0 = (len(tel.levels),
                            tel.counters.get("mesh.host_syncs", 0),
                            tel.counters.get("mesh.exchange_bytes", 0))
        if args.timed:
            # the measured window: a fully-warm re-run on the same
            # engine (in-process jit cache + learned caps) — the
            # steady-state methodology of PR 5/6, per device count
            t0 = time.time()
            result = me.run()
            wall = time.time() - t0
            window_recompiles = sum(
                1 for lv in tel.levels[lvl0:] if lv.get("fresh_compile"))
        phase_walls = me.probe_phase_walls() if args.phase_probe \
            else None
    levels = len(tel.levels) - (lvl0 if args.timed else 0)
    host_syncs = tel.counters.get("mesh.host_syncs", 0) - \
        (sync0 if args.timed else 0)
    xbytes = tel.counters.get("mesh.exchange_bytes", 0) - \
        (xb0 if args.timed else 0)
    out = {
        "ok": bool(result.ok),
        "devices": args.devices,
        "exchange": me.exchange,
        "merge": me.merge,
        "generated": int(result.generated),
        "distinct": int(result.distinct),
        "diameter": int(result.diameter),
        "truncated": bool(result.truncated),
        "wall_s": round(wall, 6),
        "warmup_wall_s": round(warm_wall, 6),
        "states_per_sec": round(result.generated / max(wall, 1e-9), 3),
        "states_per_sec_per_chip": round(
            result.generated / max(wall, 1e-9) / args.devices, 3),
        "window_recompiles": window_recompiles,
        "host_syncs": host_syncs,
        # host_syncs counts SUPERSTEPS (ISSUE 10): one scalar-ring
        # read per dispatch; `levels` stays the per-level record count
        "supersteps": host_syncs,
        "levels": levels,
        "exchange_bytes": int(xbytes),
        "exchange_bytes_per_level": int(xbytes / max(levels, 1)),
    }
    if phase_walls:
        out["phase_walls"] = phase_walls
    for k, src in (("superstep_levels", "mesh.superstep_levels"),
                   ("a2a_gamma", "mesh.a2a_gamma"),
                   ("a2a_spill", "mesh.a2a_spill"),
                   ("a2a_max_bucket", "mesh.a2a_max_bucket"),
                   ("shard_balance", "mesh.shard_balance")):
        if src in tel.gauges:
            out[k] = tel.gauges[src]
    if args.metrics_out:
        summary = tel.summary(result={
            "ok": bool(result.ok), "distinct": int(result.distinct),
            "generated": int(result.generated),
            "diameter": int(result.diameter),
            "truncated": bool(result.truncated),
            "wall_s": round(wall, 6)})
        summary["backend"] = "jax"
        summary["spec"] = args.spec
        summary["multichip"] = {k: out[k] for k in
                                ("devices", "exchange", "merge",
                                 "states_per_sec",
                                 "states_per_sec_per_chip",
                                 "window_recompiles", "host_syncs",
                                 "supersteps", "superstep_levels",
                                 "levels", "phase_walls",
                                 "exchange_bytes_per_level")
                                if k in out}
        obs.write_json_atomic(args.metrics_out, summary)
        # ISSUE 17: every bench child lands its trajectory point in the
        # persistent run ledger (never raises, JAXMC_LEDGER=off disables)
        obs.append_summary(summary, source=args.metrics_out)
    print(_RESULT_TAG + json.dumps(out), flush=True)
    return 0


def _parse_rungs(vals: Optional[List[str]], default) -> List:
    if not vals:
        return list(default)
    out = []
    for v in vals:
        if "=" in v:
            s, c = v.split("=", 1)
            out.append((s, c))
        else:
            out.append((v, None))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.meshbench",
        description="multi-chip mesh parity + scaling harness")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p, dflt_devices):
        p.add_argument("--devices", default=dflt_devices,
                       help="comma-separated device counts")
        p.add_argument("--exchange", default=None,
                       choices=(None, "a2a", "gather"),
                       help="override the per-D default strategy")
        p.add_argument("--merge", default=None,
                       choices=(None, "rank", "fullsort"),
                       help="pin the dedup-merge strategy (default: "
                            "the engine default, rank; the fullsort "
                            "leg proves escape-hatch parity)")
        p.add_argument("--rung", action="append", default=None,
                       help="spec[=cfg], repeatable (repo-relative)")
        p.add_argument("--out-dir", default=os.environ.get(
            "JAXMC_PROBE_DIR", "/tmp"))
        p.add_argument("--timeout", type=float, default=float(
            os.environ.get("JAXMC_MESHBENCH_TIMEOUT", "900")))

    pc = sub.add_parser("check", help="parity legs (make multichip-check)")
    common(pc, "2,4")
    pb = sub.add_parser("bench", help="scaling curve (make multichip-bench)")
    common(pb, "1,2,4,8")
    pb.add_argument("--out", default=os.path.join(_REPO,
                                                  "MULTICHIP_r07.json"))
    pch = sub.add_parser("child")
    pch.add_argument("--spec", required=True)
    pch.add_argument("--cfg", default=None)
    pch.add_argument("--devices", type=int, required=True)
    pch.add_argument("--exchange", default=None)
    pch.add_argument("--merge", default=None,
                     choices=(None, "rank", "fullsort"))
    pch.add_argument("--timed", action="store_true")
    pch.add_argument("--phase-probe", action="store_true")
    pch.add_argument("--store-trace", action="store_true")
    pch.add_argument("--metrics-out", default=None)
    args = ap.parse_args(argv)

    if args.cmd == "child":
        return cmd_child(args)
    args.devices = [int(x) for x in str(args.devices).split(",") if x]
    args.rungs = _parse_rungs(
        args.rung, CHECK_RUNGS if args.cmd == "check" else BENCH_RUNGS)
    return cmd_check(args) if args.cmd == "check" else cmd_bench(args)


if __name__ == "__main__":
    sys.exit(main())
