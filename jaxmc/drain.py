r"""Cooperative drain: graceful shutdown for every engine (ISSUE 7).

Before this module, SIGTERM killed a `check` (or a bench child, or a
serve worker) wherever it stood: open spans never closed, the watchdog
thread died mid-beat, and hours of search state evaporated because the
periodic checkpoint had not fired yet.  The fix is COOPERATIVE: a signal
handler (or the serve daemon's drain endpoint) only *requests* a drain
here; every engine polls `requested()` at its next safe boundary — the
serial BFS pop, the parallel engine's level barrier, a device mode's
inter-dispatch gap — writes a checkpoint if one was configured, and
returns a truncated `CheckResult` with `drained=True` and the NAMED
reason in its warnings.  The normal return path then unwinds through
the CLI/session `finally` blocks, so spans close, the watchdog joins,
and the metrics artifact is written — nothing is lost and nothing
leaks.

Exit-code contract: a drained `check` exits with DRAIN_EXIT_CODE (143,
the conventional 128+SIGTERM), never 0 (the search did NOT complete)
and never 2 (nothing was wrong with the invocation).  The serve daemon
reuses the same flag for its SIGTERM drain: in-flight jobs checkpoint
and re-queue, then the daemon exits 0 (a drained daemon IS a clean
daemon).

The state is process-global on purpose: one SIGTERM must drain every
engine the process is running (the serve daemon runs several at once).
`clear()` re-arms the process (the daemon clears after a completed
drain-and-restart cycle in tests; the CLI never needs to).
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Optional

DRAIN_EXIT_CODE = 143  # 128 + SIGTERM: "terminated, but gracefully"

_EVENT = threading.Event()
_LOCK = threading.Lock()
_REASON: Optional[str] = None
_INSTALLED = False


def request(reason: str) -> None:
    """Ask every engine in this process to checkpoint and stop at its
    next safe boundary.  First reason wins (it names the cause in every
    warning/exit line); repeat requests are no-ops."""
    global _REASON
    with _LOCK:
        if _REASON is None:
            _REASON = str(reason)
    _EVENT.set()


def requested() -> bool:
    return _EVENT.is_set()


def reason() -> str:
    with _LOCK:
        return _REASON or "drain requested"


def clear() -> None:
    """Re-arm (serve daemon restart cycles, tests)."""
    global _REASON
    with _LOCK:
        _REASON = None
    _EVENT.clear()


def install(signals=(signal.SIGTERM,),
            on_request: Optional[Callable[[str], None]] = None) -> bool:
    """Install the drain handler on `signals` (main thread only —
    Python restricts signal.signal to it; returns False elsewhere, and
    the caller keeps working without graceful drain).

    First signal: request a drain (engines checkpoint and stop).
    Second signal of the same kind: the operator means it — exit HARD
    with DRAIN_EXIT_CODE (a wedged engine must not make the process
    unkillable short of SIGKILL)."""
    global _INSTALLED
    if threading.current_thread() is not threading.main_thread():
        return False
    seen = {"signals": 0}

    def _handler(signum, frame):
        name = signal.Signals(signum).name
        # count SIGNALS, not drain requests: a drain begun some other
        # way (POST /drain, a programmatic request) must not turn the
        # operator's first, routine SIGTERM into a hard kill — only a
        # REPEATED signal says "stop waiting for the safe boundary"
        seen["signals"] += 1
        if seen["signals"] > 1:
            os._exit(DRAIN_EXIT_CODE)  # second signal: hard exit
        request(f"signal {name}")
        if on_request is not None:
            try:
                on_request(name)
            except Exception:  # noqa: BLE001 — a drain hook must never
                pass           # turn a graceful stop into a crash

    for sig in signals:
        signal.signal(sig, _handler)
    _INSTALLED = True
    return True
