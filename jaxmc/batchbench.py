r"""Cross-model batching bench leg (ISSUE 13): `python -m jaxmc.batchbench`.

The whole point of the vmapped multi-model engine is that a cohort of N
layout-compatible jobs costs ONE engine (one layout, one kernel set,
one XLA program) instead of N.  This driver turns that into a GATE over
the repo-local batchtoy family (one module, cfgs differing only in
liftable constant values), with two measured legs:

  COLD COHORT (the gated one — the serve acceptance scenario "N
  compatible jobs submitted cold -> one vmapped dispatch sequence"):
    sequential  each member pays its own full cold cost: model load,
                layout sampling, kernel build, XLA compile, search —
                the pre-PR-13 fleet's cost for a cold cohort;
    batched     ONE BatchCheckEngine: one donor build (union-sampled
                layout), one jit(vmap(hstep_core)) compile, one
                vmapped dispatch sequence.
    Aggregate cold states/sec must be >= GATE_X (default 2.0,
    JAXMC_BATCH_GATE_X) times sequential: compile/build amortization
    across the cohort is the dominant, reproducible fleet win on
    CPU-XLA containers.

  WARM DEEP RUNG (reported, informational — no gate):
    the batchtoy_bench* deep-narrow rungs, warm engines both sides,
    identical job options.  On CPU-XLA the per-dispatch overhead the
    vmapped sharing amortizes is ~0.5ms — the same order as the
    per-level host bookkeeping — so the warm same-option ratio sits
    near 1x in this container (measured 0.95-1.1x; BASELINE.md), and a
    wall-based gate would only measure machine noise (identical legs
    swing 2x run-to-run here).  The warm win is LATENCY-bound: on real
    accelerator tunnels (PAPER.md's ~160ms round trip) one dispatch
    for B members vs B dispatches is decisive — that measurement is
    the standing driver-env task.  The warm artifacts are written for
    inspection (`obs report`/`obs diff` by hand).

Per-member counts must be BIT-IDENTICAL between legs in BOTH scenarios
(batching is a throughput optimization, never a semantics change), and
the cold cohort must reach full occupancy (every member in one vmapped
program).  Environments where the leg cannot run (no jax, no native
store) print a parseable `BATCH-CHECK SKIP: <reason>` line and exit 0.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_SPEC = os.path.join(_REPO, "specs", "batchtoy.tla")
COLD_CFGS = [os.path.join(_REPO, "specs", f"batchtoy_{v}.cfg")
             for v in ("a", "b", "c", "d")]
WARM_CFGS = [os.path.join(_REPO, "specs", f"batchtoy_bench{i}.cfg")
             for i in (1, 2, 3, 4)]


def _skip(reason: str) -> int:
    print(f"BATCH-CHECK SKIP: {reason}")
    return 0


def _artifact(path: str, leg: str, wall_s: float, distinct: int,
              generated: int, members: int, occupancy: int,
              dispatches: Optional[int], lifted: List[str]) -> None:
    from . import obs
    env = obs.environment_meta()
    env["platform"] = "cpu"
    gauges = {"batch.members": members,
              "batch.occupancy": occupancy,
              "batchbench.leg": leg,
              "batch.lifted_consts": lifted}
    if dispatches is not None:
        gauges["batch.dispatch_count"] = dispatches
    art = {
        "schema": "jaxmc.metrics/2",
        "started_at": time.time(),
        "wall_s": round(wall_s, 6),
        "backend": "jax",
        "spec": DEFAULT_SPEC,
        "phases": [{"name": "search", "wall_s": round(wall_s, 6),
                    "count": members}],
        "counters": {},
        "gauges": gauges,
        "levels": [],
        "env": env,
        "result": {"ok": True, "distinct": distinct,
                   "generated": generated, "diameter": 0,
                   "truncated": False, "wall_s": round(wall_s, 6)},
    }
    obs.write_json_atomic(path, art)
    # ISSUE 17: each gate leg lands a trajectory point in the run ledger
    obs.append_summary(art, source=path)


def _counts(r):
    return (r.ok, r.distinct, r.generated, r.diameter)


def _parity_or_fail(tag: str, cfgs, solo_results, members, log) -> bool:
    for c, sr, mem in zip(cfgs, solo_results, members):
        if mem.error is not None:
            log(f"BATCH-CHECK FAIL [{tag}]: member "
                f"{os.path.basename(c)} errored: {mem.error}")
            return False
        if _counts(sr) != _counts(mem.result):
            log(f"BATCH-CHECK FAIL [{tag}]: {os.path.basename(c)} "
                f"counts diverge: solo {_counts(sr)} vs batched "
                f"{_counts(mem.result)}")
            return False
    return True


def run_leg(spec: str, cold_cfgs: List[str], warm_cfgs: List[str],
            out_dir: str, log=print) -> int:
    try:
        import jax.numpy as jnp
    except ImportError:
        return _skip("jax is not importable in this environment")
    from . import native_store
    if not native_store.is_available():
        return _skip(f"native host store unavailable "
                     f"({native_store.build_error()})")
    from .backend.batch import BatchCheckEngine, BatchIncompatible
    from .backend.bfs import TpuExplorer
    from .session import SessionConfig, load_model

    # pay backend init once, outside every timed window
    jnp.zeros(8).block_until_ready()
    os.makedirs(out_dir, exist_ok=True)

    def sess(c):
        return SessionConfig(spec=spec, cfg=c, backend="jax",
                             platform="cpu", host_seen=True,
                             no_trace=True)

    # ---- COLD COHORT: N full solo colds vs one batched cold --------
    log(f"== batchbench cold cohort: {len(cold_cfgs)} members ==")
    seq_wall = 0.0
    seq_cold = []
    for c in cold_cfgs:
        t0 = time.time()
        m = load_model(spec, c, False)
        ex = TpuExplorer(m, host_seen=True, store_trace=False)
        r = ex.run()
        w = time.time() - t0
        seq_wall += w
        seq_cold.append(r)
        log(f"   solo cold {os.path.basename(c)}: {w:.2f}s "
            f"({r.distinct} distinct)")
    seq_gen = sum(r.generated for r in seq_cold)
    seq_dis = sum(r.distinct for r in seq_cold)
    seq_rate = seq_dis / max(seq_wall, 1e-9)

    t0 = time.time()
    try:
        be = BatchCheckEngine([sess(c) for c in cold_cfgs]).build()
    except BatchIncompatible as ex:
        log(f"BATCH-CHECK FAIL: cold fixture family not batchable "
            f"({ex})")
        return 1
    members = be.run()
    bat_wall = time.time() - t0
    if not _parity_or_fail("cold", cold_cfgs, seq_cold, members, log):
        return 1
    disp = be.dispatcher
    bat_gen = sum(m.result.generated for m in members)
    bat_dis = sum(m.result.distinct for m in members)
    bat_rate = bat_dis / max(bat_wall, 1e-9)
    if disp.max_width < len(cold_cfgs):
        log(f"BATCH-CHECK FAIL: cold occupancy {disp.max_width} < "
            f"{len(cold_cfgs)} (cohort did not share one program)")
        return 1
    cold_ratio = bat_rate / max(seq_rate, 1e-9)
    log(f"   sequential cold: {seq_wall:.2f}s "
        f"({seq_rate:,.0f} states/sec aggregate)")
    log(f"   batched cold:    {bat_wall:.2f}s "
        f"({bat_rate:,.0f} states/sec; occupancy={disp.max_width}, "
        f"one engine build, lifted={','.join(be.lift_names)})")
    _artifact(os.path.join(out_dir, "jaxmc_batchbench_cold_seq.json"),
              "cold-sequential", seq_wall, seq_dis, seq_gen,
              len(cold_cfgs), 1, None, list(be.lift_names))
    _artifact(os.path.join(out_dir, "jaxmc_batchbench_cold_batch.json"),
              "cold-batched", bat_wall, bat_dis, bat_gen,
              len(cold_cfgs), disp.max_width, disp.dispatches,
              list(be.lift_names))

    # ---- WARM DEEP RUNG: reported, regression-gated ----------------
    log(f"== batchbench warm deep rung: {len(warm_cfgs)} members ==")
    wseq_wall = 0.0
    wseq = []
    for c in warm_cfgs:
        m = load_model(spec, c, False)
        ex = TpuExplorer(m, host_seen=True, store_trace=False)
        ex.run()  # warm-up: compile, untimed
        t0 = time.time()
        r = ex.run()
        wseq_wall += time.time() - t0
        wseq.append(r)
    try:
        wbe = BatchCheckEngine([sess(c) for c in warm_cfgs]).build()
    except BatchIncompatible as ex:
        log(f"BATCH-CHECK FAIL: warm fixture family not batchable "
            f"({ex})")
        return 1
    wbe.run()  # warm-up: the one vmapped compile, untimed
    t0 = time.time()
    wmembers = wbe.run()
    wbat_wall = time.time() - t0
    if not _parity_or_fail("warm", warm_cfgs, wseq, wmembers, log):
        return 1
    warm_ratio = (sum(r.distinct for r in wseq) / max(wseq_wall, 1e-9))
    warm_ratio = (sum(m.result.distinct for m in wmembers)
                  / max(wbat_wall, 1e-9)) / max(warm_ratio, 1e-9)
    log(f"   warm sequential {wseq_wall:.2f}s vs batched "
        f"{wbat_wall:.2f}s -> {warm_ratio:.2f}x aggregate "
        f"states/sec")
    _artifact(os.path.join(out_dir, "jaxmc_batchbench_warm_seq.json"),
              "warm-sequential", wseq_wall,
              sum(r.distinct for r in wseq),
              sum(r.generated for r in wseq),
              len(warm_cfgs), 1, None, list(wbe.lift_names))
    _artifact(os.path.join(out_dir, "jaxmc_batchbench_warm_batch.json"),
              "warm-batched", wbat_wall,
              sum(m.result.distinct for m in wmembers),
              sum(m.result.generated for m in wmembers),
              len(warm_cfgs), wbe.dispatcher.max_width,
              wbe.dispatcher.dispatches, list(wbe.lift_names))

    # ---- the gate ---------------------------------------------------
    gate_x = float(os.environ.get("JAXMC_BATCH_GATE_X", "2.0"))
    verdict = "PASS" if cold_ratio >= gate_x else "FAIL"
    log(f"BATCH-CHECK {verdict}: cold cohort batched/sequential = "
        f"{cold_ratio:.2f}x (gate {gate_x:.1f}x) | warm deep rung = "
        f"{warm_ratio:.2f}x (cpu-XLA, informational) | occupancy "
        f"{disp.max_width}/{len(cold_cfgs)} | parity bit-identical")
    return 0 if verdict == "PASS" else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.batchbench",
        description="cross-model vmapped batching gate (ISSUE 13)")
    ap.add_argument("--spec", default=DEFAULT_SPEC)
    ap.add_argument("--cold-cfgs", nargs="*", default=COLD_CFGS)
    ap.add_argument("--warm-cfgs", nargs="*", default=WARM_CFGS)
    ap.add_argument("--out-dir", default="/tmp")
    args = ap.parse_args(argv)
    return run_leg(args.spec, list(args.cold_cfgs),
                   list(args.warm_cfgs), args.out_dir)


if __name__ == "__main__":
    sys.exit(main())
