r"""Spec mutation testing (SURVEY.md §4.6, VERDICT r2 #4).

The SSI spec documents its own verification protocol: intentionally break
each rule of Cahill's algorithm and confirm the checker then finds the
expected serializability violations — eight listed mutations, performed in
the original work by "commenting-out code (e.g. changing 'IF
some-condition ...' to 'IF FALSE ...')"
(/root/reference/examples/serializableSnapshotIsolation.tla:103-123).

This module applies those same breaks as PROGRAMMATIC AST EDITS at bind
time — the reference files are never touched. Three edit shapes cover all
eight mutations:

  if_false(n)            the nth IF (pre-order) in the definition body
                         gets its condition replaced by FALSE — the
                         guarded abort/bookkeeping can never fire
  assign_unchanged(v)    every  v' = rhs  assignment in the body becomes
                         v' = v  (a frame condition): the algorithm
                         "forgets" to update its tracking state
  let_empty_set(name)    a LET-bound helper set is pinned to {} — e.g.
                         Commit's LoserTxns, killing First-Committer-Wins
                         loser aborts

Every mutator REQUIRES its target to exist (a loud error otherwise), so a
drifted spec cannot silently turn the mutation suite vacuous.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..front import tla_ast as A
from .eval import OpClosure


class MutationError(Exception):
    """The mutation's target was not found in the definition body."""


# ---------------------------------------------------------------------------
# generic AST rewriting (nodes are frozen dataclasses)
# ---------------------------------------------------------------------------

def _rewrite(node: Any, fn: Callable[[A.Node], Optional[A.Node]]) -> Any:
    """Bottom-up structural rewrite; fn returns a replacement or None."""
    if isinstance(node, A.Node) and dataclasses.is_dataclass(node):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = _rewrite_val(v, fn)
            if nv is not v:
                changes[f.name] = nv
        if changes:
            node = dataclasses.replace(node, **changes)
        r = fn(node)
        return node if r is None else r
    return node


def _rewrite_val(v: Any, fn) -> Any:
    if isinstance(v, A.Node):
        return _rewrite(v, fn)
    if isinstance(v, tuple):
        out = tuple(_rewrite_val(x, fn) for x in v)
        if any(o is not x for o, x in zip(out, v)):
            return out
        return v
    return v


def _preorder(node: Any):
    """Yield every Node in the tree, parents before children."""
    if isinstance(node, A.Node):
        yield node
        if dataclasses.is_dataclass(node):
            for f in dataclasses.fields(node):
                yield from _preorder_val(getattr(node, f.name))


def _preorder_val(v: Any):
    if isinstance(v, A.Node):
        yield from _preorder(v)
    elif isinstance(v, tuple):
        for x in v:
            yield from _preorder_val(x)


# ---------------------------------------------------------------------------
# the three mutators
# ---------------------------------------------------------------------------

def if_false(n: int) -> Callable[[A.Node], A.Node]:
    """Replace the condition of the nth IF (pre-order) with FALSE."""
    def apply(body: A.Node) -> A.Node:
        ifs = [x for x in _preorder(body) if isinstance(x, A.If)]
        if n >= len(ifs):
            raise MutationError(
                f"if_false({n}): body has only {len(ifs)} IF nodes")
        target = ifs[n]

        def fn(x):
            if x is target:
                return dataclasses.replace(x, cond=A.Bool(False))
            return None
        return _rewrite(body, fn)
    return apply


def assign_unchanged(var: str) -> Callable[[A.Node], A.Node]:
    """Rewrite every  var' = rhs  into  var' = var  (frame condition)."""
    def apply(body: A.Node) -> A.Node:
        hits = [0]

        def fn(x):
            if isinstance(x, A.OpApp) and x.name == "=" and \
                    len(x.args) == 2 and \
                    isinstance(x.args[0], A.Prime) and \
                    isinstance(x.args[0].expr, A.Ident) and \
                    x.args[0].expr.name == var and \
                    not (isinstance(x.args[1], A.Ident)
                         and x.args[1].name == var):
                hits[0] += 1
                return dataclasses.replace(
                    x, args=(x.args[0], A.Ident(var)))
            return None
        out = _rewrite(body, fn)
        if not hits[0]:
            raise MutationError(
                f"assign_unchanged({var!r}): no {var}' = ... assignment "
                f"in body")
        return out
    return apply


def if_true_where(ident: str) -> Callable[[A.Node], A.Node]:
    """Force TRUE the condition of the unique IF whose condition
    mentions `ident` (e.g. the deadlock-prevention cycle check — forcing
    'no cycle found' lets the waits-for graph form real cycles)."""
    def mentions(node) -> bool:
        for x in _preorder(node):
            if (isinstance(x, A.Ident) and x.name == ident) or \
                    (isinstance(x, A.OpApp) and x.name == ident):
                return True
        return False

    def apply(body: A.Node) -> A.Node:
        targets = [x for x in _preorder(body)
                   if isinstance(x, A.If) and mentions(x.cond)]
        if len(targets) != 1:
            raise MutationError(
                f"if_true_where({ident!r}): {len(targets)} matching IF "
                f"nodes (need exactly 1)")
        target = targets[0]

        def fn(x):
            if x is target:
                return dataclasses.replace(x, cond=A.Bool(True))
            return None
        return _rewrite(body, fn)
    return apply


def let_empty_set(name: str) -> Callable[[A.Node], A.Node]:
    """Pin a LET-bound operator to the empty set."""
    def apply(body: A.Node) -> A.Node:
        hits = [0]

        def fn(x):
            if isinstance(x, A.OpDef) and x.name == name:
                hits[0] += 1
                return dataclasses.replace(x, body=A.SetEnum(()))
            return None
        out = _rewrite(body, fn)
        if not hits[0]:
            raise MutationError(
                f"let_empty_set({name!r}): no LET binding {name} in body")
        return out
    return apply


# ---------------------------------------------------------------------------
# the documented SSI mutation suite
# ---------------------------------------------------------------------------

# serializableSnapshotIsolation.tla:115-123 — the eight intentional
# rule-breaks, each expected to produce a CahillSerializable /
# BernsteinSerializable violation. Targets reference the spec's
# definitions: Commit :432-451, Read :539-553, HelperWriteCanAcquireXLock
# :700-758 (pre-order IF indices: Commit's dangerous-structure IF is its
# first; the write helper's dangerous IF is nested inside its outer
# "any concurrent SIREAD owners?" IF, hence index 1).
SSI_MUTATIONS: Dict[str, Tuple[str, Callable]] = {
    # "If Commit cannot abort txn."
    "commit_cannot_abort": ("Commit", if_false(0)),
    # "If Commit doesn't abort loser transactions."
    "commit_no_loser_aborts": ("Commit", let_empty_set("LoserTxns")),
    # "If Read doesn't acquire SIREAD lock."
    "read_no_siread_lock": ("Read", assign_unchanged("holdingSIREADlocks")),
    # "If Read doesn't update inConflict."
    "read_no_inconflict": ("Read", assign_unchanged("inConflict")),
    # "If Read cannot abort txn."
    "read_cannot_abort": ("Read", if_false(0)),
    # "If Write doesn't set outConflict."
    "write_no_outconflict": ("HelperWriteCanAcquireXLock",
                             assign_unchanged("outConflict")),
    # "If Write doesn't set inConflict."
    "write_no_inconflict": ("HelperWriteCanAcquireXLock",
                            assign_unchanged("inConflict")),
    # "If Write cannot abort txn."
    "write_cannot_abort": ("HelperWriteCanAcquireXLock", if_false(1)),
}

# The NINTH documented check (serializableSnapshotIsolation.tla:103-107,
# separate from the 8 serializability mutations): "Intentionally break
# the prevention of transactional deadlock, and verify that TLC reports
# the resulting specification-deadlock as an error. Checked by altering
# the Write action to allow creation of cycles in the waiting-for-locks
# graph." Forcing the cycle check to 'no cycle' makes a blocked write
# wait into a cycle; the cycle's members then starve and the search hits
# a real deadlock state (CHECK_DEADLOCK on).
DEADLOCK_MUTATION = ("HelperWriteConflictsWithXLock",
                     if_true_where("pathThatCyclesFromTxnToTxn"))


def apply_deadlock_mutation(model) -> None:
    apply_mutation(model, *DEADLOCK_MUTATION)


def apply_mutation(model, def_name: str,
                   mutator: Callable[[A.Node], A.Node]) -> None:
    """Mutate `def_name`'s body in model.defs (in place on the model's own
    defs dict — the loader's module cache is never touched) and reset the
    model's memo store so no pre-mutation operator results survive."""
    clo = model.defs.get(def_name)
    if not isinstance(clo, OpClosure):
        raise MutationError(f"{def_name} is not a definition")
    model.defs[def_name] = OpClosure(
        clo.name, clo.params, mutator(clo.body), clo.bound, clo.defs,
        stable=clo.stable)
    model._memo = None


def mutation_names() -> List[str]:
    return list(SSI_MUTATIONS)


def apply_ssi_mutation(model, name: str) -> None:
    def_name, mutator = SSI_MUTATIONS[name]
    apply_mutation(model, def_name, mutator)
