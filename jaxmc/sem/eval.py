r"""Reference evaluator for TLA+ expressions.

Slow, exact Python semantics — oracle #2 next to TLC (SURVEY.md §7.2) and the
fallback executor for constructs the TPU kernel compiler rejects. Evaluates
constant/state/action-level expressions; state enumeration (Init/Next walking)
lives in sem/enumerate.py and reuses this evaluator for guards and RHSs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..front import tla_ast as A
from .values import (EvalError, Fcn, FcnSetV, ModelValue,
                     enumerate_set, fmt, in_set, mk_record, mk_seq,
                     tla_eq, check_set_mix)


class TLCAssertFailure(EvalError):
    """Raised by Assert(FALSE, msg) — surfaces as a violation with trace."""

    def __init__(self, msg):
        super().__init__(msg)
        self.out = msg


@dataclass
class OpClosure:
    """A (possibly parameterless) definition together with its captured
    binding environment (LET bodies close over bound vars)."""
    name: str
    params: Tuple[str, ...]
    body: A.Node
    bound: Dict[str, Any] = field(default_factory=dict)
    defs: Optional[Dict[str, Any]] = None  # module defs snapshot (instances)
    # True only for module-level definitions built once per loaded module
    # (Loader.build) — the closures eligible for memoization (sem/memo.py)
    stable: bool = field(default=False, compare=False)


@dataclass
class BuiltinOp:
    """A standard-module operator passed as a value (higher-order use,
    e.g. SelectSeq(s, SomeBuiltin)). fn takes (args, ctx)."""
    name: str
    fn: Callable


class Ctx:
    """Evaluation context: definition table, bound variables, state."""
    __slots__ = ("defs", "bound", "state", "primes", "vars", "on_print",
                 "memo")

    def __init__(self, defs, bound=None, state=None, primes=None, vars=(),
                 on_print=None, memo=None):
        self.defs = defs          # name -> OpClosure | BuiltinOp | value
        self.bound = bound or {}  # name -> value (quantifier/param bindings)
        self.state = state        # name -> value, None outside behaviors
        self.primes = primes      # name -> value (partial during enumeration)
        self.vars = vars          # declared VARIABLE names
        self.on_print = on_print  # callback for TLC Print
        self.memo = memo          # per-model MemoStore (sem/memo.py) or None

    def with_bound(self, extra: Dict[str, Any]) -> "Ctx":
        c = Ctx(self.defs, {**self.bound, **extra}, self.state, self.primes,
                self.vars, self.on_print, self.memo)
        return c

    def with_defs(self, extra: Dict[str, Any]) -> "Ctx":
        c = Ctx({**self.defs, **extra}, self.bound, self.state, self.primes,
                self.vars, self.on_print, self.memo)
        return c


class UnassignedPrime(EvalError):
    def __init__(self, var):
        super().__init__(f"primed variable {var}' read before assignment")
        self.var = var


class RecFcn(Fcn):
    """Lazily-evaluated recursive function constructor f[x \\in S] == body
    (e.g. vmem, /root/reference/examples/SpecifyingSystems/CachingMemory/
    WriteThroughCache.tla:54-61). Entries are memoized on demand so the
    recursion terminates; equality/hash force full evaluation."""
    __slots__ = ("_dom_list", "_fn", "_forced", "_inprog")

    def __init__(self, dom_list, fn):
        super().__init__({})
        self._dom_list = dom_list
        self._fn = fn
        self._forced = False
        self._inprog = set()

    def apply(self, arg):
        if arg in self._d:
            return self._d[arg]
        if not any(tla_eq(arg, k) for k in self._dom_list):
            raise EvalError(f"recursive function applied outside domain: "
                            f"{fmt(arg)}")
        karg = arg
        if karg in self._inprog:
            raise EvalError("recursive function definition does not terminate")
        self._inprog.add(karg)
        try:
            v = self._fn(arg)
        finally:
            self._inprog.discard(karg)
        self._d[karg] = v
        return v

    def _force_all(self):
        if not self._forced:
            for k in self._dom_list:
                self.apply(k)
            self._forced = True
            self._hash = None

    def _materialized_items(self):
        self._force_all()
        return self._d.items()

    def domain(self):
        return frozenset(self._dom_list)

    def __len__(self):
        return len(self._dom_list)

    def __eq__(self, other):
        if not isinstance(other, Fcn):
            return NotImplemented
        self._force_all()
        if isinstance(other, RecFcn):
            other._force_all()
        return self._d == other._d

    def __hash__(self):
        self._force_all()
        return hash(frozenset(self._d.items()))

    def is_seq(self):
        self._force_all()
        return super().is_seq()

    def is_record(self):
        self._force_all()
        return super().is_record()

    def as_list(self):
        self._force_all()
        return super().as_list()

    @property
    def d(self):
        self._force_all()
        return self._d


def _bool(v, what="expression"):
    if isinstance(v, bool):
        return v
    raise EvalError(f"{what} evaluated to non-boolean {fmt(v)}")


def bind_pattern(pat, value) -> Dict[str, Any]:
    """Bind a binder name or tuple pattern <<a, b>> against a value."""
    if isinstance(pat, str):
        return {pat: value}
    if not isinstance(value, Fcn) or not (len(value) == 0 or value.is_seq()) \
            or len(value) != len(pat):
        raise EvalError(f"cannot destructure {fmt(value)} as <<{', '.join(pat)}>>")
    return dict(zip(pat, value.as_list()))


def iter_binders(binders, ctx, ev) -> "itertools.product":
    """Yield bound-dicts for quantifier/setmap/fndef binder lists.
    Each binder: ((name_or_pat, ...), set_expr)."""
    groups = []
    for names, sexpr in binders:
        if sexpr is None:
            raise EvalError("unbounded quantifier not supported")
        sval = ev(sexpr, ctx)
        elems = enumerate_set(sval)
        for pat in names:
            groups.append((pat, elems))
    keys = [g[0] for g in groups]
    for combo in itertools.product(*[g[1] for g in groups]):
        b: Dict[str, Any] = {}
        for pat, v in zip(keys, combo):
            b.update(bind_pattern(pat, v))
        yield b


# ---------------------------------------------------------------------------

def eval_expr(e: A.Node, ctx: Ctx) -> Any:
    t = type(e)
    fn = _DISPATCH.get(t)
    if fn is None:
        raise EvalError(f"cannot evaluate {t.__name__} node: {e!r}")
    return fn(e, ctx)


def _ev_num(e, ctx):
    return e.val


def _ev_str(e, ctx):
    return e.val


def _ev_bool(e, ctx):
    return e.val


# stdlib/memo are import cycles with this module; resolve them once on
# first use instead of re-running the import machinery on the hot path
# (the `from .stdlib import BUILTIN_OPS` in _resolve showed up as ~250k
# importlib calls per 40k generated states)
_BUILTIN_OPS = None
_memo_key = None


def _get_builtin_ops():
    global _BUILTIN_OPS
    if _BUILTIN_OPS is None:
        from .stdlib import BUILTIN_OPS
        _BUILTIN_OPS = BUILTIN_OPS
    return _BUILTIN_OPS


def _get_memo_key():
    global _memo_key
    if _memo_key is None:
        from .memo import memo_key
        _memo_key = memo_key
    return _memo_key


def _resolve(name: str, ctx: Ctx):
    if name in ctx.bound:
        return ctx.bound[name]
    if ctx.state is not None and name in ctx.vars:
        if name not in ctx.state:
            raise EvalError(f"variable {name} unassigned")
        return ctx.state[name]
    if name in ctx.defs:
        return ctx.defs[name]
    ops = _BUILTIN_OPS if _BUILTIN_OPS is not None else _get_builtin_ops()
    if name in ops:
        return BuiltinOp(name, ops[name])
    raise EvalError(f"unknown identifier {name}")


_MISS = object()


def _force(v, ctx, name=""):
    """Resolve a definition reference to a value (apply zero-arg closures)."""
    if isinstance(v, OpClosure):
        if v.params:
            return v  # operator value (can be passed higher-order)
        store = ctx.memo
        if store is not None and v.stable and not v.bound \
                and v.defs is None:
            memo_key = _memo_key if _memo_key is not None \
                else _get_memo_key()
            key = memo_key(store, v, ctx.defs, ctx)
            if key is not None:
                hit = store.vals.get(key, _MISS)
                if hit is not _MISS:
                    store.hits += 1
                    return hit
                store.misses += 1
                val = eval_expr(v.body, ctx)
                store.put(key, val)
                return val
        inner = ctx if v.defs is None else Ctx(v.defs, ctx.bound, ctx.state,
                                               ctx.primes, ctx.vars,
                                               ctx.on_print, ctx.memo)
        if v.bound:
            inner = inner.with_bound(v.bound)
        if isinstance(v.body, A.FnConstrDef):
            return _build_rec_fcn(v.body, inner)
        return eval_expr(v.body, inner)
    if isinstance(v, BuiltinOp):
        return v
    return v


def _build_rec_fcn(d: A.FnConstrDef, ctx: Ctx) -> "RecFcn":
    """Build the lazily-memoized function for f[x \\in S] == body."""
    if len(d.binders) != 1 or len(d.binders[0][0]) != 1:
        raise EvalError("recursive function constructors support a single "
                        "binder only")
    pat, sexpr = d.binders[0][0][0], d.binders[0][1]
    dom = enumerate_set(eval_expr(sexpr, ctx))
    holder = {}

    def compute(x):
        inner = ctx.with_defs({d.name: holder["rf"]})
        return eval_expr(d.body, inner.with_bound(bind_pattern(pat, x)))

    rf = RecFcn(dom, compute)
    holder["rf"] = rf
    return rf


def _ev_ident(e, ctx):
    return _force(_resolve(e.name, ctx), ctx, e.name)


def _ev_prime(e, ctx):
    if not isinstance(e.expr, A.Ident):
        # prime distributes over state expressions; evaluate in primed context
        if ctx.primes is None:
            raise EvalError("primed expression outside an action")
        sub = Ctx(ctx.defs, ctx.bound, ctx.primes, None, ctx.vars,
                  ctx.on_print, ctx.memo)
        return eval_expr(e.expr, sub)
    name = e.expr.name
    if ctx.primes is None:
        raise EvalError(f"{name}' used outside an action")
    if name in ctx.vars or name in ctx.primes:
        if name not in ctx.primes:
            raise UnassignedPrime(name)
        return ctx.primes[name]
    # primed DEFINITION (opId', InnerSerial.tla:6): evaluate its body with
    # the primed state as the state
    sub = Ctx(ctx.defs, ctx.bound, ctx.primes, None, ctx.vars, ctx.on_print,
              ctx.memo)
    return eval_expr(e.expr, sub)


def apply_op(opv, args: List[Any], ctx: Ctx):
    if isinstance(opv, BuiltinOp):
        return opv.fn(args, ctx)
    if isinstance(opv, OpClosure):
        if len(opv.params) != len(args):
            raise EvalError(f"{opv.name} expects {len(opv.params)} args, "
                            f"got {len(args)}")
        store = ctx.memo
        if store is not None and opv.stable and not opv.bound and args \
                and opv.defs is None:
            memo_key = _memo_key if _memo_key is not None \
                else _get_memo_key()
            key = memo_key(store, opv, ctx.defs, ctx, tuple(args))
            if key is not None:
                hit = store.vals.get(key, _MISS)
                if hit is not _MISS:
                    store.hits += 1
                    return hit
                store.misses += 1
                inner = ctx.with_bound(dict(zip(opv.params, args)))
                val = eval_expr(opv.body, inner)
                store.put(key, val)
                return val
        base = ctx if opv.defs is None else Ctx(opv.defs, ctx.bound, ctx.state,
                                                ctx.primes, ctx.vars,
                                                ctx.on_print, ctx.memo)
        inner = base.with_bound({**opv.bound, **dict(zip(opv.params, args))})
        return eval_expr(opv.body, inner)
    raise EvalError(f"value {fmt(opv)} is not an operator")


def _arg_value(a: A.Node, ctx: Ctx):
    """Evaluate an operator argument; a bare name referring to an operator
    definition passes the operator itself (higher-order TLA+)."""
    if isinstance(a, A.Ident):
        v = _resolve(a.name, ctx)
        if isinstance(v, OpClosure) and v.params:
            return v
        if isinstance(v, BuiltinOp):
            return v
        return _force(v, ctx, a.name)
    if isinstance(a, A.Lambda):
        return OpClosure("LAMBDA", a.params, a.body, dict(ctx.bound))
    return eval_expr(a, ctx)


def _flatten_junction(e: A.Node, op: str):
    if isinstance(e, A.OpApp) and e.name == op and len(e.args) == 2:
        return _flatten_junction(e.args[0], op) + _flatten_junction(e.args[1], op)
    return [e]


def _ev_opapp(e: A.OpApp, ctx: Ctx):
    name = e.name
    # instance path: resolve qualifier chain
    if e.path:
        return _eval_instance_path(e, ctx)
    if name == "!sel":
        # Inv!2 — second conjunct of Inv's definition (MCPaxos.tla:41-43)
        base, num = e.args
        if not isinstance(base, A.Ident):
            raise EvalError("!sel on non-identifier")
        d = _resolve(base.name, ctx)
        if not isinstance(d, OpClosure):
            raise EvalError(f"!sel target {base.name} is not a definition")
        conjs = _flatten_junction(d.body, "/\\")
        idx = num.val
        if not 1 <= idx <= len(conjs):
            raise EvalError(f"{base.name}!{idx} out of range")
        return eval_expr(conjs[idx - 1], ctx)

    # short-circuit logical forms first
    if name == "/\\":
        return _bool(eval_expr(e.args[0], ctx), "conjunct") and \
            _bool(eval_expr(e.args[1], ctx), "conjunct")
    if name == "\\/":
        return _bool(eval_expr(e.args[0], ctx), "disjunct") or \
            _bool(eval_expr(e.args[1], ctx), "disjunct")
    if name == "=>":
        return (not _bool(eval_expr(e.args[0], ctx))) or \
            _bool(eval_expr(e.args[1], ctx))
    if name in ("<=>", "\\equiv"):
        return _bool(eval_expr(e.args[0], ctx)) == _bool(eval_expr(e.args[1], ctx))
    if name == "~":
        return not _bool(eval_expr(e.args[0], ctx))
    if name == "=":
        return tla_eq(eval_expr(e.args[0], ctx), eval_expr(e.args[1], ctx))
    if name in ("/=", "#"):
        return not tla_eq(eval_expr(e.args[0], ctx), eval_expr(e.args[1], ctx))
    if name == "\\in":
        return in_set(eval_expr(e.args[0], ctx), eval_expr(e.args[1], ctx))
    if name == "\\notin":
        return not in_set(eval_expr(e.args[0], ctx), eval_expr(e.args[1], ctx))

    # user definitions shadow builtins (e.g. a module redefining \o)
    target = None
    if name in ctx.bound:
        target = ctx.bound[name]
    elif name in ctx.defs:
        target = ctx.defs[name]
    if target is not None and isinstance(target, (OpClosure, BuiltinOp)):
        args = [_arg_value(a, ctx) for a in e.args]
        return apply_op(target, args, ctx)
    if target is not None and not e.args:
        return _force(target, ctx, name)

    ops = _BUILTIN_OPS if _BUILTIN_OPS is not None else _get_builtin_ops()
    b = ops.get(name)
    if b is not None:
        args = [_arg_value(a, ctx) for a in e.args]
        return b(args, ctx)
    raise EvalError(f"unknown operator {name}")


def _eval_instance_path(e: A.OpApp, ctx: Ctx):
    """V!Op(args) — look up Op inside instance V's substituted namespace."""
    cur = ctx
    for inst_name, inst_args in e.path:
        inst = _resolve(inst_name, cur)
        from .modules import InstanceNamespace  # late import
        if isinstance(inst, OpClosure) and isinstance(inst.body, InstanceNamespace):
            ns = inst.body
        elif isinstance(inst, InstanceNamespace):
            ns = inst
        else:
            raise EvalError(f"{inst_name} is not an instance")
        argvals = [_arg_value(a, cur) for a in inst_args]
        cur = ns.enter(cur, argvals)
    inner = A.OpApp(e.name, e.args) if e.args else A.Ident(e.name)
    # evaluate the op inside the instance context, but with outer bound args
    return eval_expr(inner, cur)


def _ev_fnapp(e: A.FnApp, ctx: Ctx):
    f = eval_expr(e.fn, ctx)
    args = [eval_expr(a, ctx) for a in e.args]
    if isinstance(f, Fcn):
        if len(args) == 1:
            return f.apply(args[0])
        return f.apply(mk_seq(args))  # f[a, b] == f[<<a, b>>]
    if isinstance(f, (OpClosure, BuiltinOp)):
        return apply_op(f, args, ctx)
    raise EvalError(f"cannot apply non-function {fmt(f)}")


def _ev_dot(e: A.Dot, ctx: Ctx):
    r = eval_expr(e.expr, ctx)
    if isinstance(r, Fcn):
        return r.apply(e.fld)
    raise EvalError(f"field access .{e.fld} on non-record {fmt(r)}")


def _ev_tuple(e: A.TupleExpr, ctx: Ctx):
    return mk_seq([eval_expr(x, ctx) for x in e.items])


def _ev_setenum(e: A.SetEnum, ctx: Ctx):
    vals = [eval_expr(x, ctx) for x in e.items]
    # TLC comparability: {TRUE, 1} is an error, not a True==1 collapse
    check_set_mix(vals)
    return frozenset(vals)


def _ev_setfilter(e: A.SetFilter, ctx: Ctx):
    s = eval_expr(e.set, ctx)
    out = []
    for v in enumerate_set(s):
        b = bind_pattern(e.var, v)
        if _bool(eval_expr(e.pred, ctx.with_bound(b)), "set filter"):
            out.append(v)
    return frozenset(out)


def _ev_setmap(e: A.SetMap, ctx: Ctx):
    out = []
    for b in iter_binders(e.binders, ctx, eval_expr):
        out.append(eval_expr(e.expr, ctx.with_bound(b)))
    check_set_mix(out)
    return frozenset(out)


def _ev_fndef(e: A.FnDef, ctx: Ctx):
    # [x \in S, y \in T |-> body]: multi-binder functions take tuple args
    entries = {}
    binder_list = []
    for names, sexpr in e.binders:
        sval = eval_expr(sexpr, ctx)
        for pat in names:
            binder_list.append((pat, enumerate_set(sval)))
    single = len(binder_list) == 1
    for combo in itertools.product(*[els for _, els in binder_list]):
        b = {}
        for (pat, _), v in zip(binder_list, combo):
            b.update(bind_pattern(pat, v))
        key = combo[0] if single else mk_seq(combo)
        entries[key] = eval_expr(e.body, ctx.with_bound(b))
    return Fcn(entries)


def _ev_fnset(e: A.FnSet, ctx: Ctx):
    from .values import FcnSetV
    dom = eval_expr(e.dom, ctx)
    rng = eval_expr(e.rng, ctx)
    return FcnSetV(dom, rng)


def _ev_record(e: A.RecordExpr, ctx: Ctx):
    return mk_record({k: eval_expr(v, ctx) for k, v in e.fields})


def _ev_recordset(e: A.RecordSet, ctx: Ctx):
    keys = [k for k, _ in e.fields]
    sets = [enumerate_set(eval_expr(s, ctx)) for _, s in e.fields]
    out = []
    for combo in itertools.product(*sets):
        out.append(mk_record(dict(zip(keys, combo))))
    return frozenset(out)


def _except_update(val, path, rhs_expr, ctx):
    """Apply one EXCEPT update along path; @ refers to the old value."""
    if not path:
        old = val
        return eval_expr(rhs_expr, ctx.with_bound({"@": old}))
    kind, arg = path[0]
    if not isinstance(val, Fcn):
        raise EvalError(f"EXCEPT into non-function {fmt(val)}")
    if kind == "idx":
        keys = [eval_expr(a, ctx) for a in arg]
        key = keys[0] if len(keys) == 1 else mk_seq(keys)
    else:
        key = arg
    old = val.apply(key)
    new = _except_update(old, path[1:], rhs_expr, ctx)
    d = dict(val.d)
    d[key] = new
    return Fcn(d)


def _ev_except(e: A.Except, ctx: Ctx):
    val = eval_expr(e.fn, ctx)
    for path, rhs in e.updates:
        val = _except_update(val, list(path), rhs, ctx)
    return val


def _ev_at(e: A.At, ctx: Ctx):
    if "@" not in ctx.bound:
        raise EvalError("@ used outside EXCEPT")
    return ctx.bound["@"]


def _ev_if(e: A.If, ctx: Ctx):
    c = _bool(eval_expr(e.cond, ctx), "IF condition")
    return eval_expr(e.then if c else e.els, ctx)


def _ev_case(e: A.Case, ctx: Ctx):
    for g, b in e.arms:
        if _bool(eval_expr(g, ctx), "CASE guard"):
            return eval_expr(b, ctx)
    if e.other is not None:
        return eval_expr(e.other, ctx)
    raise EvalError("CASE: no guard matched and no OTHER")


def make_let_defs(defs, ctx: Ctx) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    bound_snapshot = dict(ctx.bound)
    for d in defs:
        if isinstance(d, A.OpDef):
            out[d.name] = OpClosure(d.name, d.params, d.body, bound_snapshot)
        elif isinstance(d, A.FnConstrDef):
            # f[x \in S] == body — possibly recursive; built lazily by _force
            out[d.name] = OpClosure(d.name, (), d, bound_snapshot)
        elif isinstance(d, A.RecursiveDecl):
            continue  # names become visible through the defs dict itself
        else:
            raise EvalError(f"unsupported LET definition {d!r}")
    return out


def _ev_let(e: A.Let, ctx: Ctx):
    new = make_let_defs(e.defs, ctx)
    inner = ctx.with_defs(new)
    # recursive LET defs must resolve through the extended table
    for v in new.values():
        if isinstance(v, OpClosure):
            v.defs = inner.defs
    return eval_expr(e.body, inner)


def _ev_quant(e: A.Quant, ctx: Ctx):
    if e.kind == "A":
        for b in iter_binders(e.binders, ctx, eval_expr):
            if not _bool(eval_expr(e.body, ctx.with_bound(b)), "\\A body"):
                return False
        return True
    for b in iter_binders(e.binders, ctx, eval_expr):
        if _bool(eval_expr(e.body, ctx.with_bound(b)), "\\E body"):
            return True
    return False


_FRESH_CHOOSE: Dict[A.Node, ModelValue] = {}


def _ev_choose(e: A.Choose, ctx: Ctx):
    if e.set is None:
        # TLC's special case: CHOOSE x : x \notin S evaluates to an
        # arbitrary value outside S — a fresh model value, deterministic
        # per CHOOSE expression (textbookSnapshotIsolation.tla:32 NoLock,
        # InnerSerial.tla:9 InitWr). Anything else unbounded is rejected,
        # as in TLC.
        if isinstance(e.pred, A.OpApp) and e.pred.name == "\\notin" \
                and isinstance(e.pred.args[0], A.Ident) \
                and isinstance(e.var, str) \
                and e.pred.args[0].name == e.var:
            mv = _FRESH_CHOOSE.get(e)
            if mv is None:
                import hashlib
                tag = hashlib.md5(repr(e).encode()).hexdigest()[:8]
                mv = ModelValue(f"$fresh_{tag}")
                _FRESH_CHOOSE[e] = mv
            return mv
        raise EvalError("unbounded CHOOSE not supported (except the "
                        "CHOOSE x : x \\notin S fresh-value idiom)")
    s = eval_expr(e.set, ctx)
    for v in enumerate_set(s):
        b = bind_pattern(e.var, v)
        if _bool(eval_expr(e.pred, ctx.with_bound(b)), "CHOOSE body"):
            return v
    raise EvalError(f"CHOOSE: no value in {fmt(s)} satisfies predicate")


def _ev_unchanged(e: A.Unchanged, ctx: Ctx):
    # as a boolean expression: vars' = vars
    return tla_eq(eval_expr(A.Prime(e.expr), ctx), eval_expr(e.expr, ctx))


def _ev_fair(e: A.Fair, ctx: Ctx):
    raise EvalError("fairness formulas are temporal; not state-evaluable")


def _ev_boxaction(e, ctx):
    raise EvalError("[A]_v is action-level; not state-evaluable")


def _ev_enabled(e: A.Enabled, ctx: Ctx):
    from .enumerate import action_enabled  # late import
    return action_enabled(e.expr, ctx)


_DISPATCH: Dict[type, Callable] = {
    A.Num: _ev_num,
    A.Str: _ev_str,
    A.Bool: _ev_bool,
    A.Ident: _ev_ident,
    A.Prime: _ev_prime,
    A.OpApp: _ev_opapp,
    A.FnApp: _ev_fnapp,
    A.Dot: _ev_dot,
    A.TupleExpr: _ev_tuple,
    A.SetEnum: _ev_setenum,
    A.SetFilter: _ev_setfilter,
    A.SetMap: _ev_setmap,
    A.FnDef: _ev_fndef,
    A.FnSet: _ev_fnset,
    A.RecordExpr: _ev_record,
    A.RecordSet: _ev_recordset,
    A.Except: _ev_except,
    A.At: _ev_at,
    A.If: _ev_if,
    A.Case: _ev_case,
    A.Let: _ev_let,
    A.Quant: _ev_quant,
    A.Choose: _ev_choose,
    A.Unchanged: _ev_unchanged,
    A.Fair: _ev_fair,
    A.BoxAction: _ev_boxaction,
    A.Enabled: _ev_enabled,
}
