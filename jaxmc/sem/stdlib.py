r"""Native implementations of the TLA+ standard modules.

Semantic definitions these implement (SURVEY.md §1 L2):
  Naturals/Integers: /root/reference/examples/SpecifyingSystems/Standard/
    Naturals.tla:4-16, Integers.tla:5-6 (+ - * ^ <= < .. \div % Int unary -)
  Sequences: Sequences.tla:14-58 (Seq Len \o Append Head Tail SubSeq SelectSeq)
  FiniteSets: FiniteSets.tla:9-22 (IsFiniteSet Cardinality)
  Bags: Bags.tla:4-45 (multiset ops — raft encodes its bag manually)
  TLC: TLC/TLC.tla (Print/Assert :5-6, :> and @@ :10-12, Permutations :13-14,
    SortSeq :20-23)

Each entry takes (args, ctx) — the evaluator resolves user redefinitions first,
so a module shadowing an operator wins.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

from .values import (EvalError, Fcn, InfiniteSet, EMPTY_FCN,
                     enumerate_set, fmt, in_set, mk_seq,
                     tla_eq, check_set_mix)
from .eval import TLCAssertFailure, apply_op


def _int(v, op):
    if isinstance(v, bool) or not isinstance(v, int):
        raise EvalError(f"{op} applied to non-integer {fmt(v)}")
    return v


def _set(v, op):
    if isinstance(v, frozenset):
        return v
    from .values import FcnSetV
    if isinstance(v, FcnSetV):
        return v.materialize()
    raise EvalError(f"{op} applied to non-enumerable-set {fmt(v)}")


def _seq(v, op):
    if isinstance(v, Fcn) and (len(v) == 0 or v.is_seq()):
        return v
    raise EvalError(f"{op} applied to non-sequence {fmt(v)}")


def _arith(name):
    def f(args, ctx):
        a, b = (_int(x, name) for x in args)
        if name == "+":
            return a + b
        if name == "-":
            return a - b
        if name == "*":
            return a * b
        if name == "^":
            return a ** b
        if name == "\\div":
            if b == 0:
                raise EvalError("division by zero")
            return a // b
        if name == "%":
            if b == 0:
                raise EvalError("modulo by zero")
            return a % b
        raise AssertionError(name)
    return f


def _cmp(name):
    def f(args, ctx):
        a, b = (_int(x, name) for x in args)
        return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[name]
    return f


def _interval(args, ctx):
    a, b = (_int(x, "..") for x in args)
    return frozenset(range(a, b + 1))


def _setop(name):
    def f(args, ctx):
        a = _set(args[0], name)
        b = _set(args[1], name)
        # check the OPERANDS' members for every set operator: True == 1
        # collapses inside `a | b` / `a & b` / `a - b` itself, so the
        # result would hide the mix ({TRUE} \cap {1} -> {1}, {TRUE} \ {1}
        # -> {}) where TLC raises a comparability error
        check_set_mix(itertools.chain(a, b))
        if name in ("\\cup", "\\union"):
            return a | b
        if name in ("\\cap", "\\intersect"):
            return a & b
        if name == "\\":
            return a - b
        raise AssertionError(name)
    return f


def _subseteq(args, ctx):
    a = _set(args[0], "\\subseteq")
    return all(in_set(x, args[1]) for x in a)


def _subset_proper(args, ctx):
    return _subseteq(args, ctx) and not tla_eq(args[0], args[1])


def _powerset(args, ctx):
    elems = enumerate_set(args[0])
    out = []
    for r in range(len(elems) + 1):
        for combo in itertools.combinations(elems, r):
            out.append(frozenset(combo))
    return frozenset(out)


def _union(args, ctx):
    out = []
    for s in enumerate_set(args[0]):
        out.extend(_set(s, "UNION"))
    check_set_mix(out)
    return frozenset(out)


def _domain(args, ctx):
    v = args[0]
    if isinstance(v, Fcn):
        return v.domain()
    raise EvalError(f"DOMAIN of non-function {fmt(v)}")


def _cardinality(args, ctx):
    return len(_set(args[0], "Cardinality"))


def _is_finite_set(args, ctx):
    return isinstance(args[0], frozenset)


def _cartprod(args, ctx):
    sets = [enumerate_set(s) for s in args]
    return frozenset(mk_seq(list(c)) for c in itertools.product(*sets))


# ---- Sequences ----

def _len(args, ctx):
    return len(_seq(args[0], "Len"))


def _concat(args, ctx):
    a, b = _seq(args[0], "\\o"), _seq(args[1], "\\o")
    return mk_seq(a.as_list() + b.as_list())


def _append(args, ctx):
    s = _seq(args[0], "Append")
    return mk_seq(s.as_list() + [args[1]])


def _head(args, ctx):
    s = _seq(args[0], "Head")
    if len(s) == 0:
        raise EvalError("Head of empty sequence")
    return s.apply(1)


def _tail(args, ctx):
    s = _seq(args[0], "Tail")
    if len(s) == 0:
        raise EvalError("Tail of empty sequence")
    return mk_seq(s.as_list()[1:])


def _subseq(args, ctx):
    s = _seq(args[0], "SubSeq")
    m, n = _int(args[1], "SubSeq"), _int(args[2], "SubSeq")
    lst = s.as_list()
    if m < 1 or n > len(lst):
        if m > n:  # empty result allowed for m > n even out of range
            return EMPTY_FCN
        raise EvalError(f"SubSeq({fmt(args[0])}, {m}, {n}) out of range")
    return mk_seq(lst[m - 1:n])


def _selectseq(args, ctx):
    s = _seq(args[0], "SelectSeq")
    test = args[1]
    out = [v for v in s.as_list()
           if apply_op(test, [v], ctx) is True]
    return mk_seq(out)


def _seq_set(args, ctx):
    return InfiniteSet("Seq", args[0])


# ---- Bags (Standard/Bags.tla:4-45) ----

def _is_bag(v):
    return isinstance(v, Fcn) and all(
        isinstance(c, int) and not isinstance(c, bool) and c > 0
        for c in v.d.values())


def _bag_add(args, ctx):
    a, b = args
    if not (isinstance(a, Fcn) and isinstance(b, Fcn)):
        raise EvalError("(+) applied to non-bags")
    d = dict(a.d)
    for k, c in b.d.items():
        d[k] = d.get(k, 0) + c
    return Fcn(d)


def _bag_sub(args, ctx):
    a, b = args
    if not (isinstance(a, Fcn) and isinstance(b, Fcn)):
        raise EvalError("(-) applied to non-bags")
    d = {}
    for k, c in a.d.items():
        nc = c - b.d.get(k, 0)
        if nc > 0:
            d[k] = nc
    return Fcn(d)


def _bag_in(args, ctx):
    e, b = args
    return isinstance(b, Fcn) and e in b.d and b.d[e] > 0


def _bag_to_set(args, ctx):
    return frozenset(k for k, c in args[0].d.items() if c > 0)


def _set_to_bag(args, ctx):
    return Fcn({k: 1 for k in enumerate_set(args[0])})


def _copies_in(args, ctx):
    e, b = args
    return b.d.get(e, 0) if isinstance(b, Fcn) else 0


def _bag_union(args, ctx):
    out: Dict[Any, int] = {}
    for b in enumerate_set(args[0]):
        for k, c in b.d.items():
            out[k] = out.get(k, 0) + c
    return Fcn(out)


def _bag_cardinality(args, ctx):
    return sum(args[0].d.values())


def _sub_bag(args, ctx):
    b = args[0]
    items = list(b.d.items())
    out = []
    for counts in itertools.product(*[range(c + 1) for _, c in items]):
        out.append(Fcn({k: n for (k, _), n in zip(items, counts) if n > 0}))
    return frozenset(out)


def _bag_of_all(args, ctx):
    op, b = args
    out: Dict[Any, int] = {}
    for k, c in b.d.items():
        nk = apply_op(op, [k], ctx)
        out[nk] = out.get(nk, 0) + c
    return Fcn(out)


# ---- TLC module ----

def _print(args, ctx):
    out, val = args
    if ctx.on_print is not None:
        ctx.on_print(out)
    else:
        print(fmt(out) if not isinstance(out, str) else out)
    return val


def _print_t(args, ctx):
    return _print([args[0], True], ctx)


def _assert(args, ctx):
    val, out = args
    if val is not True:
        raise TLCAssertFailure(out)
    return True


def _colon_gt(args, ctx):
    return Fcn({args[0]: args[1]})


def _at_at(args, ctx):
    f, g = args
    if not (isinstance(f, Fcn) and isinstance(g, Fcn)):
        raise EvalError("@@ applied to non-functions")
    d = dict(g.d)
    d.update(f.d)  # f wins on overlap, per TLC.tla:11-12
    return Fcn(d)


def _permutations(args, ctx):
    s = enumerate_set(args[0])
    out = []
    for perm in itertools.permutations(s):
        out.append(Fcn(dict(zip(s, perm))))
    return frozenset(out)


def _sort_seq(args, ctx):
    s, op = args
    lst = _seq(s, "SortSeq").as_list()
    import functools

    def cmp(a, b):
        if apply_op(op, [a, b], ctx) is True:
            return -1
        if apply_op(op, [b, a], ctx) is True:
            return 1
        return 0
    return mk_seq(sorted(lst, key=functools.cmp_to_key(cmp)))


def _tlc_eval(args, ctx):
    return args[0]


_RAW_OPS = {
    "+": _arith("+"), "-": _arith("-"), "*": _arith("*"), "^": _arith("^"),
    "\\div": _arith("\\div"), "%": _arith("%"), "\\mod": _arith("%"),
    "<": _cmp("<"), ">": _cmp(">"),
    "<=": _cmp("<="), "=<": _cmp("<="), "\\leq": _cmp("<="),
    ">=": _cmp(">="), "\\geq": _cmp(">="),
    "..": _interval,
    "-.": lambda args, ctx: -_int(args[0], "-"),
    "\\cup": _setop("\\cup"), "\\union": _setop("\\cup"),
    "\\cap": _setop("\\cap"), "\\intersect": _setop("\\cap"),
    "\\": _setop("\\"),
    "\\subseteq": _subseteq,
    "\\subset": _subset_proper,
    "\\supseteq": lambda args, ctx: _subseteq([args[1], args[0]], ctx),
    "\\supset": lambda args, ctx: _subset_proper([args[1], args[0]], ctx),
    "SUBSET": _powerset,
    "UNION": _union,
    "DOMAIN": _domain,
    "\\X": _cartprod,
    "Cardinality": _cardinality,
    "IsFiniteSet": _is_finite_set,
    "Len": _len,
    "\\o": _concat, "\\circ": _concat,
    "Append": _append,
    "Head": _head,
    "Tail": _tail,
    "SubSeq": _subseq,
    "SelectSeq": _selectseq,
    "Seq": _seq_set,
    "(+)": _bag_add, "(-)": _bag_sub,
    "BagIn": _bag_in,
    "BagToSet": _bag_to_set,
    "SetToBag": _set_to_bag,
    "CopiesIn": _copies_in,
    "BagUnion": _bag_union,
    "BagCardinality": _bag_cardinality,
    "SubBag": _sub_bag,
    "BagOfAll": _bag_of_all,
    "EmptyBag": lambda args, ctx: EMPTY_FCN,
    "IsABag": lambda args, ctx: _is_bag(args[0]),
    "Print": _print,
    "PrintT": _print_t,
    "Assert": _assert,
    ":>": _colon_gt,
    "@@": _at_at,
    "Permutations": _permutations,
    "SortSeq": _sort_seq,
    "TLCEval": _tlc_eval,
    "ToString": lambda args, ctx: fmt(args[0]),
}

BUILTIN_OPS = dict(_RAW_OPS)
