r"""TLC-style state enumeration: walking Init and Next as assignment programs.

This is the loop reconstructed in SURVEY.md §3.2: a conjunction is processed
left-to-right threading partial assignments; `v = e` assigns (or filters, if
already assigned), `v \in S` branches over S's elements, disjunctions and
\E branch, user operator applications expand, everything else is a boolean
guard. The same walker serves Init (unprimed targets), Next (primed targets),
and ENABLED.

Action labels: the innermost named operator expanded before the action's
first guard or assignment is evaluated (Restart(s1), Receive(m), ...) — the
provenance TLC prints in counterexample traces
(/root/reference/README.md:278-311). A label is a (name, args, frozen)
triple: operator expansion overwrites it until frozen by the first
guard/assignment.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..front import tla_ast as A
from .values import EvalError, enumerate_set, fmt, in_set, tla_eq
from .eval import (Ctx, OpClosure, _arg_value, _bool, _resolve, eval_expr,
                   iter_binders, make_let_defs)


_OP_PLAN_CAP = 1 << 16  # entries; cleared beyond (LET-heavy specs mint
# fresh closures per evaluation, so an id-keyed cache must be bounded)


class Walker:
    """mode 'init': assign unprimed variables; mode 'next': assign primes.

    A Walker is reusable across states (engine hot loop): the expansion
    plan for each operator application — call-by-name vs call-by-value,
    and the substituted body for the call-by-name case — depends only on
    the application node and the resolved closure, so it is decided ONCE
    per run and cached, instead of re-running the contains_prime /
    primes_params AST scans and the subst() tree rebuild on every state
    (the dominant per-state cost the profiler showed on transfer_scaled).
    """

    def __init__(self, mode: str, vars: Tuple[str, ...], state=None):
        assert mode in ("init", "next")
        self.mode = mode
        self.vars = set(vars)
        self.var_order = tuple(vars)
        self.state = state  # fixed pre-state in next mode
        # (id(app-node), id(closure)) -> ("cbn", substituted-body) |
        # ("call", None); _plan_pins keeps the keyed objects alive so a
        # gc'd closure's id is never reused against a stale plan
        self._op_plan = {}
        self._plan_pins = []

    def _ctx(self, base: Ctx, partial: Dict[str, Any]) -> Ctx:
        if self.mode == "init":
            return Ctx(base.defs, base.bound, partial, None, self.var_order,
                       base.on_print, base.memo)
        return Ctx(base.defs, base.bound, self.state, partial, self.var_order,
                   base.on_print, base.memo)

    def _target(self, e: A.Node, ctx: Ctx) -> Optional[str]:
        """Variable name if e is an assignable occurrence in this mode."""
        if self.mode == "next":
            if isinstance(e, A.Prime) and isinstance(e.expr, A.Ident) \
                    and e.expr.name in self.vars:
                return e.expr.name
            return None
        if isinstance(e, A.Ident) and e.name in self.vars \
                and e.name not in ctx.bound:
            return e.name
        return None

    def _op_expand_plan(self, e: A.OpApp, target: OpClosure):
        """The once-per-run expansion decision for `target` applied at
        node `e`: call-by-name (with the substituted body, built once)
        when an argument or the body primes a parameter, else plain
        call-by-value. Both inputs are immutable, so the plan is a pure
        function of (node, closure)."""
        ck = (id(e), id(target))
        plan = self._op_plan.get(ck)
        if plan is None:
            from ..front.subst import (contains_prime, primes_params,
                                       subst)
            if (any(contains_prime(a) for a in e.args)
                    or primes_params(target.body, target.params)) \
                    and target.defs is None:
                # call-by-name: an argument carries a primed variable
                # (Lose(msgQ) assigning q', Send(..., memInt') through an
                # operator constant) — substitute argument ASTs so the
                # assignment target survives into the body
                plan = ("cbn", subst(target.body,
                                     dict(zip(target.params, e.args))))
            else:
                plan = ("call", None)
            if len(self._op_plan) >= _OP_PLAN_CAP:
                self._op_plan.clear()
                self._plan_pins.clear()
            self._op_plan[ck] = plan
            self._plan_pins.append((e, target))
        return plan

    def walk(self, e: A.Node, ctx: Ctx, partial: Dict[str, Any],
             label) -> Iterator[Tuple[Dict[str, Any], Any]]:
        """Yield (complete-or-partial assignment, action label) pairs.

        The evaluation context (ectx) is built lazily per branch: the
        structural branches (conjunction, disjunction, operator
        expansion, UNCHANGED) never evaluate an expression, and they are
        the bulk of the walk calls."""
        if isinstance(e, A.OpApp):
            name = e.name
            if name == "/\\":
                for p1, l1 in self.walk(e.args[0], ctx, partial, label):
                    yield from self.walk(e.args[1], ctx, p1, l1)
                return
            if name == "\\/":
                for arm in e.args:
                    yield from self.walk(arm, ctx, dict(partial), label)
                return
            if name == "=":
                tgt = self._target(e.args[0], ctx)
                if tgt is not None:
                    label = _freeze(label)
                    ectx = self._ctx(ctx, partial)
                    if tgt in partial:
                        # second assignment acts as an equality filter
                        rhs = eval_expr(e.args[1], ectx)
                        if tla_eq(partial[tgt], rhs):
                            yield partial, label
                        return
                    rhs = eval_expr(e.args[1], ectx)
                    partial[tgt] = rhs
                    yield partial, label
                    return
                # fall through to guard evaluation
            if name == "\\in":
                tgt = self._target(e.args[0], ctx)
                if tgt is not None:
                    label = _freeze(label)
                    sval = eval_expr(e.args[1], self._ctx(ctx, partial))
                    if tgt in partial:
                        if in_set(partial[tgt], sval):
                            yield partial, label
                        return
                    for v in enumerate_set(sval):
                        p = dict(partial)
                        p[tgt] = v
                        yield p, label
                    return
            if name == "!sel":
                base, num = e.args
                if isinstance(base, A.Ident):
                    d = _resolve(base.name, ctx)
                    if isinstance(d, OpClosure):
                        conjs = _flatten(d.body, "/\\")
                        idx = num.val
                        if 1 <= idx <= len(conjs):
                            yield from self.walk(conjs[idx - 1], ctx, partial,
                                                 label)
                            return
            # user-defined operator application → expand as action
            target = ctx.bound[name] if name in ctx.bound else ctx.defs.get(name)
            if isinstance(target, OpClosure):
                plan = self._op_plan.get((id(e), id(target)))
                if plan is None:
                    plan = self._op_expand_plan(e, target)
                if plan[0] == "cbn":
                    new_label = label
                    if label is None or not label[2]:
                        new_label = (name, (), False)
                    yield from self.walk(plan[1], ctx, partial, new_label)
                    return
                ectx = self._ctx(ctx, partial)
                args = [_arg_value(a, ectx) for a in e.args]
                inner = ctx
                if target.defs is not None:
                    inner = Ctx(target.defs, ctx.bound, ctx.state, ctx.primes,
                                ctx.vars, ctx.on_print, ctx.memo)
                inner = inner.with_bound(
                    {**target.bound, **dict(zip(target.params, args))})
                new_label = label
                if label is None or not label[2]:
                    new_label = (name, tuple(args), False)
                yield from self.walk(target.body, inner, partial, new_label)
                return
            # else: boolean guard below

        elif isinstance(e, A.Ident):
            target = ctx.bound[e.name] if e.name in ctx.bound \
                else ctx.defs.get(e.name)
            if isinstance(target, OpClosure) and not target.params:
                inner = ctx
                if target.defs is not None:
                    inner = Ctx(target.defs, ctx.bound, ctx.state, ctx.primes,
                                ctx.vars, ctx.on_print, ctx.memo)
                if target.bound:
                    inner = inner.with_bound(target.bound)
                new_label = label
                if label is None or not label[2]:
                    new_label = (e.name, (), False)
                yield from self.walk(target.body, inner, partial, new_label)
                return

        elif isinstance(e, A.Quant):
            if e.kind == "E":
                ectx = self._ctx(ctx, partial)
                for b in iter_binders(e.binders, ectx, eval_expr):
                    yield from self.walk(e.body, ctx.with_bound(b),
                                         dict(partial), label)
                return
            # \A as guard (fall through)

        elif isinstance(e, A.If):
            c = _bool(eval_expr(e.cond, self._ctx(ctx, partial)),
                      "IF condition")
            yield from self.walk(e.then if c else e.els, ctx, partial, label)
            return

        elif isinstance(e, A.Case):
            ectx = self._ctx(ctx, partial)
            for g, b in e.arms:
                if _bool(eval_expr(g, ectx), "CASE guard"):
                    yield from self.walk(b, ctx, partial, label)
                    return
            if e.other is not None:
                yield from self.walk(e.other, ctx, partial, label)
                return
            raise EvalError("CASE: no guard matched")

        elif isinstance(e, A.Let):
            new = make_let_defs(e.defs, self._ctx(ctx, partial))
            inner = ctx.with_defs(new)
            for v in new.values():
                if isinstance(v, OpClosure):
                    v.defs = inner.defs
            yield from self.walk(e.body, inner, partial, label)
            return

        elif isinstance(e, A.Unchanged):
            if self.mode != "next":
                raise EvalError("UNCHANGED in Init")
            label = _freeze(label)
            p = dict(partial)
            if self._unchanged(e.expr, ctx, p):
                yield p, label
            return

        elif isinstance(e, A.BoxAction):
            # [A]_v as an action: A \/ (v' = v)  (MCRealTimeHourClock's
            # BigNext composes subactions this way)
            if self.mode != "next":
                raise EvalError("[A]_v in Init")
            yield from self.walk(e.action, ctx, dict(partial), label)
            p = dict(partial)
            if self._unchanged(e.sub, ctx, p):
                yield p, _freeze(label)
            return

        elif isinstance(e, A.Bool):
            if e.val:
                yield partial, label
            return

        # default: boolean guard
        label = _freeze(label)
        v = eval_expr(e, self._ctx(ctx, partial))
        if _bool(v, "action conjunct"):
            yield partial, label

    def _unchanged(self, e: A.Node, ctx: Ctx, partial) -> bool:
        """Assign v' = v for every variable under e; returns False if an
        existing assignment contradicts."""
        if isinstance(e, A.Ident):
            if e.name in self.vars:
                old = self.state[e.name]
                if e.name in partial:
                    return tla_eq(partial[e.name], old)
                partial[e.name] = old
                return True
            target = ctx.bound[e.name] if e.name in ctx.bound \
                else ctx.defs.get(e.name)
            if isinstance(target, OpClosure) and not target.params:
                inner = ctx
                if target.defs is not None:
                    inner = Ctx(target.defs, ctx.bound, ctx.state, ctx.primes,
                                ctx.vars, ctx.on_print, ctx.memo)
                return self._unchanged(target.body, inner, partial)
            raise EvalError(f"UNCHANGED of non-variable {e.name}")
        if isinstance(e, A.TupleExpr):
            return all(self._unchanged(x, ctx, partial) for x in e.items)
        raise EvalError(f"unsupported UNCHANGED argument {e!r}")


def _freeze(label):
    if label is not None and not label[2]:
        return (label[0], label[1], True)
    return label


def _flatten(e: A.Node, op: str):
    if isinstance(e, A.OpApp) and e.name == op and len(e.args) == 2:
        return _flatten(e.args[0], op) + _flatten(e.args[1], op)
    return [e]


def label_str(label) -> str:
    if label is None:
        return "Next"
    name, args = label[0], label[1]
    if not args:
        return name
    return f"{name}({', '.join(fmt(a) for a in args)})"


def enumerate_init(init: A.Node, base_ctx: Ctx,
                   vars: Tuple[str, ...]) -> List[Dict[str, Any]]:
    w = Walker("init", vars)
    out = []
    for partial, _ in w.walk(init, base_ctx, {}, None):
        missing = [v for v in vars if v not in partial]
        if missing:
            raise EvalError(f"Init leaves variables unassigned: {missing}")
        out.append(partial)
    return out


def enumerate_next(next_expr: A.Node, base_ctx: Ctx, vars: Tuple[str, ...],
                   state: Dict[str, Any], walker: Optional[Walker] = None):
    """Yield (successor-state dict, label) for every enabled instance.

    Pass a reusable `walker` (Walker("next", vars)) when enumerating many
    states of one run: its per-run expansion-plan cache then amortizes the
    action-AST split across the whole search instead of redoing it per
    state (the engines' hot loop does this; one-shot callers like ENABLED
    get a fresh walker)."""
    if walker is None:
        walker = Walker("next", vars)
    walker.state = state
    for partial, label in walker.walk(next_expr, base_ctx, {}, None):
        missing = [v for v in vars if v not in partial]
        if missing:
            raise EvalError(
                f"action {label_str(label)} leaves {missing} unassigned")
        yield partial, label


def action_enabled(action: A.Node, ctx: Ctx) -> bool:
    """ENABLED A: does any assignment complete A from the current state?"""
    if ctx.state is None:
        raise EvalError("ENABLED outside a behavior")
    w = Walker("next", tuple(ctx.vars), dict(ctx.state))
    for _ in w.walk(action, ctx, {}, None):
        return True
    return False
