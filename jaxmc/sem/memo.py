r"""Operator memoization for the reference interpreter.

TLC evaluates operator definitions by substitution, so a definition like
InnerSerial's totalOpOrder (a filtered SUBSET of opId \X opId,
/root/reference/examples/SpecifyingSystems/AdvancedExamples/InnerSerial.tla:46-52)
is recomputed at every reference — and the corpus's golden runs took 17-22h
on it (testout1:59). Here every module-level operator gets a static
dependency analysis: the set of state variables its body (transitively)
reads, unprimed and primed. Evaluation results are then cached per model,
keyed by (operator, argument values, dependency-variable values) — so
totalOpOrder is computed once per distinct opQ value instead of once per
reference.

Soundness notes:
- Only "stable" closures (built once per loaded module, Loader.build) are
  memoized; LET bodies and instance-substitution closures are created per
  evaluation and are skipped.
- The store lives on the Model (Model.ctx threads it through evaluation),
  never on the closure: the same module (and its closures) can be bound by
  several models with different cfg constants.
- Anything the analysis cannot prove deterministic-in-(deps, args) marks
  the operator uncacheable: Print/PrintT (side effects), ENABLED, temporal
  and action forms, instance paths, unresolvable names. Legal TLA+ cannot
  shadow a defined operator name with a bound variable, so defs-resolution
  at analysis time matches runtime resolution.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

from ..front import tla_ast as A

# builtin operators with observable side effects — bodies referencing
# these are never memoized
IMPURE_BUILTINS = {"Print", "PrintT"}

# logical forms handled structurally by the evaluator (not via BUILTIN_OPS)
_LOGICAL = {"/\\", "\\/", "=>", "<=>", "\\equiv", "~", "=", "/=", "#",
            "\\in", "\\notin"}

_VALS_CAP = 1 << 20  # entries; epoch-cleared beyond this


class _Uncacheable(Exception):
    pass


class MemoStore:
    """Per-model memoization state.

    deps: id(closure) -> (closure, analysis) — the closure reference pins
          the id against reuse after garbage collection.
    analysis: (state_deps tuple, prime_deps tuple) or None (uncacheable).
    vals: (id(closure), *args, *dep values) -> cached result.
    base_defs: the model's definition table. Memoization only applies when
    evaluation runs under exactly this table — name resolution (and so the
    dependency analysis) is table-relative, and instance/LET contexts swap
    the table.
    hits/misses: cache-effectiveness counters (plain ints, incremented on
    the eval hot path) — read by the obs telemetry rollup at end of run.
    """
    __slots__ = ("deps", "vals", "base_defs", "hits", "misses")

    def __init__(self, base_defs=None):
        self.deps: Dict[int, Tuple[Any, Optional[Tuple[Tuple[str, ...],
                                                       Tuple[str, ...]]]]] = {}
        self.vals: Dict[tuple, Any] = {}
        self.base_defs = base_defs
        self.hits = 0
        self.misses = 0

    def put(self, key: tuple, val: Any) -> None:
        if len(self.vals) >= _VALS_CAP:
            self.vals.clear()
        self.vals[key] = val

    def stats(self) -> Tuple[int, int]:
        """(hits, misses) — the unit the parallel engine merges: each
        worker's forked store counts independently, and the parent folds
        the per-chunk deltas back so the end-of-run memo gauges cover
        the whole run, not just the parent's share."""
        return self.hits, self.misses

    def merge_stats(self, hits: int, misses: int) -> None:
        self.hits += hits
        self.misses += misses


def analyze_closure(clo, defs: Dict[str, Any], vars) -> Optional[
        Tuple[Tuple[str, ...], Tuple[str, ...]]]:
    """Free-state-variable analysis of a closure body.

    Returns (unprimed deps, primed deps) sorted, or None if the body is
    not safely memoizable."""
    state: Set[str] = set()
    primed: Set[str] = set()
    varset = set(vars)
    in_progress: Set[int] = set()

    def resolve_name(name: str, local: Set[str], prime_mode: bool):
        if name in local:
            return
        if name in varset:
            (primed if prime_mode else state).add(name)
            return
        if name in defs:
            walk_target(defs[name], prime_mode)
            return
        from .stdlib import BUILTIN_OPS  # late import (module cycle)
        if name in BUILTIN_OPS or name in _LOGICAL:
            if name in IMPURE_BUILTINS:
                raise _Uncacheable(name)
            return
        # unknown: would resolve through runtime bindings we cannot see
        raise _Uncacheable(name)

    def walk_target(tgt, prime_mode: bool):
        # a referenced definition: fold its own deps in
        from .eval import OpClosure, BuiltinOp  # late import
        if isinstance(tgt, OpClosure):
            if tgt.bound:
                raise _Uncacheable("closure with captured environment")
            if id(tgt) in in_progress:
                return  # RECURSIVE: deps covered by the outer walk
            in_progress.add(id(tgt))
            try:
                body = tgt.body
                local = set(tgt.params)
                if isinstance(body, A.FnConstrDef):
                    for pats, sexpr in body.binders:
                        walk(sexpr, local, prime_mode)
                        local = local | set(_pat_names(pats))
                    local.add(body.name)
                    walk(body.body, local, prime_mode)
                else:
                    walk(body, local, prime_mode)
            finally:
                in_progress.discard(id(tgt))
            return
        if isinstance(tgt, BuiltinOp):
            if tgt.name in IMPURE_BUILTINS:
                raise _Uncacheable(tgt.name)
            return
        if isinstance(tgt, A.Node):
            raise _Uncacheable("AST-valued definition")
        # plain value (cfg constant, model value, number, set...)
        return

    def _pat_names(pats):
        out = []
        for p in pats:
            if isinstance(p, str):
                out.append(p)
            else:
                out.extend(_pat_names(p))
        return out

    def walk_binders(binders, local: Set[str], prime_mode: bool) -> Set[str]:
        loc = set(local)
        for pats, sexpr in binders:
            if sexpr is not None:
                walk(sexpr, loc, prime_mode)
            loc |= set(_pat_names(pats))
        return loc

    def walk(e, local: Set[str], prime_mode: bool):
        if isinstance(e, (A.Num, A.Str, A.Bool, A.At)):
            return
        if isinstance(e, A.Ident):
            resolve_name(e.name, local, prime_mode)
            return
        if isinstance(e, A.OpApp):
            if e.path or e.name == "!sel":
                raise _Uncacheable("instance path / !sel")
            if e.name not in local and e.name not in _LOGICAL:
                resolve_name(e.name, local, prime_mode)
            for a in e.args:
                walk(a, local, prime_mode)
            return
        if isinstance(e, A.Prime):
            if prime_mode:
                raise _Uncacheable("nested prime")
            walk(e.expr, local, True)
            return
        if isinstance(e, A.FnApp):
            walk(e.fn, local, prime_mode)
            for a in e.args:
                walk(a, local, prime_mode)
            return
        if isinstance(e, A.Dot):
            walk(e.expr, local, prime_mode)
            return
        if isinstance(e, (A.TupleExpr, A.SetEnum)):
            for x in e.items:
                walk(x, local, prime_mode)
            return
        if isinstance(e, A.SetFilter):
            walk(e.set, local, prime_mode)
            loc = local | set(_pat_names([e.var]))
            walk(e.pred, loc, prime_mode)
            return
        if isinstance(e, A.SetMap):
            loc = walk_binders(e.binders, local, prime_mode)
            walk(e.expr, loc, prime_mode)
            return
        if isinstance(e, A.FnDef):
            loc = walk_binders(e.binders, local, prime_mode)
            walk(e.body, loc, prime_mode)
            return
        if isinstance(e, A.FnSet):
            walk(e.dom, local, prime_mode)
            walk(e.rng, local, prime_mode)
            return
        if isinstance(e, (A.RecordExpr, A.RecordSet)):
            for _nm, x in e.fields:
                walk(x, local, prime_mode)
            return
        if isinstance(e, A.Except):
            walk(e.fn, local, prime_mode)
            for path, rhs in e.updates:
                for kind, item in path:
                    if kind == "idx":
                        for x in item:
                            walk(x, local, prime_mode)
                walk(rhs, local, prime_mode)
            return
        if isinstance(e, A.If):
            walk(e.cond, local, prime_mode)
            walk(e.then, local, prime_mode)
            walk(e.els, local, prime_mode)
            return
        if isinstance(e, A.Case):
            for c, v in e.arms:
                walk(c, local, prime_mode)
                walk(v, local, prime_mode)
            if e.other is not None:
                walk(e.other, local, prime_mode)
            return
        if isinstance(e, A.Let):
            loc = set(local)
            # LET RECURSIVE declarations put names in scope before their
            # definitions (textbookSnapshotIsolation.tla:647)
            for d in e.defs:
                if isinstance(d, A.RecursiveDecl):
                    loc |= {nm for nm, _arity in d.names}
            for d in e.defs:
                if isinstance(d, A.OpDef):
                    walk(d.body, loc | set(d.params), prime_mode)
                    loc.add(d.name)
                elif isinstance(d, A.FnConstrDef):
                    loc2 = set(loc)
                    for pats, sexpr in d.binders:
                        walk(sexpr, loc2, prime_mode)
                        loc2 |= set(_pat_names(pats))
                    walk(d.body, loc2 | {d.name}, prime_mode)
                    loc.add(d.name)
                elif isinstance(d, A.RecursiveDecl):
                    pass
                else:
                    raise _Uncacheable("unsupported LET unit")
            walk(e.body, loc, prime_mode)
            return
        if isinstance(e, A.Quant):
            loc = walk_binders(e.binders, local, prime_mode)
            walk(e.body, loc, prime_mode)
            return
        if isinstance(e, A.Choose):
            if e.set is not None:
                walk(e.set, local, prime_mode)
            loc = local | set(_pat_names([e.var]))
            walk(e.pred, loc, prime_mode)
            return
        if isinstance(e, A.Lambda):
            walk(e.body, local | set(e.params), prime_mode)
            return
        # temporal/action forms, ENABLED, UNCHANGED, \AA/\EE: not
        # deterministic in (deps, args) under this evaluation model
        raise _Uncacheable(type(e).__name__)

    try:
        body = clo.body
        local = set(clo.params)
        if isinstance(body, A.FnConstrDef):
            return None  # recursive fn constructors build their own memo
        walk(body, local, False)
    except _Uncacheable:
        return None
    return (tuple(sorted(state)), tuple(sorted(primed)))


def memo_key(store: MemoStore, clo, defs, ctx, args=()) -> Optional[tuple]:
    """Build the cache key for applying `clo` to `args` in `ctx`, or None
    when this call is not cacheable (non-base defs table, unknown deps,
    partial state, unhashable argument)."""
    if defs is not store.base_defs:
        return None
    ent = store.deps.get(id(clo))
    if ent is None or ent[0] is not clo:
        ent = (clo, analyze_closure(clo, defs, ctx.vars))
        store.deps[id(clo)] = ent
    an = ent[1]
    if an is None:
        return None
    sdeps, pdeps = an
    # type names ride along because Python conflates True==1/False==0 in
    # tuple equality — TLA+ treats them as different values (sem/values.py
    # _enum_key has the same guard). Nested conflation inside containers
    # remains the documented True/1 deviation.
    parts = [id(clo)]
    for a in args:
        parts.append(type(a).__name__)
        parts.append(a)
    st, pr = ctx.state, ctx.primes
    for v in sdeps:
        if st is None or v not in st:
            return None
        parts.append(type(st[v]).__name__)
        parts.append(st[v])
    for v in pdeps:
        if pr is None or v not in pr:
            return None
        parts.append(type(pr[v]).__name__)
        parts.append(pr[v])
    key = tuple(parts)
    try:
        hash(key)
    except TypeError:
        return None
    return key
