r"""TLA+ value domain for the reference interpreter.

Python natives carry most of the weight: int, bool, str, frozenset. Functions
(which subsume sequences, tuples, records, and bags — e.g. raft's message bag
is a function Message -> Nat, /root/reference/examples/raft.tla:33-36) are the
immutable Fcn class. Model values come from cfg CONSTANT bindings.

A total deterministic order over all values (sort_key) fixes CHOOSE witnesses
and canonical display order, mirroring TLC's deterministic enumeration.

Known deviation: Python's True == 1 could collapse BOOLEAN/0/1-int mixes.
TLC raises a comparability error on such mixes; specs that TLC accepts
without error never hit this. Guarded (raises like TLC): tla_eq on direct
bool-int comparison, in_set membership, set-operator operands
(\cup/\cap/\/UNION/enumeration/comprehension via check_set_mix), and —
since round 4 — NESTED mixes wherever a collapse could occur: two values
that are Python-equal only via a nested True==1 conflation (e.g. {{TRUE}}
vs {{1}}, <<TRUE>> vs <<1>>) raise at the comparison/construction site
(_assert_no_collapse, gated by the cheap _has_boolish scan). Residual
deviation (answer-preserving): TLC also raises when comparing nested
values that are NOT Python-equal, e.g. {{TRUE}} = {{2}} — we return
FALSE where TLC errors; no wrong answer is produced, only a missing
error report on specs TLC would reject anyway.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Tuple


class EvalError(Exception):
    pass


class ModelValue:
    """An uninterpreted model value (cfg `Ident = Ident`). Compares unequal
    to every other value, equal only to itself."""
    __slots__ = ("name",)
    _interned: Dict[str, "ModelValue"] = {}

    def __new__(cls, name: str):
        mv = cls._interned.get(name)
        if mv is None:
            mv = object.__new__(cls)
            mv.name = name
            cls._interned[name] = mv
        return mv

    def __reduce__(self):
        # re-intern on unpickle (checkpoint/resume); default pickling
        # would call __new__ with no args and break identity equality
        return (ModelValue, (self.name,))

    def __repr__(self):
        return self.name

    def __hash__(self):
        return hash(("$mv", self.name))

    def __eq__(self, other):
        return self is other


class Fcn:
    """Immutable TLA+ function. Sequences are functions with domain 1..n,
    records functions with string domain — all compare uniformly."""
    __slots__ = ("_d", "_hash", "_sk", "_hb")

    def __init__(self, mapping: Iterable):
        d = dict(mapping)
        self._d = d
        self._hash = None
        self._sk = None  # cached sort_key (never pickled — see __reduce__)
        self._hb = None  # cached _has_bool (rebuilt on unpickle too)

    @property
    def d(self) -> dict:
        return self._d

    def domain(self) -> frozenset:
        return frozenset(self._d.keys())

    def apply(self, arg):
        try:
            return self._d[arg]
        except KeyError:
            raise EvalError(f"function applied outside domain: {fmt(arg)} "
                            f"not in {fmt(self.domain())}")
        except TypeError:
            raise EvalError(f"unhashable function argument {arg!r}")

    def is_seq(self) -> bool:
        n = len(self._d)
        return all(isinstance(k, int) for k in self._d) and \
            set(self._d.keys()) == set(range(1, n + 1))

    def is_record(self) -> bool:
        return len(self._d) > 0 and all(isinstance(k, str) for k in self._d)

    def as_list(self) -> List[Any]:
        n = len(self._d)
        return [self._d[i] for i in range(1, n + 1)]

    def __len__(self):
        return len(self._d)

    def __eq__(self, other):
        if not isinstance(other, Fcn):
            return NotImplemented
        return self._d == other._d

    def __hash__(self):
        if self._hash is None:
            self._hash = hash(frozenset(self._d.items()))
        return self._hash

    def __reduce__(self):
        # never pickle the cached hash: str/frozenset hashes are
        # per-process (PYTHONHASHSEED), so a checkpointed hash is wrong
        # in the resuming process and set/dict membership silently breaks.
        # Rebuilding via __init__ also forces lazy subclasses (RecFcn)
        # to a plain materialized Fcn, whose closures cannot pickle
        return (Fcn, (list(self._materialized_items()),))

    def _materialized_items(self):
        return self._d.items()

    def __repr__(self):
        return fmt(self)


EMPTY_FCN = Fcn({})


def mk_seq(items: Iterable) -> Fcn:
    return Fcn({i + 1: v for i, v in enumerate(items)})


def mk_record(fields: Dict[str, Any]) -> Fcn:
    return Fcn(fields)


class InfiniteSet:
    """Sentinel for Nat, Int, STRING, Seq(S): supports membership, refuses
    enumeration (TLC behaves the same way)."""
    __slots__ = ("kind", "param")

    def __init__(self, kind: str, param=None):
        self.kind = kind
        self.param = param

    def contains(self, v) -> bool:
        if self.kind == "Nat":
            return isinstance(v, int) and not isinstance(v, bool) and v >= 0
        if self.kind == "Int":
            return isinstance(v, int) and not isinstance(v, bool)
        if self.kind == "STRING":
            return isinstance(v, str)
        if self.kind == "Seq":
            return isinstance(v, Fcn) and (len(v) == 0 or v.is_seq()) and \
                all(in_set(x, self.param) for x in v.as_list())
        if self.kind == "Real":
            return isinstance(v, (int, float)) and not isinstance(v, bool)
        raise EvalError(f"unknown infinite set {self.kind}")

    def __repr__(self):
        return self.kind if self.param is None else f"Seq({fmt(self.param)})"

    def __eq__(self, other):
        return isinstance(other, InfiniteSet) and self.kind == other.kind \
            and self.param == other.param

    def __hash__(self):
        return hash(("$inf", self.kind, self.param))


class FcnSetV:
    """Lazy [S -> T]: membership without materialization, so TypeOK-style
    checks like opQ \\in [Proc -> Seq(opVal)] work with infinite ranges
    (AdvancedExamples/InnerSerial.tla:24). Enumeration materializes."""
    __slots__ = ("dom", "rng", "_mat")

    def __init__(self, dom, rng):
        self.dom = dom
        self.rng = rng
        self._mat = None

    def contains(self, v) -> bool:
        if not isinstance(v, Fcn):
            return False
        if v.domain() != (self.dom if isinstance(self.dom, frozenset)
                          else frozenset(enumerate_set(self.dom))):
            return False
        return all(in_set(x, self.rng) for x in v.d.values())

    def materialize(self) -> frozenset:
        if self._mat is None:
            import itertools
            delems = enumerate_set(self.dom)
            relems = enumerate_set(self.rng)
            self._mat = frozenset(
                Fcn(dict(zip(delems, combo)))
                for combo in itertools.product(relems, repeat=len(delems)))
        return self._mat

    def __eq__(self, other):
        if isinstance(other, FcnSetV):
            return self.dom == other.dom and self.rng == other.rng
        if isinstance(other, frozenset):
            return self.materialize() == other
        return NotImplemented

    def __hash__(self):
        return hash(self.materialize())

    def __repr__(self):
        return f"[{fmt(self.dom)} -> {fmt(self.rng)}]"


NAT = InfiniteSet("Nat")
INT = InfiniteSet("Int")
REAL = InfiniteSet("Real")
STRING_SET = InfiniteSet("STRING")
BOOLEAN_SET = frozenset({True, False})


def in_set(v, s) -> bool:
    if isinstance(s, FcnSetV):
        return s.contains(v)
    if isinstance(s, frozenset):
        # Python's True == 1 must not leak into TLA+ semantics where
        # TRUE /= 1: disambiguate bool/int hash collisions by scan.
        if isinstance(v, bool):
            if not _has_bool(s):
                return False  # no bool anywhere in s; a hash hit is 0/1
            return any(x is v for x in s)
        if isinstance(v, int) and v in (0, 1):
            if not _has_bool(s):
                return v in s  # every hash-equal member is an int
            return any(x == v and not isinstance(x, bool) for x in s)
        if isinstance(v, (frozenset, Fcn)) and (_has_bool(v)
                                                or _has_bool(s)):
            # container membership can match only via a nested True==1
            # conflation ({1} \in {{TRUE}}), which needs a bool on one
            # side: hash-check first (a miss can't collapse), and only on
            # a hit scan for the Python-equal member to raise like TLC
            if v not in s:
                return False
            for x in s:
                if x == v:
                    _assert_no_collapse(v, x)
                    return True
            return True  # unreachable: the hash hit guarantees a match
        return v in s
    if isinstance(s, InfiniteSet):
        return s.contains(v)
    raise EvalError(f"\\in applied to non-set {fmt(s)}")


_ENUM_CACHE: Dict[Any, List[Any]] = {}
_ENUM_CACHE_CAP = 1 << 16


def _enum_key(s: frozenset):
    # Python conflates True==1 / False==0, so {0, 1} and {FALSE, TRUE}
    # are EQUAL frozensets — TLA+ distinguishes them (sort_key ranks bool
    # before int). Tag the key with the exact bool subset: two Python-
    # equal sets can only differ in which of 0/1 are booleans, and the
    # bool subset pins that down ({0, TRUE} vs {1, FALSE} get distinct
    # keys), so the cache never serves ints as booleans or vice versa.
    bools = frozenset(x for x in s if type(x) is bool)
    return (s, bools)


def enumerate_set(s) -> List[Any]:
    """Deterministically ordered elements; raises on infinite sets.

    Results for frozensets are cached (values are immutable and equal sets
    enumerate identically) — callers must NOT mutate the returned list."""
    if isinstance(s, FcnSetV):
        return sorted(s.materialize(), key=sort_key)
    if isinstance(s, frozenset):
        key = _enum_key(s)
        hit = _ENUM_CACHE.get(key)
        if hit is None:
            if len(_ENUM_CACHE) >= _ENUM_CACHE_CAP:
                _ENUM_CACHE.clear()
            hit = sorted(s, key=sort_key)
            _ENUM_CACHE[key] = hit
        return hit
    if isinstance(s, InfiniteSet):
        raise EvalError(f"cannot enumerate infinite set {s!r}")
    raise EvalError(f"expected a set, got {fmt(s)}")


_TYPE_RANK = {bool: 0, int: 1, str: 2, ModelValue: 3, frozenset: 4, Fcn: 5,
              InfiniteSet: 6}


def sort_key(v):
    t = type(v)
    if t is bool:
        return (0, v)
    if t is int:
        return (1, v)
    if t is str:
        return (2, v)
    if t is ModelValue:
        return (3, v.name)
    if t is frozenset:
        return (4, len(v), tuple(sort_key(x) for x in enumerate_set(v)))
    if t is Fcn:
        sk = v._sk
        if sk is None:
            items = sorted(v.d.items(), key=lambda kv: sort_key(kv[0]))
            sk = (5, len(items),
                  tuple((sort_key(k), sort_key(x)) for k, x in items))
            v._sk = sk
        return sk
    if t is InfiniteSet:
        return (6, v.kind)
    if t is FcnSetV:
        return sort_key(v.materialize())
    if t is tuple:
        # engine-level state tuples (symmetry canonicalization)
        return tuple(sort_key(x) for x in v)
    raise EvalError(f"unorderable value {v!r}")


def _has_boolish(v) -> bool:
    """Could v participate in a True==1 collapse from EITHER side? True iff
    it contains a bool or a 0/1 integer anywhere. Used only at set
    CONSTRUCTION sites (check_set_mix), where pure-int members must still
    enter the nested-dedup dict so a later bool-bearing member can collide
    with them ({1} before {TRUE})."""
    if isinstance(v, bool):
        return True
    if isinstance(v, int):
        return v in (0, 1)
    if isinstance(v, frozenset):
        return any(_has_boolish(x) for x in v)
    if isinstance(v, Fcn):
        return any(_has_boolish(k) or _has_boolish(x)
                   for k, x in v.d.items())
    return False


_HAS_BOOL_CACHE: Dict[int, Tuple[Any, bool]] = {}
_HAS_BOOL_CACHE_CAP = 1 << 16


def _has_bool(v) -> bool:
    """Does v contain an ACTUAL bool anywhere? A True==1 conflation needs a
    bool on at least one side (int-vs-int positions never raise), so for a
    PAIR of Python-equal values `_has_bool(a) or _has_bool(b)` is the exact
    gate for _assert_no_collapse — unlike _has_boolish, a pure-int sequence
    or record (domain keys 1..n, 0/1 payloads) gates False and the hot
    equality/membership paths stay single-pass. Cached per container object
    (Fcn slot; id-keyed strong-ref table for frozensets)."""
    if isinstance(v, bool):
        return True
    if isinstance(v, Fcn):
        hb = v._hb
        if hb is None:
            # _materialized_items (not ._d) so a lazy RecFcn is forced
            # BEFORE the scan — scanning a partially-evaluated memo dict
            # would cache a stale False and silently equate a later
            # True==1 conflation instead of raising
            hb = any(_has_bool(k) or _has_bool(x)
                     for k, x in v._materialized_items())
            v._hb = hb
        return hb
    if isinstance(v, frozenset):
        e = _HAS_BOOL_CACHE.get(id(v))
        if e is not None and e[0] is v:
            return e[1]
        r = any(_has_bool(x) for x in v)
        if len(_HAS_BOOL_CACHE) >= _HAS_BOOL_CACHE_CAP:
            _HAS_BOOL_CACHE.clear()
        _HAS_BOOL_CACHE[id(v)] = (v, r)
        return r
    return False


def _assert_no_collapse(a, b) -> None:
    """Given a == b under PYTHON equality, raise EvalError if that
    equality rides a True==1 conflation anywhere inside — TLC treats
    BOOLEAN and integers as incomparable at every depth, so {{TRUE}}
    vs {{1}} is a comparability error there, never an equality."""
    if isinstance(a, bool) != isinstance(b, bool):
        raise EvalError(
            f"attempted to compare {fmt(a)} with {fmt(b)} (BOOLEAN vs "
            "integer, incomparable in TLA+; TLC raises here too)")
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        bd = {x: x for x in b}
        for m in a:
            _assert_no_collapse(m, bd[m])
    elif isinstance(a, Fcn) and isinstance(b, Fcn):
        bkeys = {k: k for k in b.d}
        for k, v in a.d.items():
            bk = bkeys[k]
            _assert_no_collapse(k, bk)
            _assert_no_collapse(v, b.d[bk])


def check_set_mix(vals) -> None:
    """TLC comparability: a set holding both BOOLEAN and integer members
    is an error, never a silent True==1 collapse (the documented
    deviation above). Called by the set CONSTRUCTION sites — enumeration,
    comprehension, union-family operators (sem/eval.py, sem/stdlib.py).
    Also catches NESTED collapses: two members that are Python-equal only
    via an inner True==1 conflation ({{TRUE}, {1}} would silently dedup
    to a 1-element set before any downstream check could see it)."""
    has_bool = has_int = False
    nested = None
    for v in vals:
        if isinstance(v, bool):
            has_bool = True
        elif isinstance(v, int):
            has_int = True
        elif isinstance(v, (frozenset, Fcn)) and _has_boolish(v):
            if nested is None:
                nested = {}
            prev = nested.setdefault(v, v)
            if prev is not v:
                _assert_no_collapse(prev, v)
        if has_bool and has_int:
            raise EvalError(
                "set mixes BOOLEAN and integer values (incomparable in "
                "TLA+; TLC raises here too)")


def values_comparable(a, b) -> bool:
    """TLC-style comparability: model values compare (unequal) with anything;
    otherwise kinds must match."""
    if isinstance(a, ModelValue) or isinstance(b, ModelValue):
        return True
    ka, kb = _kind(a), _kind(b)
    return ka == kb


def _kind(v):
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, str):
        return "str"
    if isinstance(v, (frozenset, InfiniteSet, FcnSetV)):
        return "set"
    if isinstance(v, Fcn):
        return "fcn"
    return "other"


def tla_eq(a, b) -> bool:
    if isinstance(a, ModelValue) or isinstance(b, ModelValue):
        return a is b
    if not values_comparable(a, b):
        raise EvalError(f"attempted to compare {fmt(a)} with {fmt(b)}")
    if isinstance(a, FcnSetV):
        return a == b
    if isinstance(b, FcnSetV):
        return b == a
    r = a == b
    if r and isinstance(a, (frozenset, Fcn)) and (_has_bool(a)
                                                  or _has_bool(b)):
        # Python-equal containers may be equal only via a nested True==1
        # conflation ({{TRUE}} == {{1}}), which needs an actual bool on
        # one side: TLC raises there, never equates
        _assert_no_collapse(a, b)
    return r


def fmt(v) -> str:
    """TLC-style display, used for counterexample traces
    (format reference: /root/reference/README.md:268-318)."""
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, ModelValue):
        return v.name
    if isinstance(v, frozenset):
        return "{" + ", ".join(fmt(x) for x in sorted(v, key=sort_key)) + "}"
    if isinstance(v, Fcn):
        if len(v) == 0:
            return "<<>>"
        if v.is_seq():
            return "<<" + ", ".join(fmt(x) for x in v.as_list()) + ">>"
        if v.is_record():
            return "[" + ", ".join(f"{k} |-> {fmt(x)}"
                                   for k, x in sorted(v.d.items())) + "]"
        items = sorted(v.d.items(), key=lambda kv: sort_key(kv[0]))
        return "(" + " @@ ".join(f"{fmt(k)} :> {fmt(x)}" for k, x in items) + ")"
    if isinstance(v, (InfiniteSet, FcnSetV)):
        return repr(v)
    return repr(v)
