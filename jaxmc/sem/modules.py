r"""Module loading: EXTENDS closure, INSTANCE substitution, cfg binding.

Builds the definition table the evaluator runs against. Standard modules
(Naturals, Integers, Sequences, FiniteSets, Bags, TLC, Reals, Peano) are
native (SURVEY.md §1 L2): their operators live in stdlib.BUILTIN_OPS and the
identifiers Nat/Int/Real/BOOLEAN/STRING are injected here.

INSTANCE semantics (needed for the Paxos refinement chain,
/root/reference/examples/Paxos/Paxos.tla:195): a named instance
`V == INSTANCE M WITH a <- e` creates a namespace in which M's definitions
are evaluated with M's constants/variables resolved through the
substitutions, themselves evaluated in the outer module's context. Omitted
substitutions default to the same-named outer entity.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..front import tla_ast as A
from ..front.parser import parse_module_text
from ..front.cfg import ModelConfig, CfgModelValue
from .values import (EvalError, ModelValue, BOOLEAN_SET, INT, NAT, REAL,
                     STRING_SET)
from .eval import Ctx, OpClosure, eval_expr, _force

NATIVE_MODULES = {"Naturals", "Integers", "Reals", "Sequences", "FiniteSets",
                  "Bags", "TLC", "Peano", "ProtoReals"}

BASE_IDENTS = {
    "Nat": NAT, "Int": INT, "Real": REAL,
    "BOOLEAN": BOOLEAN_SET, "STRING": STRING_SET,
    "Infinity": ModelValue("$Infinity"),
}


@dataclass
class LoadedModule:
    name: str
    ast: A.Module
    defs: Dict[str, Any] = field(default_factory=dict)
    constants: List[Tuple[str, int]] = field(default_factory=list)
    variables: List[str] = field(default_factory=list)
    assumes: List[A.Assume] = field(default_factory=list)
    path: Optional[str] = None


class InstanceNamespace:
    """Runtime value of `name(params) == INSTANCE M WITH substs`."""

    def __init__(self, module: LoadedModule, substs, params: Tuple[str, ...]):
        self.module = module
        self.substs = dict(substs)  # inner name -> outer expr
        self.params = params

    def enter(self, outer: Ctx, argvals) -> Ctx:
        """Build the evaluation context for expressions inside the instance."""
        if len(argvals) != len(self.params):
            raise EvalError(
                f"instance of {self.module.name} takes {len(self.params)} "
                f"arguments, got {len(argvals)}")
        outer_bound = {**outer.bound, **dict(zip(self.params, argvals))}
        subst_ctx_bound = outer_bound
        defs = dict(self.module.defs)
        # explicit substitutions: evaluate lazily in the outer context
        for inner_name, expr in self.substs.items():
            defs[inner_name] = OpClosure(inner_name, (), expr,
                                         dict(subst_ctx_bound), outer.defs)
        # implicit same-name substitutions for unsubstituted constants/vars
        for cname, arity in self.module.constants:
            if cname not in self.substs:
                defs[cname] = OpClosure(cname, (), A.Ident(cname),
                                        dict(subst_ctx_bound), outer.defs)
        for vname in self.module.variables:
            if vname not in self.substs and vname not in self.params:
                defs[vname] = OpClosure(vname, (), A.Ident(vname),
                                        dict(subst_ctx_bound), outer.defs)
        # params refer to outer values directly
        for p, v in zip(self.params, argvals):
            defs[p] = v
        return Ctx(defs, outer.bound, outer.state, outer.primes, outer.vars,
                   outer.on_print, outer.memo)

    def __repr__(self):
        return f"<instance of {self.module.name}>"


class Loader:
    def __init__(self, search_dirs: List[str]):
        self.search_dirs = list(search_dirs)
        self.cache: Dict[str, LoadedModule] = {}
        self.inner_modules: Dict[str, A.Module] = {}

    def find(self, name: str) -> str:
        for d in self.search_dirs:
            p = os.path.join(d, name + ".tla")
            if os.path.exists(p):
                return p
        raise EvalError(f"module {name} not found in {self.search_dirs}")

    def _parse_file(self, path: str) -> A.Module:
        from .. import obs
        tel = obs.current()
        with open(path, encoding="utf-8", errors="replace") as fh:
            src = fh.read()
        with tel.span("parse", module=os.path.basename(path)):
            ast = parse_module_text(src)
        from ..front.pcal import has_algorithm, translate_module
        if has_algorithm(src):
            # the in-memory equivalent of `make transpile` (Makefile:4)
            with tel.span("pcal_translate",
                          module=os.path.basename(path)):
                ast = translate_module(src, ast)
        return ast

    def load(self, name: str) -> LoadedModule:
        if name in self.cache:
            return self.cache[name]
        if name in self.inner_modules:
            return self.build(self.inner_modules[name], path=None)
        path = self.find(name)
        return self.build(self._parse_file(path), path, preferred_name=name)

    def load_path(self, path: str) -> LoadedModule:
        d = os.path.dirname(os.path.abspath(path))
        if d not in self.search_dirs:
            self.search_dirs.insert(0, d)
        return self.build(self._parse_file(path), path)

    def build(self, ast: A.Module, path: Optional[str],
              preferred_name: Optional[str] = None) -> LoadedModule:
        name = preferred_name or ast.name
        if name in self.cache:
            return self.cache[name]
        m = LoadedModule(name=name, ast=ast, path=path)
        self.cache[name] = m
        defs: Dict[str, Any] = dict(BASE_IDENTS)
        for ext in ast.extends:
            if ext in NATIVE_MODULES:
                continue
            sub = self.load(ext)
            defs.update(sub.defs)
            m.constants.extend(c for c in sub.constants
                               if c not in m.constants)
            m.variables.extend(v for v in sub.variables
                               if v not in m.variables)
        for u in ast.units:
            if isinstance(u, A.Module):
                # nested inner module: register for later INSTANCE
                self.inner_modules[u.name] = u
            elif isinstance(u, A.Constants):
                m.constants.extend(u.names)
            elif isinstance(u, A.Variables):
                m.variables.extend(u.names)
            elif isinstance(u, A.OpDef):
                defs[u.name] = OpClosure(u.name, u.params, u.body,
                                         stable=True)
            elif isinstance(u, A.FnConstrDef):
                defs[u.name] = OpClosure(u.name, (), u, stable=True)
            elif isinstance(u, A.InstanceDef):
                if u.name is None:
                    if u.module in NATIVE_MODULES:
                        continue
                    if u.substs:
                        raise EvalError(
                            "bare INSTANCE with WITH not supported")
                    sub = self.load(u.module)
                    defs.update(sub.defs)
                    m.constants.extend(c for c in sub.constants
                                       if c not in m.constants)
                    m.variables.extend(v for v in sub.variables
                                       if v not in m.variables)
                else:
                    sub = self.load(u.module)
                    defs[u.name] = InstanceNamespace(sub, u.substs, u.params)
            elif isinstance(u, A.Assume):
                m.assumes.append(u)
            elif isinstance(u, (A.Theorem, A.RecursiveDecl)):
                continue
            else:
                raise EvalError(f"unsupported module unit {u!r}")
        m.defs = defs
        return m


@dataclass
class Model:
    """A loaded module plus a bound cfg: ready to check."""
    module: LoadedModule
    cfg: ModelConfig
    init: A.Node
    next: A.Node
    invariants: List[Tuple[str, A.Node]]
    constraints: List[Tuple[str, A.Node]]
    action_constraints: List[Tuple[str, A.Node]]
    properties: List[Tuple[str, A.Node]]
    symmetry: Optional[A.Node]
    # cfg VIEW: states are deduplicated by this expression's VALUE instead
    # of the full state (TLC semantics, ConfigFileGrammar.tla:8-11) —
    # interp backend only; the jax backends reject it loudly
    view: Optional[A.Node]
    vars: Tuple[str, ...]
    defs: Dict[str, Any]
    check_deadlock: bool = True
    # fairness conjuncts of the SPECIFICATION formula (WF/SF, possibly
    # quantified or behind named ops) — consumed by engine/liveness.py
    fairness: List[A.Node] = field(default_factory=list)
    _memo: Any = field(default=None, repr=False, compare=False)

    def ctx(self, state=None, primes=None, on_print=None) -> Ctx:
        # one MemoStore per model: operator results are keyed by dependency
        # values, and constants differ between models (sem/memo.py)
        if self._memo is None:
            from .memo import MemoStore
            self._memo = MemoStore(self.defs)
        return Ctx(self.defs, {}, state, primes, self.vars, on_print,
                   self._memo)


def satisfies_constraints(model: "Model", state) -> bool:
    """Does `state` satisfy every cfg CONSTRAINT? The ONE implementation —
    the engine, the device backends, and layout sampling must agree on
    which states the search keeps (TLC discard semantics)."""
    if not model.constraints:
        return True  # skip the per-state ctx build entirely
    from .eval import _bool
    ctx = model.ctx(state=state)
    for name, expr in model.constraints:
        if not _bool(eval_expr(expr, ctx), f"constraint {name}"):
            return False
    return True


def _cfg_value(v):
    if isinstance(v, CfgModelValue):
        return ModelValue(v.name)
    if isinstance(v, frozenset):
        return frozenset(_cfg_value(x) for x in v)
    return v


def _split_spec(expr: A.Node, defs: Dict[str, Any]):
    """Extract Init and Next from Spec == Init /\\ [][Next]_vars /\\ fairness.

    A conjunct that is a plain name (LSpec == HC /\\ WF_hr(HCnxt),
    Liveness/LiveHourClock.tla:9) is expanded when its definition contains
    a [][N]_v somewhere — so nested Spec definitions resolve — and treated
    as the initial predicate otherwise."""
    from ..front.subst import contains_box
    init = None
    nxt = None
    sub = None
    fair = []

    def walk(e):
        nonlocal init, nxt, sub
        if isinstance(e, A.OpApp) and e.name == "/\\":
            walk(e.args[0])
            walk(e.args[1])
            return
        if isinstance(e, A.OpApp) and e.name == "[]" and \
                isinstance(e.args[0], A.BoxAction):
            if nxt is not None:
                raise EvalError("specification has two [][Next]_vars "
                                "conjuncts")
            nxt = e.args[0].action
            sub = e.args[0].sub
            return
        if isinstance(e, (A.Fair, A.Quant)):
            fair.append(e)
            return
        if isinstance(e, A.Ident):
            d = defs.get(e.name)
            if isinstance(d, OpClosure) and not d.params \
                    and contains_box(d.body):
                walk(d.body)
                return
        if init is None:
            init = e
        else:
            fair.append(e)

    walk(expr)
    if init is None or nxt is None:
        raise EvalError("could not extract Init and [][Next]_vars from "
                        "specification formula")
    return init, nxt, sub, fair


def bind_model_defs(module: LoadedModule, cfg: ModelConfig) -> Dict[str, Any]:
    """Bind cfg constants/overrides into a definition table."""
    defs = dict(module.defs)
    declared = {n for n, _ in module.constants}
    for cname, val in cfg.constants.items():
        defs[cname] = _cfg_value(val)
    for cname, defn in cfg.overrides.items():
        if defn not in defs:
            raise EvalError(f"cfg substitutes {cname} <- {defn}, "
                            f"but {defn} is not defined")
        defs[cname] = defs[defn]
    # scoped overrides (Ballot <-[Voting] MCBallot): rebuild the affected
    # instances with the extra substitution — never mutate the loader-cached
    # namespace, other models may share it
    for (modname, cname), defn in cfg.scoped_overrides.items():
        for k, v in list(defs.items()):
            if isinstance(v, InstanceNamespace) and v.module.name == modname:
                defs[k] = InstanceNamespace(
                    v.module, {**v.substs, cname: A.Ident(defn)}, v.params)
    missing = [n for n in declared if n not in defs]
    if missing:
        raise EvalError(f"constants not bound by cfg: {missing}")
    return defs


def bind_model(module: LoadedModule, cfg: ModelConfig) -> Model:
    """Bind cfg constants/overrides and resolve the checked formulas."""
    defs = bind_model_defs(module, cfg)
    vars = tuple(module.variables)

    def named(nm):
        d = defs.get(nm)
        if d is None:
            raise EvalError(f"cfg names unknown definition {nm}")
        if isinstance(d, OpClosure):
            return d.body
        raise EvalError(f"cfg name {nm} does not name a definition")

    fair: List[A.Node] = []
    if cfg.specification:
        spec_body = named(cfg.specification)
        init, nxt, _sub, fair = _split_spec(spec_body, defs)
    else:
        if not cfg.init or not cfg.next:
            raise EvalError("cfg must give SPECIFICATION or INIT+NEXT")
        init = named(cfg.init)
        nxt = named(cfg.next)

    invariants = [(nm, named(nm)) for nm in cfg.invariants]
    constraints = [(nm, named(nm)) for nm in cfg.constraints]
    action_constraints = [(nm, named(nm)) for nm in cfg.action_constraints]
    properties = [(nm, named(nm)) for nm in cfg.properties]
    symmetry = named(cfg.symmetry) if cfg.symmetry else None
    view = None
    if cfg.view:
        vd = defs.get(cfg.view)
        if not isinstance(vd, OpClosure):
            raise EvalError(f"cfg VIEW names unknown definition "
                            f"{cfg.view}")
        if vd.params:
            # TLC rejects parameterized views at config time too; letting
            # it through would crash on the unhashable closure later
            raise EvalError(f"cfg VIEW {cfg.view} takes parameters; a "
                            f"view must be a state expression")
        view = A.Ident(cfg.view)

    return Model(module=module, cfg=cfg, init=init, next=nxt,
                 invariants=invariants, constraints=constraints,
                 action_constraints=action_constraints,
                 properties=properties, symmetry=symmetry, view=view,
                 vars=vars, defs=defs, check_deadlock=cfg.check_deadlock,
                 fairness=fair)
