"""TLA+ parser (Pratt / precedence-climbing, with junction lists).

Covers the subset exercised by the reference corpus: full expression grammar
including indentation-sensitive /\\ and \\/ junction lists, LET/IN, EXCEPT,
CASE, quantifiers, CHOOSE, records, functions, tuples, temporal operators
([]/<>/~>, [A]_v, <<A>>_v, WF_/SF_), instance paths (V!Spec), and module units
(EXTENDS, CONSTANTS, VARIABLES, definitions, INSTANCE ... WITH, ASSUME,
THEOREM, RECURSIVE, nested modules).

Grammar reference: the corpus's own BNF at
/root/reference/examples/SpecifyingSystems/Syntax/TLAPlusGrammar.tla (module
grammar from :70); junction-list semantics per the *Specifying Systems* book.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .lexer import Token, tokenize
from . import tla_ast as A


class ParseError(Exception):
    def __init__(self, msg: str, tok: Optional[Token] = None):
        if tok is not None:
            msg = f"{msg} (at {tok.line}:{tok.col}, near {tok.text!r})"
        super().__init__(msg)


# infix operator -> (precedence, right_assoc)
INFIX = {
    "=>": (1, False),
    "<=>": (2, False), "\\equiv": (2, False),
    "~>": (2, False), "-+->": (2, False),
    "\\/": (3, False),
    "/\\": (3, False),
    "=": (5, False), "/=": (5, False), "#": (5, False),
    "<": (5, False), ">": (5, False), "<=": (5, False), "=<": (5, False),
    ">=": (5, False), "\\leq": (5, False), "\\geq": (5, False),
    "\\in": (5, False), "\\notin": (5, False),
    "\\subseteq": (5, False), "\\subset": (5, False),
    "\\supseteq": (5, False), "\\supset": (5, False),
    "\\prec": (5, False), "\\succ": (5, False),
    "\\sqsubseteq": (5, False), "\\sqsupseteq": (5, False),
    "@@": (6, False),
    ":>": (7, False),
    "\\cup": (8, False), "\\union": (8, False),
    "\\cap": (8, False), "\\intersect": (8, False),
    "\\": (8, False),
    "..": (9, False),
    "+": (10, False), "-": (10, False),
    "(+)": (10, False), "(-)": (10, False),
    "%": (10, False), "\\mod": (10, False),
    "*": (13, False), "/": (13, False), "\\div": (13, False),
    "\\o": (13, False), "\\circ": (13, False),
    "\\X": (13, False), "\\times": (13, False),
    "^": (14, True),
    # user-definable grammar-combinator ops (BNFGrammars.tla:5-27)
    "&": (13, False), "|": (10, False), "::=": (2, False),
}

POSTFIX_OPS = {"^*", "^+", "^#"}

PREFIX = {
    "~": 4, "\\lnot": 4, "\\neg": 4,
    "[]": 4, "<>": 4,
    "-": 12,
}

_STOP_KINDS = {"eof", "end4", "sep4", "prooflabel"}
# tokens that always terminate an expression
_STOP_OPS = {")", "]", "}", ">>", ",", ":", ";", "|->", "->", "<-", "]_", ">>_",
             ":=", "||", "@"}
_STOP_RESERVED = {"THEN", "ELSE", "IN", "OTHER", "EXCEPT", "WITH", "MODULE",
                  "EXTENDS", "CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES",
                  "ASSUME", "ASSUMPTION", "AXIOM", "THEOREM", "LEMMA",
                  "INSTANCE", "LOCAL", "RECURSIVE", "BY", "PROOF", "OBVIOUS",
                  "OMITTED", "QED"}


class Parser:
    def __init__(self, toks: List[Token]):
        self.toks = toks
        self.i = 0
        self.fences: List[int] = []  # junction-list columns

    # ---- token helpers ----
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def peek(self, k: int = 1) -> Token:
        j = min(self.i + k, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_op(self, *texts) -> bool:
        return self.cur.kind == "op" and self.cur.text in texts

    def at_res(self, *texts) -> bool:
        return self.cur.kind == "reserved" and self.cur.text in texts

    def expect_op(self, text) -> Token:
        if not self.at_op(text):
            raise ParseError(f"expected {text!r}", self.cur)
        return self.next()

    def expect_res(self, text) -> Token:
        if not self.at_res(text):
            raise ParseError(f"expected {text!r}", self.cur)
        return self.next()

    def expect_ident(self) -> str:
        if self.cur.kind != "ident":
            raise ParseError("expected identifier", self.cur)
        return self.next().text

    def _fenced(self) -> bool:
        """True if the current token lies at/left of the innermost junction
        bullet column — it then belongs to an enclosing construct."""
        return bool(self.fences) and self.cur.col <= self.fences[-1]

    def _expr_ended(self) -> bool:
        t = self.cur
        if t.kind in _STOP_KINDS:
            return True
        if self._fenced():
            return True
        if t.kind == "op" and t.text in _STOP_OPS:
            return True
        if t.kind == "reserved" and t.text in _STOP_RESERVED:
            return True
        # a new top-level definition: Ident == ... / Ident(params) == ...
        return False

    # ---- expressions ----
    def parse_expr(self, min_prec: int = 0) -> A.Node:
        lhs = self.parse_prefix()
        return self.parse_infix_loop(lhs, min_prec)

    def parse_infix_loop(self, lhs: A.Node, min_prec: int) -> A.Node:
        # /\ and \/ may not be mixed without parentheses (SANY rejects the
        # mix as a precedence conflict; parsing it silently would check the
        # wrong formula)
        junction_seen = None
        while True:
            if self._expr_ended():
                return lhs
            t = self.cur
            # postfix prime
            if t.kind == "op" and t.text == "'":
                self.next()
                lhs = A.Prime(lhs)
                continue
            if t.kind == "op" and t.text in POSTFIX_OPS:
                self.next()
                lhs = A.OpApp(t.text, (lhs,))
                continue
            # function application f[x, y]  (prec 16, tighter than all infix)
            if t.kind == "op" and t.text == "[":
                self.next()
                args = [self.parse_expr()]
                while self.at_op(","):
                    self.next()
                    args.append(self.parse_expr())
                self.expect_op("]")
                lhs = A.FnApp(lhs, tuple(args))
                continue
            # record access  (prec 17)
            if t.kind == "op" and t.text == ".":
                self.next()
                fld = self.expect_ident()
                lhs = A.Dot(lhs, fld)
                continue
            if t.kind != "op" or t.text not in INFIX:
                return lhs
            prec, right = INFIX[t.text]
            if prec < min_prec:
                return lhs
            op = t.text
            if op in ("/\\", "\\/"):
                if junction_seen is not None and junction_seen != op:
                    raise ParseError(
                        "/\\ and \\/ mixed without parentheses", t)
                junction_seen = op
            self.next()
            # n-ary cartesian product: a \X b \X c is the set of triples
            if op in ("\\X", "\\times"):
                items = [lhs, self.parse_expr(prec + 1)]
                while self.at_op("\\X", "\\times") and not self._expr_ended():
                    self.next()
                    items.append(self.parse_expr(prec + 1))
                lhs = A.OpApp("\\X", tuple(items))
                continue
            rhs = self.parse_expr(prec if right else prec + 1)
            lhs = A.OpApp(op, (lhs, rhs))

    def _parse_junction(self, op: str) -> A.Node:
        col = self.cur.col
        items = []
        while self.at_op(op) and self.cur.col == col:
            self.next()
            self.fences.append(col)
            try:
                items.append(self.parse_expr())
            finally:
                self.fences.pop()
        node = items[0]
        for it in items[1:]:
            node = A.OpApp(op, (node, it))
        return node

    def _try_parse_pattern(self):
        """Parse a tuple-destructuring pattern <<a, b>>; None if not one."""
        if not self.at_op("<<") or self.peek().kind != "ident":
            return None
        save = self.i
        self.next()
        names = [self.expect_ident()]
        while self.at_op(","):
            self.next()
            if self.cur.kind != "ident":
                self.i = save
                return None
            names.append(self.next().text)
        if not self.at_op(">>"):
            self.i = save
            return None
        self.next()
        return tuple(names)

    def _parse_binders(self, require_set=True):
        """Parse  x, y \\in S, z \\in T  (or untyped x, y when allowed).
        A name may be a tuple pattern <<a, b>> (destructured per element)."""
        binders = []
        while True:
            pat = self._try_parse_pattern()
            names = [pat if pat is not None else self.expect_ident()]
            while self.at_op(","):
                # lookahead: Ident (',' | '\in')
                save = self.i
                self.next()
                nm = self.expect_ident()
                names.append(nm)
                if self.at_op(",") or self.at_op("\\in"):
                    continue
                # it was the start of the next binder group? restore
                self.i = save
                names.pop()
                break
            if self.at_op("\\in"):
                self.next()
                s = self.parse_expr(6)  # bind tighter than \in level
                binders.append((tuple(names), s))
            else:
                if require_set:
                    raise ParseError("expected \\in in binder", self.cur)
                binders.append((tuple(names), None))
            if self.at_op(","):
                self.next()
                continue
            return tuple(binders)

    def parse_prefix(self) -> A.Node:
        t = self.cur
        if t.kind in _STOP_KINDS:
            raise ParseError("unexpected end of input", t)

        # junction lists
        if t.kind == "op" and t.text in ("/\\", "\\/"):
            return self._parse_junction(t.text)

        if t.kind == "number":
            self.next()
            return A.Num(int(t.text))
        if t.kind == "string":
            self.next()
            return A.Str(t.text)

        if t.kind == "reserved":
            w = t.text
            if w == "TRUE":
                self.next()
                return A.Bool(True)
            if w == "FALSE":
                self.next()
                return A.Bool(False)
            if w == "BOOLEAN":
                self.next()
                return A.Ident("BOOLEAN")
            if w == "STRING":
                self.next()
                return A.Ident("STRING")
            if w == "IF":
                self.next()
                c = self.parse_expr()
                self.expect_res("THEN")
                th = self.parse_expr()
                self.expect_res("ELSE")
                el = self.parse_expr()
                return A.If(c, th, el)
            if w == "CASE":
                self.next()
                arms = []
                other = None
                while True:
                    if self.at_res("OTHER"):
                        self.next()
                        self.expect_op("->")
                        other = self.parse_expr()
                    else:
                        g = self.parse_expr()
                        self.expect_op("->")
                        e = self.parse_expr()
                        arms.append((g, e))
                    if self.at_op("[]"):
                        self.next()
                        continue
                    break
                return A.Case(tuple(arms), other)
            if w == "LET":
                self.next()
                defs = []
                while True:
                    if self.at_res("RECURSIVE"):
                        self.next()
                        names = [(self.expect_ident(), self._parse_arity())]
                        while self.at_op(","):
                            self.next()
                            names.append((self.expect_ident(), self._parse_arity()))
                        defs.append(A.RecursiveDecl(tuple(names)))
                    else:
                        defs.append(self.parse_definition(local=False))
                    if self.at_res("IN"):
                        break
                self.expect_res("IN")
                body = self.parse_expr()
                return A.Let(tuple(defs), body)
            if w == "CHOOSE":
                self.next()
                var = self._try_parse_pattern()
                if var is None:
                    var = self.expect_ident()
                s = None
                if self.at_op("\\in"):
                    self.next()
                    s = self.parse_expr(6)
                self.expect_op(":")
                pred = self.parse_expr()
                return A.Choose(var, s, pred)
            if w == "ENABLED":
                self.next()
                return A.Enabled(self.parse_expr(4))
            if w == "UNCHANGED":
                self.next()
                return A.Unchanged(self.parse_expr(15))
            if w == "SUBSET":
                self.next()
                return A.OpApp("SUBSET", (self.parse_expr(8),))
            if w == "UNION":
                self.next()
                return A.OpApp("UNION", (self.parse_expr(8),))
            if w == "DOMAIN":
                self.next()
                return A.OpApp("DOMAIN", (self.parse_expr(9),))
            if w in ("WF_", "SF_"):
                self.next()
                sub = self.parse_subscript()
                self.expect_op("(")
                act = self.parse_expr()
                self.expect_op(")")
                return A.Fair(w[:2], sub, act)
            if w == "LAMBDA":
                self.next()
                params = [self.expect_ident()]
                while self.at_op(","):
                    self.next()
                    params.append(self.expect_ident())
                self.expect_op(":")
                body = self.parse_expr()
                return A.Lambda(tuple(params), body)
            raise ParseError(f"unexpected keyword {w}", t)

        if t.kind == "op":
            op = t.text
            if op in ("\\A", "\\E"):
                self.next()
                binders = self._parse_binders(require_set=False)
                self.expect_op(":")
                body = self.parse_expr()
                return A.Quant(op[1], binders, body)
            if op in ("\\AA", "\\EE"):
                self.next()
                names = [self.expect_ident()]
                while self.at_op(","):
                    self.next()
                    names.append(self.expect_ident())
                self.expect_op(":")
                body = self.parse_expr()
                return A.TemporalQuant(op[1:], tuple(names), body)
            if op == "(":
                self.next()
                saved, self.fences = self.fences, []
                try:
                    e = self.parse_expr()
                finally:
                    self.fences = saved
                self.expect_op(")")
                return e
            if op == "{":
                return self.parse_braces()
            if op == "[":
                return self.parse_brackets()
            if op == "<<":
                self.next()
                items = []
                saved, self.fences = self.fences, []
                try:
                    if not self.at_op(">>") and not self.at_op(">>_"):
                        items.append(self.parse_expr())
                        while self.at_op(","):
                            self.next()
                            items.append(self.parse_expr())
                finally:
                    self.fences = saved
                if self.at_op(">>_"):
                    # <<A>>_v  angle action
                    self.next()
                    if len(items) != 1:
                        raise ParseError("<<A>>_v with multiple exprs", t)
                    sub = self.parse_subscript()
                    return A.AngleAction(items[0], sub)
                self.expect_op(">>")
                return A.TupleExpr(tuple(items))
            if op == "@":
                self.next()
                return A.At()
            if op in PREFIX:
                self.next()
                if op == "[]":
                    # [] [A]_v or []P
                    arg = self.parse_expr(PREFIX[op])
                    return A.OpApp("[]", (arg,))
                if op == "<>":
                    arg = self.parse_expr(PREFIX[op])
                    return A.OpApp("<>", (arg,))
                arg = self.parse_expr(PREFIX[op])
                if op == "-":
                    return A.OpApp("-.", (arg,))
                return A.OpApp("~", (arg,)) if op in ("~", "\\lnot", "\\neg") else A.OpApp(op, (arg,))

        if t.kind == "ident":
            return self.parse_general_ident_tight()

        raise ParseError("unexpected token", t)

    def parse_subscript(self) -> A.Node:
        """Subscript of WF_/SF_/[A]_/<<A>>_: either a simple name, a tuple
        <<a, b>>, or a parenthesized expression.  A bare name is NOT treated
        as an operator application — in WF_vars(A) the parens belong to WF."""
        if self.cur.kind == "ident":
            return A.Ident(self.next().text)
        if self.at_op("<<"):
            self.next()
            items = [self.parse_expr()]
            while self.at_op(","):
                self.next()
                items.append(self.parse_expr())
            self.expect_op(">>")
            return A.TupleExpr(tuple(items))
        if self.at_op("("):
            self.next()
            e = self.parse_expr()
            self.expect_op(")")
            return e
        raise ParseError("expected fairness/action subscript", self.cur)

    def _parse_call_args(self) -> Tuple[A.Node, ...]:
        if not self.at_op("("):
            return ()
        self.next()
        saved, self.fences = self.fences, []
        try:
            lst = [self.parse_expr()]
            while self.at_op(","):
                self.next()
                lst.append(self.parse_expr())
            self.expect_op(")")
        finally:
            self.fences = saved
        return tuple(lst)

    def parse_general_ident_tight(self) -> A.Node:
        """An identifier with optional arguments and !-instance path segments
        (each segment may itself take arguments: Inner(mem)!Spec)."""
        name = self.expect_ident()
        args = self._parse_call_args()
        path = []
        while self.at_op("!"):
            nxt = self.peek()
            if nxt.kind == "op" and nxt.text == ":":
                # TLAPS-style assumption citation 'Name!:' — plain reference
                self.next()
                self.next()
                break
            if nxt.kind != "ident":
                break
            self.next()
            path.append((name, args))
            name = self.expect_ident()
            args = self._parse_call_args()
        node: A.Node
        if path or args:
            node = A.OpApp(name, args, tuple(path))
        else:
            node = A.Ident(name)
        # conjunct selection: Inv!2 picks the 2nd conjunct of Inv's definition
        # (used by MCPaxos.tla:41-43)
        while self.at_op("!") and self.peek().kind == "number":
            self.next()
            node = A.OpApp("!sel", (node, A.Num(int(self.next().text))))
        return node

    def parse_braces(self) -> A.Node:
        self.expect_op("{")
        saved, self.fences = self.fences, []
        try:
            if self.at_op("}"):
                self.next()
                return A.SetEnum(())
            # Try {x \in S : P} / {<<a, b>> \in S : P} filter forms
            save = self.i
            pat = self._try_parse_pattern()
            var = None
            if pat is not None:
                var = pat
            elif self.cur.kind == "ident" and self.peek().kind == "op" \
                    and self.peek().text == "\\in":
                var = self.expect_ident()
            if var is not None and self.at_op("\\in"):
                self.next()  # \in
                s = self.parse_expr(6)
                if self.at_op(":"):
                    self.next()
                    pred = self.parse_expr()
                    self.expect_op("}")
                    return A.SetFilter(var, s, pred)
            self.i = save
            first = self.parse_expr()
            if self.at_op(":"):
                # {e : x \in S, ...} map form
                self.next()
                binders = self._parse_binders()
                self.expect_op("}")
                return A.SetMap(first, binders)
            items = [first]
            while self.at_op(","):
                self.next()
                items.append(self.parse_expr())
            self.expect_op("}")
            return A.SetEnum(tuple(items))
        finally:
            self.fences = saved

    def parse_brackets(self) -> A.Node:
        """All '['-introduced forms: [x \\in S |-> e], [S -> T], [a |-> e],
        [a : S], [f EXCEPT ...], [A]_v."""
        self.expect_op("[")
        saved, self.fences = self.fences, []
        try:
            # record forms: Ident (|-> / :)
            if self.cur.kind == "ident" and self.peek().kind == "op" and \
                    self.peek().text in ("|->", ":") :
                if self.peek().text == "|->":
                    fields = []
                    while True:
                        nm = self.expect_ident()
                        self.expect_op("|->")
                        fields.append((nm, self.parse_expr()))
                        if self.at_op(","):
                            self.next()
                            continue
                        break
                    self.expect_op("]")
                    return A.RecordExpr(tuple(fields))
                else:
                    fields = []
                    while True:
                        nm = self.expect_ident()
                        self.expect_op(":")
                        fields.append((nm, self.parse_expr()))
                        if self.at_op(","):
                            self.next()
                            continue
                        break
                    self.expect_op("]")
                    return A.RecordSet(tuple(fields))
            # function constructor [x \in S, ... |-> e]  (names or <<a,b>> patterns)
            if self.cur.kind == "ident" or self.at_op("<<"):
                save = self.i
                try:
                    binders = self._parse_binders()
                    if self.at_op("|->"):
                        self.next()
                        body = self.parse_expr()
                        self.expect_op("]")
                        return A.FnDef(binders, body)
                except ParseError:
                    pass
                self.i = save
            first = self.parse_expr()
            if self.at_op("->"):
                self.next()
                rng = self.parse_expr()
                self.expect_op("]")
                return A.FnSet(first, rng)
            if self.at_res("EXCEPT"):
                self.next()
                updates = []
                while True:
                    self.expect_op("!")
                    path = []
                    while True:
                        if self.at_op("["):
                            self.next()
                            idx = [self.parse_expr()]
                            while self.at_op(","):
                                self.next()
                                idx.append(self.parse_expr())
                            self.expect_op("]")
                            path.append(("idx", tuple(idx)))
                        elif self.at_op("."):
                            self.next()
                            path.append(("dot", self.expect_ident()))
                        else:
                            break
                    self.expect_op("=")
                    rhs = self.parse_expr()
                    updates.append((tuple(path), rhs))
                    if self.at_op(","):
                        self.next()
                        continue
                    break
                self.expect_op("]")
                return A.Except(first, tuple(updates))
            if self.at_op("]_"):
                self.next()
                self.fences = saved  # subscript is outside the brackets
                sub = self.parse_subscript()
                return A.BoxAction(first, sub)
            self.expect_op("]")
            raise ParseError("unrecognized [...] form", self.cur)
        finally:
            self.fences = saved

    # ---- module units ----
    # infix lexemes a user module may (re)define: a (+) b == ..., d :> e == ...
    _DEFINABLE_INFIX = set(INFIX) - {"=", "=>", "\\in"}

    def parse_definition(self, local: bool) -> A.Node:
        """Parse one definition: Op == e, Op(p, q) == e, f[x \\in S] == e,
        infix  a OP b == e,  prefix  -. a == e,  postfix  a ^* == e."""
        # prefix operator definition
        if self.at_op("-.") and self.peek().kind == "ident":
            self.next()
            p = self.expect_ident()
            self.expect_op("==")
            return A.OpDef("-.", (p,), self.parse_expr(), local)
        name = self.expect_ident()
        # infix operator definition
        if self.cur.kind == "op" and self.cur.text in self._DEFINABLE_INFIX \
                and self.peek().kind == "ident" \
                and self.peek(2).kind == "op" and self.peek(2).text == "==":
            op = self.next().text
            rhsname = self.expect_ident()
            self.expect_op("==")
            return A.OpDef(op, (name, rhsname), self.parse_expr(), local)
        # postfix operator definition
        if self.cur.kind == "op" and self.cur.text in POSTFIX_OPS \
                and self.peek().kind == "op" and self.peek().text == "==":
            op = self.next().text
            self.expect_op("==")
            return A.OpDef(op, (name,), self.parse_expr(), local)
        params: List[str] = []
        if self.at_op("("):
            self.next()
            params.append(self._param_name())
            while self.at_op(","):
                self.next()
                params.append(self._param_name())
            self.expect_op(")")
            self.expect_op("==")
            body = self._parse_def_body(name, tuple(params), local)
            return body
        if self.at_op("["):
            self.next()
            binders = self._parse_binders()
            self.expect_op("]")
            self.expect_op("==")
            body = self.parse_expr()
            return A.FnConstrDef(name, binders, body, local)
        self.expect_op("==")
        return self._parse_def_body(name, (), local)

    def _param_name(self) -> str:
        # ordinary name or operator-parameter decl like  Op(_, _)  /  _ (+) _
        if self.cur.kind == "ident":
            nm = self.next().text
            if self.at_op("("):
                # higher-order param  P(_, _): record arity in name only
                self.next()
                while self.at_op("_", ","):
                    self.next()
                self.expect_op(")")
            return nm
        if self.at_op("_"):
            raise ParseError("infix operator definitions not supported", self.cur)
        raise ParseError("expected parameter name", self.cur)

    def _parse_def_body(self, name, params, local) -> A.Node:
        if self.at_res("INSTANCE"):
            self.next()
            mod = self.expect_ident()
            substs = self._parse_with()
            return A.InstanceDef(name, params, mod, substs, local)
        body = self.parse_expr()
        return A.OpDef(name, params, body, local)

    def _parse_with(self):
        substs = []
        if self.at_res("WITH"):
            self.next()
            while True:
                nm = self.expect_ident()
                self.expect_op("<-")
                substs.append((nm, self.parse_expr()))
                if self.at_op(","):
                    self.next()
                    continue
                break
        return tuple(substs)

    def _at_definition_start(self) -> bool:
        if self.at_op("-.") and self.peek().kind == "ident" \
                and self.peek(2).kind == "op" and self.peek(2).text == "==":
            return True
        if self.cur.kind != "ident":
            return False
        t1 = self.peek()
        if t1.kind == "op" and t1.text == "==":
            return True
        if t1.kind == "op" and t1.text in self._DEFINABLE_INFIX \
                and self.peek(2).kind == "ident" \
                and self.peek(3).kind == "op" and self.peek(3).text == "==":
            return True
        if t1.kind == "op" and t1.text in POSTFIX_OPS \
                and self.peek(2).kind == "op" and self.peek(2).text == "==":
            return True
        if t1.kind == "op" and t1.text in ("(", "["):
            # scan ahead for matching close then '=='
            depth = 0
            j = self.i + 1
            while j < len(self.toks) - 1:
                tt = self.toks[j]
                if tt.kind == "op" and tt.text in ("(", "[", "{"):
                    depth += 1
                elif tt.kind == "op" and tt.text in (")", "]", "}"):
                    depth -= 1
                    if depth == 0:
                        nx = self.toks[j + 1]
                        return nx.kind == "op" and nx.text == "=="
                elif depth == 0:
                    return False
                j += 1
        return False

    def parse_module(self) -> A.Module:
        # ---- MODULE name ----
        while not (self.cur.kind == "sep4" and self.peek().kind == "reserved"
                   and self.peek().text == "MODULE"):
            if self.cur.kind == "eof":
                raise ParseError("no module header found", self.cur)
            self.next()
        self.next()  # sep4
        self.expect_res("MODULE")
        name = self.expect_ident()
        if self.cur.kind == "sep4":
            self.next()
        extends: List[str] = []
        units: List[A.Node] = []
        if self.at_res("EXTENDS"):
            self.next()
            extends.append(self.expect_ident())
            while self.at_op(","):
                self.next()
                extends.append(self.expect_ident())
        while True:
            t = self.cur
            if t.kind == "eof":
                break
            if t.kind == "end4":
                self.next()
                break
            if t.kind == "sep4":
                if self.peek().kind == "reserved" and self.peek().text == "MODULE":
                    units.append(self.parse_module())
                    continue
                self.next()
                continue
            if t.kind == "reserved":
                w = t.text
                if w in ("CONSTANT", "CONSTANTS"):
                    self.next()
                    names = []
                    while True:
                        nm = self.expect_ident()
                        names.append((nm, self._parse_arity()))
                        if self.at_op(","):
                            self.next()
                            continue
                        break
                    units.append(A.Constants(tuple(names)))
                    continue
                if w in ("VARIABLE", "VARIABLES"):
                    self.next()
                    names = [self.expect_ident()]
                    while self.at_op(","):
                        self.next()
                        names.append(self.expect_ident())
                    units.append(A.Variables(tuple(names)))
                    continue
                if w in ("ASSUME", "ASSUMPTION", "AXIOM"):
                    self.next()
                    nm = None
                    if self._at_definition_start():
                        nm = self.expect_ident()
                        self.expect_op("==")
                    units.append(A.Assume(nm, self.parse_expr()))
                    continue
                if w in ("THEOREM", "LEMMA", "COROLLARY"):
                    self.next()
                    nm = None
                    if self._at_definition_start():
                        nm = self.expect_ident()
                        self.expect_op("==")
                    units.append(A.Theorem(nm, self.parse_expr()))
                    self._skip_proof()
                    continue
                if w == "LOCAL":
                    self.next()
                    if self.at_res("INSTANCE"):
                        self.next()
                        mod = self.expect_ident()
                        units.append(A.InstanceDef(None, (), mod, self._parse_with(), True))
                    else:
                        d = self.parse_definition(local=True)
                        units.append(d)
                    continue
                if w == "INSTANCE":
                    self.next()
                    mod = self.expect_ident()
                    units.append(A.InstanceDef(None, (), mod, self._parse_with(), False))
                    continue
                if w == "RECURSIVE":
                    self.next()
                    names = []
                    while True:
                        nm = self.expect_ident()
                        names.append((nm, self._parse_arity()))
                        if self.at_op(","):
                            self.next()
                            continue
                        break
                    units.append(A.RecursiveDecl(tuple(names)))
                    continue
                raise ParseError(f"unexpected {w} at module level", t)
            if self._at_definition_start():
                units.append(self.parse_definition(local=False))
                continue
            raise ParseError("unexpected token at module level", t)
        return A.Module(name, tuple(extends), tuple(units))

    def _parse_arity(self) -> int:
        """Parse the (_, _, ...) suffix of an operator declaration."""
        if not self.at_op("("):
            return 0
        self.next()
        arity = 0
        while not self.at_op(")"):
            if self.at_op("_"):
                self.next()
                arity += 1
            elif self.at_op(","):
                self.next()
            else:
                raise ParseError("expected _ in operator arity decl", self.cur)
        self.next()
        return arity

    _PROOF_WORDS = {"PROOF", "BY", "OBVIOUS", "OMITTED", "QED"}
    _UNIT_WORDS = {"CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES", "ASSUME",
                   "ASSUMPTION", "AXIOM", "THEOREM", "LEMMA", "COROLLARY",
                   "INSTANCE", "LOCAL", "RECURSIVE"}

    def _skip_proof(self):
        """Skip a structured proof body (step labels <1>1., BY/QED leaves)
        following a THEOREM, up to the next module-level unit."""
        if not (self.cur.kind == "prooflabel" or self.at_res(*self._PROOF_WORDS)):
            return
        while True:
            t = self.cur
            if t.kind in ("eof", "end4", "sep4"):
                return
            if t.kind == "prooflabel":
                self.next()
                continue
            if t.kind == "reserved":
                if t.text in self._PROOF_WORDS:
                    self.next()
                    continue
                if t.text in self._UNIT_WORDS:
                    return
                self.next()
                continue
            if self._at_definition_start():
                return
            self.next()


def parse_module_text(src: str) -> A.Module:
    return Parser(tokenize(src)).parse_module()


def parse_expr_text(src: str) -> A.Node:
    p = Parser(tokenize(src))
    e = p.parse_expr()
    if p.cur.kind != "eof":
        raise ParseError("trailing input after expression", p.cur)
    return e
