r"""PlusCal (p-syntax) → TLA+ translator for the corpus subset.

Observable contract (/root/reference/README.md:217-311 and p-manual.pdf):
per-process locals and pc become functions over ProcSet, every label becomes
an action parameterized by `self`, labels inside if-branches end the enclosing
action with a conditional pc' assignment, and the whole algorithm yields
Init / per-label actions / Next / Spec / Terminating definitions.

Subset: top-level `variables`, `process P \in S` / `process P = v` with local
`variables`, statements: `x := e`, `if/then/else/end if`, `while/do/end while`,
`await e`, `assert e`, `skip`, `goto L`, with labels anywhere a statement
starts. This covers pcal_intro.tla, atomic_add.tla and the README's buggy
money-transfer variant (README.md:222-241).

The translation is built directly as AST units appended to the host module —
no text round-trip.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .lexer import tokenize
from .parser import Parser
from . import tla_ast as A


class PcalError(Exception):
    pass


# ---- statement forms ----

@dataclass
class Assign:
    var: str
    expr: A.Node
    line: int = 0


@dataclass
class If:
    cond: A.Node
    then: List
    els: List


@dataclass
class While:
    cond: A.Node
    body: List


@dataclass
class Await:
    expr: A.Node


@dataclass
class AssertStmt:
    expr: A.Node
    line: int
    col: int


@dataclass
class Skip:
    pass


@dataclass
class Goto:
    label: str


@dataclass
class Labeled:
    label: str
    stmt: object


@dataclass
class ProcDecl:
    name: str
    ids: A.Node          # the id-set expression (or singleton value expr)
    singleton: bool      # process Name = expr
    locals: List[Tuple[str, str, A.Node]]  # (name, '='|'in', expr)
    body: List


@dataclass
class Algorithm:
    name: str
    globals: List[Tuple[str, str, A.Node]]
    procs: List[ProcDecl]


_ALG_RE = re.compile(r"\(\*\s*--algorithm\s+(\w+)(.*?)end\s+algorithm\s*;?\s*\*\)",
                     re.DOTALL)


def has_algorithm(src: str) -> bool:
    return _ALG_RE.search(src) is not None


class _P(Parser):
    """Token cursor over the algorithm body, reusing the TLA+ expression
    parser for embedded expressions."""

    def at_word(self, *words) -> bool:
        return self.cur.kind == "ident" and self.cur.text in words

    def expect_word(self, w):
        if not self.at_word(w):
            raise PcalError(f"expected '{w}' at {self.cur.line}:{self.cur.col}"
                            f", found {self.cur.text!r}")
        return self.next()

    def parse_var_decls(self):
        decls = []
        while True:
            if self.cur.kind != "ident" or self.at_word(
                    "process", "begin", "define", "macro", "procedure"):
                break
            name = self.next().text
            if self.at_op("="):
                self.next()
                decls.append((name, "=", self.parse_expr()))
            elif self.at_op("\\in"):
                self.next()
                decls.append((name, "in", self.parse_expr()))
            else:
                decls.append((name, "=", A.Ident("defaultInitValue")))
            if self.at_op(",") or self.at_op(";"):
                self.next()
                continue
        return decls

    def parse_stmts(self, stop_words) -> List:
        out = []
        while True:
            if self.cur.kind == "eof" or self.at_word(*stop_words):
                return out
            if self.at_op(";"):
                self.next()
                continue
            out.append(self.parse_stmt())

    def parse_stmt(self):
        # label?
        if self.cur.kind == "ident" and self.peek().kind == "op" \
                and self.peek().text == ":" and not self.at_word(
                    "if", "while", "await", "when", "assert", "skip", "goto"):
            label = self.next().text
            self.next()  # ':'
            return Labeled(label, self.parse_stmt())
        if self.at_word("if"):
            self.next()
            cond = self.parse_expr()
            self.expect_word("then")
            then = self.parse_stmts(("else", "elsif", "end"))
            els: List = []
            if self.at_word("else"):
                self.next()
                els = self.parse_stmts(("end",))
            elif self.at_word("elsif"):
                raise PcalError("elsif not supported")
            self.expect_word("end")
            self.expect_word("if")
            return If(cond, then, els)
        if self.at_word("while"):
            self.next()
            cond = self.parse_expr()
            self.expect_word("do")
            body = self.parse_stmts(("end",))
            self.expect_word("end")
            self.expect_word("while")
            return While(cond, body)
        if self.at_word("await", "when"):
            self.next()
            return Await(self.parse_expr())
        if self.at_word("assert"):
            t = self.cur
            self.next()
            return AssertStmt(self.parse_expr(), t.line, t.col)
        if self.at_word("skip"):
            self.next()
            return Skip()
        if self.at_word("goto"):
            self.next()
            return Goto(self.next().text)
        if self.cur.kind == "ident":
            t = self.cur
            name = self.next().text
            if self.at_op(":="):
                self.next()
                return Assign(name, self.parse_expr(), t.line)
            raise PcalError(f"unsupported statement at {t.line}:{t.col} "
                            f"({name!r})")
        raise PcalError(f"unsupported statement at "
                        f"{self.cur.line}:{self.cur.col}")


def parse_algorithm(src: str) -> Tuple[Algorithm, int]:
    """Extract and parse the PlusCal algorithm; returns (alg, line offset)."""
    m = _ALG_RE.search(src)
    if not m:
        raise PcalError("no --algorithm block found")
    name = m.group(1)
    body = m.group(2)
    line_off = src[:m.start(2)].count("\n")
    p = _P(tokenize(body))
    globals_: List = []
    procs: List[ProcDecl] = []
    while p.cur.kind != "eof":
        if p.at_word("variables", "variable"):
            p.next()
            globals_.extend(p.parse_var_decls())
            continue
        if p.at_word("define"):
            raise PcalError("define blocks not supported yet")
        if p.at_word("process"):
            p.next()
            pname = p.next().text
            if p.at_op("="):
                p.next()
                ids = p.parse_expr()
                singleton = True
            elif p.at_op("\\in"):
                p.next()
                ids = p.parse_expr()
                singleton = False
            else:
                raise PcalError("process needs = or \\in")
            locs: List = []
            if p.at_word("variables", "variable"):
                p.next()
                locs = p.parse_var_decls()
            p.expect_word("begin")
            stmts = p.parse_stmts(("end",))
            p.expect_word("end")
            p.expect_word("process")
            if p.at_op(";"):
                p.next()
            procs.append(ProcDecl(pname, ids, singleton, locs, stmts))
            continue
        if p.at_word("begin"):
            raise PcalError("single-process algorithms not supported yet")
        raise PcalError(f"unexpected token {p.cur.text!r} at "
                        f"{p.cur.line}:{p.cur.col}")
    if not procs:
        raise PcalError("algorithm has no processes")
    return Algorithm(name, globals_, procs), line_off


# ---- translation ----

def _conj(items: List[A.Node]) -> A.Node:
    out = items[0]
    for it in items[1:]:
        out = A.OpApp("/\\", (out, it))
    return out


def _disj(items: List[A.Node]) -> A.Node:
    out = items[0]
    for it in items[1:]:
        out = A.OpApp("\\/", (out, it))
    return out


def _eq(a, b):
    return A.OpApp("=", (a, b))


def _pc_is(label):
    return _eq(A.FnApp(A.Ident("pc"), (A.Ident("self"),)), A.Str(label))


def _pc_set(label):
    return _eq(A.Prime(A.Ident("pc")),
               A.Except(A.Ident("pc"),
                        ((((("idx", (A.Ident("self"),))),), A.Str(label)),)))


@dataclass
class _Path:
    # ordered action conjuncts: ('cond', expr) or ('upd', var, rhs_expr),
    # in statement order — order matters because a read after an assignment
    # sees the primed value
    items: List[tuple] = field(default_factory=list)
    next_label: Optional[str] = None

    def assigned(self):
        return {it[1] for it in self.items if it[0] == 'upd'}


class Translator:
    def __init__(self, alg: Algorithm, line_off: int, module_name: str):
        self.alg = alg
        self.line_off = line_off
        self.module_name = module_name
        self.global_names = [n for n, _, _ in alg.globals]
        self.all_vars: List[str] = list(self.global_names)
        for pr in alg.procs:
            self.all_vars.extend(n for n, _, _ in pr.locals)
        self.all_vars.append("pc")

    # -- expression rewriting: local var v  ->  v[self] --
    def _rw(self, e: A.Node, locals_: set) -> A.Node:
        R = lambda x: self._rw(x, locals_)
        if isinstance(e, A.Ident):
            if e.name in locals_:
                return A.FnApp(A.Ident(e.name), (A.Ident("self"),))
            return e
        if isinstance(e, A.Num) or isinstance(e, A.Str) or isinstance(e, A.Bool):
            return e
        if isinstance(e, A.OpApp):
            return A.OpApp(e.name, tuple(R(a) for a in e.args), e.path)
        if isinstance(e, A.FnApp):
            return A.FnApp(R(e.fn), tuple(R(a) for a in e.args))
        if isinstance(e, A.Dot):
            return A.Dot(R(e.expr), e.fld)
        if isinstance(e, A.TupleExpr):
            return A.TupleExpr(tuple(R(x) for x in e.items))
        if isinstance(e, A.SetEnum):
            return A.SetEnum(tuple(R(x) for x in e.items))
        if isinstance(e, A.If):
            return A.If(R(e.cond), R(e.then), R(e.els))
        if isinstance(e, A.SetFilter):
            return A.SetFilter(e.var, R(e.set), R(e.pred))
        if isinstance(e, A.SetMap):
            return A.SetMap(R(e.expr),
                            tuple((n, R(s)) for n, s in e.binders))
        if isinstance(e, A.Quant):
            return A.Quant(e.kind,
                           tuple((n, R(s) if s else None) for n, s in e.binders),
                           R(e.body))
        if isinstance(e, A.FnDef):
            return A.FnDef(tuple((n, R(s)) for n, s in e.binders), R(e.body))
        if isinstance(e, A.Except):
            return A.Except(R(e.fn), tuple(
                ((tuple(("idx", tuple(R(i) for i in arg)) if k == "idx"
                        else (k, arg) for k, arg in path)), R(rhs))
                for path, rhs in e.updates))
        if isinstance(e, A.Choose):
            return A.Choose(e.var, R(e.set) if e.set else None, R(e.pred))
        return e

    def translate(self) -> List[A.Node]:
        alg = self.alg
        units: List[A.Node] = []
        # ProcSet
        id_sets = []
        for pr in alg.procs:
            ids = pr.ids
            id_sets.append(A.SetEnum((ids,)) if pr.singleton else ids)
        procset: A.Node = id_sets[0]
        for s in id_sets[1:]:
            procset = A.OpApp("\\cup", (procset, s))
        units.append(A.OpDef("ProcSet", (), procset))

        # vars tuple
        units.append(A.OpDef("vars", (), A.TupleExpr(
            tuple(A.Ident(v) for v in self.all_vars))))

        # Init
        init_conjs: List[A.Node] = []
        for n, kind, e in alg.globals:
            init_conjs.append(
                _eq(A.Ident(n), e) if kind == "=" else
                A.OpApp("\\in", (A.Ident(n), e)))
        for pr in alg.procs:
            locals_ = {n for n, _, _ in pr.locals}
            idset = A.SetEnum((pr.ids,)) if pr.singleton else pr.ids
            for n, kind, e in pr.locals:
                if kind == "=":
                    init_conjs.append(_eq(
                        A.Ident(n),
                        A.FnDef(((("self",), idset),), self._rw(e, locals_))))
                else:
                    init_conjs.append(A.OpApp("\\in", (
                        A.Ident(n), A.FnSet(idset, e))))
        # pc initial: first label per process
        arms = []
        for pr in alg.procs:
            first = self._first_label(pr)
            guard = _eq(A.Ident("self"), pr.ids) if pr.singleton else \
                A.OpApp("\\in", (A.Ident("self"), pr.ids))
            arms.append((guard, A.Str(first)))
        pc_init = A.FnDef(((("self",), A.Ident("ProcSet")),),
                          A.Case(tuple(arms), None))
        init_conjs.append(_eq(A.Ident("pc"), pc_init))
        units.append(A.OpDef("Init", (), _conj(init_conjs)))

        # actions per process
        proc_next_disjs: List[A.Node] = []
        for pr in alg.procs:
            actions = self._compile_proc(pr)
            label_names = []
            for label, body in actions:
                units.append(A.OpDef(label, ("self",), body))
                label_names.append(label)
            pbody = _disj([A.OpApp(l, (A.Ident("self"),))
                           for l in label_names])
            units.append(A.OpDef(pr.name, ("self",), pbody))
            if pr.singleton:
                proc_next_disjs.append(A.OpApp(pr.name, (pr.ids,)))
            else:
                proc_next_disjs.append(A.Quant(
                    "E", ((("self",), pr.ids),),
                    A.OpApp(pr.name, (A.Ident("self"),))))

        # Terminating
        term = A.OpApp("/\\", (
            A.Quant("A", ((("self",), A.Ident("ProcSet")),),
                    _pc_is_done()),
            A.Unchanged(A.Ident("vars"))))
        units.append(A.OpDef("Terminating", (), term))
        proc_next_disjs.append(A.Ident("Terminating"))
        units.append(A.OpDef("Next", (), _disj(proc_next_disjs)))
        units.append(A.OpDef("Spec", (), A.OpApp("/\\", (
            A.Ident("Init"),
            A.OpApp("[]", (A.BoxAction(A.Ident("Next"), A.Ident("vars")),))))))
        units.append(A.OpDef("Termination", (), A.OpApp("<>", (
            A.Quant("A", ((("self",), A.Ident("ProcSet")),),
                    _pc_is_done()),))))
        return units

    def _first_label(self, pr: ProcDecl) -> str:
        s = pr.body[0]
        if isinstance(s, Labeled):
            return s.label
        raise PcalError(f"process {pr.name} body must start with a label")

    def _compile_proc(self, pr: ProcDecl) -> List[Tuple[str, A.Node]]:
        """Build (label, action body) list for one process."""
        locals_ = {n for n, _, _ in pr.locals}
        self._cur_locals = locals_
        actions: Dict[str, A.Node] = {}
        # collect label positions: walk statements building per-label stmt
        # suffixes (statements from the label to the end of the process,
        # through enclosing control structure)
        pending: List[Tuple[str, List]] = []
        first = self._first_label(pr)
        pending.append((first, pr.body))
        done_set = set()
        while pending:
            label, stmts = pending.pop()
            if label in done_set:
                continue
            done_set.add(label)
            # stmts[0] is Labeled(label, ...)
            assert isinstance(stmts[0], Labeled) and stmts[0].label == label
            flat = [stmts[0].stmt] + list(stmts[1:])
            paths = self._compile_seq(flat, "Done", pending, cur_label=label)
            body = self._paths_to_body(label, paths)
            actions[label] = body
        order = self._label_order(pr)
        return [(l, actions[l]) for l in order if l in actions]

    def _label_order(self, pr: ProcDecl) -> List[str]:
        out = []

        def scan(stmts):
            for s in stmts:
                if isinstance(s, Labeled):
                    out.append(s.label)
                    scan([s.stmt])
                elif isinstance(s, If):
                    scan(s.then)
                    scan(s.els)
                elif isinstance(s, While):
                    scan(s.body)
        scan(pr.body)
        return out

    def _prime_assigned(self, e: A.Node, assigned: frozenset) -> A.Node:
        """Rewrite reads of already-assigned variables to primed reads —
        PlusCal statements execute sequentially within a step, so
        `x := 1; y := x` reads the NEW x (p-manual semantics; pcal2tla
        performs the same rewriting)."""
        if not assigned:
            return e
        R = lambda x: self._prime_assigned(x, assigned)
        if isinstance(e, A.Ident):
            return A.Prime(e) if e.name in assigned else e
        if isinstance(e, (A.Num, A.Str, A.Bool)):
            return e
        if isinstance(e, A.OpApp):
            return A.OpApp(e.name, tuple(R(a) for a in e.args), e.path)
        if isinstance(e, A.FnApp):
            return A.FnApp(R(e.fn), tuple(R(a) for a in e.args))
        if isinstance(e, A.Dot):
            return A.Dot(R(e.expr), e.fld)
        if isinstance(e, A.TupleExpr):
            return A.TupleExpr(tuple(R(x) for x in e.items))
        if isinstance(e, A.SetEnum):
            return A.SetEnum(tuple(R(x) for x in e.items))
        if isinstance(e, A.If):
            return A.If(R(e.cond), R(e.then), R(e.els))
        if isinstance(e, A.SetFilter):
            return A.SetFilter(e.var, R(e.set), R(e.pred))
        if isinstance(e, A.SetMap):
            return A.SetMap(R(e.expr), tuple((n, R(s)) for n, s in e.binders))
        if isinstance(e, A.Quant):
            return A.Quant(e.kind,
                           tuple((n, R(s) if s else None)
                                 for n, s in e.binders), R(e.body))
        if isinstance(e, A.FnDef):
            return A.FnDef(tuple((n, R(s)) for n, s in e.binders), R(e.body))
        if isinstance(e, A.Except):
            return A.Except(R(e.fn), tuple(
                ((tuple(("idx", tuple(R(i) for i in arg)) if kk == "idx"
                        else (kk, arg) for kk, arg in path)), R(rhs))
                for path, rhs in e.updates))
        if isinstance(e, A.Choose):
            return A.Choose(e.var, R(e.set) if e.set else None, R(e.pred))
        if isinstance(e, A.Prime):
            return e
        return e

    def _compile_seq(self, stmts: List, k: str, pending,
                     cur_label: str = "",
                     assigned: frozenset = frozenset()) -> List[_Path]:
        """Compile a statement list into paths; k is the fall-through label;
        assigned tracks variables already assigned earlier in this step."""
        if not stmts:
            p = _Path()
            p.next_label = k
            return [p]
        s, rest = stmts[0], list(stmts[1:])
        if isinstance(s, Labeled):
            # current action ends here, jumping to s.label
            pending.append((s.label, stmts))
            p = _Path()
            p.next_label = s.label
            return [p]
        if isinstance(s, Assign):
            rw = self._prime_assigned(self._rw(s.expr, self._cur_locals),
                                      assigned)
            if s.var in self._cur_locals:
                base = A.Ident(s.var)
                if s.var in assigned:
                    raise PcalError(
                        f"two assignments to {s.var} in one step")
                rhs = A.Except(base, (((("idx", (A.Ident("self"),)),), rw),))
            else:
                if s.var in assigned:
                    raise PcalError(
                        f"two assignments to {s.var} in one step")
                rhs = rw
            tails = self._compile_seq(rest, k, pending, cur_label,
                                      assigned | {s.var})
            out = []
            for t in tails:
                np = _Path([("upd", s.var, rhs)] + list(t.items),
                           t.next_label)
                out.append(np)
            return out
        if isinstance(s, If):
            cond = self._prime_assigned(self._rw(s.cond, self._cur_locals),
                                        assigned)
            tpaths = self._compile_seq(list(s.then) + rest, k, pending,
                                       cur_label, assigned)
            epaths = self._compile_seq(list(s.els) + rest, k, pending,
                                       cur_label, assigned)
            for p in tpaths:
                p.items.insert(0, ("cond", cond))
            neg = A.OpApp("~", (cond,))
            for p in epaths:
                p.items.insert(0, ("cond", neg))
            return tpaths + epaths
        if isinstance(s, While):
            # L: while c do body end while; rest
            # ~~> IF c THEN body; goto L ELSE rest  (pcal requires a label
            # on every while, so cur_label is the loop head)
            if not cur_label:
                raise PcalError("while loop without an enclosing label")
            cond = self._prime_assigned(self._rw(s.cond, self._cur_locals),
                                        assigned)
            tpaths = self._compile_seq(list(s.body) + [Goto(cur_label)],
                                       k, pending, cur_label, assigned)
            epaths = self._compile_seq(rest, k, pending, cur_label, assigned)
            for p in tpaths:
                p.items.insert(0, ("cond", cond))
            neg = A.OpApp("~", (cond,))
            for p in epaths:
                p.items.insert(0, ("cond", neg))
            return tpaths + epaths
        if isinstance(s, Await):
            tails = self._compile_seq(rest, k, pending, cur_label, assigned)
            g = self._prime_assigned(self._rw(s.expr, self._cur_locals),
                                     assigned)
            for p in tails:
                p.items.insert(0, ("cond", g))
            return tails
        if isinstance(s, AssertStmt):
            g = self._prime_assigned(self._rw(s.expr, self._cur_locals),
                                     assigned)
            msg = (f"Failure of assertion at line {s.line + self.line_off}, "
                   f"column {s.col}.")
            call = A.OpApp("Assert", (g, A.Str(msg)))
            tails = self._compile_seq(rest, k, pending, cur_label, assigned)
            for p in tails:
                p.items.insert(0, ("cond", call))
            return tails
        if isinstance(s, Skip):
            return self._compile_seq(rest, k, pending, cur_label, assigned)
        if isinstance(s, Goto):
            p = _Path()
            p.next_label = s.label
            return [p]
        raise PcalError(f"unsupported statement {s!r}")

    def _paths_to_body(self, label: str, paths: List[_Path]) -> A.Node:
        assigned_any = set()
        for p in paths:
            assigned_any.update(p.assigned())
        arms = []
        for p in paths:
            conjs: List[A.Node] = []
            for it in p.items:
                if it[0] == "cond":
                    conjs.append(it[1])
                else:
                    _, var, rhs = it
                    conjs.append(_eq(A.Prime(A.Ident(var)), rhs))
            # vars assigned in other paths but not this one stay equal
            for var in sorted(assigned_any - p.assigned()):
                conjs.append(_eq(A.Prime(A.Ident(var)), A.Ident(var)))
            conjs.append(_pc_set(p.next_label))
            arms.append(_conj(conjs))
        body = _disj(arms)
        unchanged = [v for v in self.all_vars
                     if v != "pc" and v not in assigned_any]
        guard = _pc_is(label)
        parts: List[A.Node] = [guard, body]
        if unchanged:
            parts.append(A.Unchanged(A.TupleExpr(
                tuple(A.Ident(v) for v in unchanged))))
        return _conj(parts)


def _pc_is_done():
    return _eq(A.FnApp(A.Ident("pc"), (A.Ident("self"),)), A.Str("Done"))


def translate_module(src: str, module_ast: A.Module) -> A.Module:
    """Return module_ast with the PlusCal translation appended (the in-memory
    equivalent of pcal2tla's in-place insertion, Makefile:4)."""
    alg, line_off = parse_algorithm(src)
    tr = Translator(alg, line_off, module_ast.name)
    units = tr.translate()
    # declare the translation's variables
    var_names = tuple(tr.all_vars)
    new_units = (A.Variables(var_names),) + tuple(units) + module_ast.units
    return A.Module(module_ast.name, module_ast.extends, new_units)
