r"""Capture-aware AST substitution.

TLA+ operator application is call-by-name: Lose(msgQ) with
Lose(q) == ... q' = ... means msgQ' gets assigned
(/root/reference/examples/SpecifyingSystems/TLC/AlternatingBit.tla:55-64),
and operator-constant instantiations like Send(p, d, memInt, memInt')
(CachingMemory/MemoryInterface.tla) pass a primed variable as an argument.
The enumeration walker therefore expands such applications by substituting
argument ASTs for parameters instead of evaluating eagerly.

Substitution skips occurrences shadowed by binders. (Alpha-capture of an
argument's free names by a binder inside the body is not renamed — no
corpus spec does this.)
"""

from __future__ import annotations

from typing import Dict

from . import tla_ast as A


def _names_of(pat) -> set:
    if isinstance(pat, str):
        return {pat}
    return set(pat)


def subst(e: A.Node, m: Dict[str, A.Node]) -> A.Node:
    """Substitute m's ASTs for free identifier occurrences in e."""
    if not m:
        return e
    t = type(e)
    if t is A.Ident:
        return m.get(e.name, e)
    if t in (A.Num, A.Str, A.Bool, A.At):
        return e
    if t is A.OpApp:
        # an applied operator name is not substitutable (op params are
        # first-order here); only its arguments are
        return A.OpApp(e.name, tuple(subst(a, m) for a in e.args),
                       tuple((n, tuple(subst(a, m) for a in args))
                             for n, args in e.path))
    if t is A.FnApp:
        return A.FnApp(subst(e.fn, m), tuple(subst(a, m) for a in e.args))
    if t is A.Dot:
        return A.Dot(subst(e.expr, m), e.fld)
    if t is A.TupleExpr:
        return A.TupleExpr(tuple(subst(x, m) for x in e.items))
    if t is A.SetEnum:
        return A.SetEnum(tuple(subst(x, m) for x in e.items))
    if t is A.SetFilter:
        inner = {k: v for k, v in m.items() if k not in _names_of(e.var)}
        return A.SetFilter(e.var, subst(e.set, m), subst(e.pred, inner))
    if t is A.SetMap:
        bound = set()
        new_binders = []
        for names, s in e.binders:
            new_binders.append((names, subst(s, {k: v for k, v in m.items()
                                                 if k not in bound})))
            for pat in names:
                bound |= _names_of(pat)
        inner = {k: v for k, v in m.items() if k not in bound}
        return A.SetMap(subst(e.expr, inner), tuple(new_binders))
    if t is A.FnDef:
        bound = set()
        new_binders = []
        for names, s in e.binders:
            new_binders.append((names, subst(s, {k: v for k, v in m.items()
                                                 if k not in bound})))
            for pat in names:
                bound |= _names_of(pat)
        inner = {k: v for k, v in m.items() if k not in bound}
        return A.FnDef(tuple(new_binders), subst(e.body, inner))
    if t is A.FnSet:
        return A.FnSet(subst(e.dom, m), subst(e.rng, m))
    if t is A.RecordExpr:
        return A.RecordExpr(tuple((k, subst(v, m)) for k, v in e.fields))
    if t is A.RecordSet:
        return A.RecordSet(tuple((k, subst(v, m)) for k, v in e.fields))
    if t is A.Except:
        return A.Except(subst(e.fn, m), tuple(
            (tuple(("idx", tuple(subst(i, m) for i in arg)) if k == "idx"
                   else (k, arg) for k, arg in path),
             subst(rhs, m))
            for path, rhs in e.updates))
    if t is A.If:
        return A.If(subst(e.cond, m), subst(e.then, m), subst(e.els, m))
    if t is A.Case:
        return A.Case(tuple((subst(g, m), subst(b, m)) for g, b in e.arms),
                      subst(e.other, m) if e.other is not None else None)
    if t is A.Let:
        bound = set()
        new_defs = []
        for d in e.defs:
            if isinstance(d, A.OpDef):
                inner = {k: v for k, v in m.items()
                         if k not in bound and k not in d.params}
                new_defs.append(A.OpDef(d.name, d.params,
                                        subst(d.body, inner), d.local))
                bound.add(d.name)
            elif isinstance(d, A.FnConstrDef):
                binder_names = set()
                for names, _ in d.binders:
                    for pat in names:
                        binder_names |= _names_of(pat)
                inner = {k: v for k, v in m.items()
                         if k not in bound and k not in binder_names
                         and k != d.name}
                new_defs.append(A.FnConstrDef(
                    d.name,
                    tuple((names, subst(s, {k: v for k, v in m.items()
                                            if k not in bound}))
                          for names, s in d.binders),
                    subst(d.body, inner), d.local))
                bound.add(d.name)
            else:
                new_defs.append(d)
        inner = {k: v for k, v in m.items() if k not in bound}
        return A.Let(tuple(new_defs), subst(e.body, inner))
    if t is A.Quant:
        bound = set()
        new_binders = []
        for names, s in e.binders:
            new_binders.append((names,
                                subst(s, {k: v for k, v in m.items()
                                          if k not in bound})
                                if s is not None else None))
            for pat in names:
                bound |= _names_of(pat)
        inner = {k: v for k, v in m.items() if k not in bound}
        return A.Quant(e.kind, tuple(new_binders), subst(e.body, inner))
    if t is A.Choose:
        inner = {k: v for k, v in m.items() if k not in _names_of(e.var)}
        return A.Choose(e.var,
                        subst(e.set, m) if e.set is not None else None,
                        subst(e.pred, inner))
    if t is A.Prime:
        return A.Prime(subst(e.expr, m))
    if t is A.BoxAction:
        return A.BoxAction(subst(e.action, m), subst(e.sub, m))
    if t is A.AngleAction:
        return A.AngleAction(subst(e.action, m), subst(e.sub, m))
    if t is A.Fair:
        return A.Fair(e.kind, subst(e.sub, m), subst(e.action, m))
    if t is A.Unchanged:
        return A.Unchanged(subst(e.expr, m))
    if t is A.Enabled:
        return A.Enabled(subst(e.expr, m))
    if t is A.TemporalQuant:
        inner = {k: v for k, v in m.items() if k not in e.vars}
        return A.TemporalQuant(e.kind, e.vars, subst(e.body, inner))
    if t is A.Lambda:
        inner = {k: v for k, v in m.items() if k not in e.params}
        return A.Lambda(e.params, subst(e.body, inner))
    return e


def occurs_free(e: A.Node, names) -> bool:
    """Does any of `names` occur FREE in e — as an identifier reference
    or an applied-operator name — under the same shadowing rules subst
    uses? ground.split_arms asks this before distributing a rider
    conjunct under a disjunct's binder bindings (raft's Next shape,
    /root/reference/examples/raft.tla:482-493): a rider whose free names
    collide with the new bindings would be captured, so the conjunction
    then stays one arm."""
    ns = set(names)
    if not ns:
        return False

    def tup(tv, sh) -> bool:
        for x in tv:
            if isinstance(x, A.Node):
                if go(x, sh):
                    return True
            elif isinstance(x, tuple):
                if tup(x, sh):
                    return True
        return False

    def go(x, sh) -> bool:
        t = type(x)
        if t is A.Ident:
            return x.name in ns and x.name not in sh
        if t in (A.Num, A.Str, A.Bool, A.At):
            return False
        if t is A.OpApp:
            if x.name in ns and x.name not in sh:
                return True
            if any(go(a, sh) for a in x.args):
                return True
            return any(go(a, sh)
                       for _n, args in x.path for a in args)
        if t is A.SetFilter:
            return go(x.set, sh) or go(x.pred, sh | _names_of(x.var))
        if t in (A.SetMap, A.FnDef):
            bound = set()
            for bn, s in x.binders:
                if s is not None and go(s, sh | bound):
                    return True
                for pat in bn:
                    bound |= _names_of(pat)
            body = x.expr if t is A.SetMap else x.body
            return go(body, sh | bound)
        if t is A.Quant:
            bound = set()
            for bn, s in x.binders:
                if s is not None and go(s, sh | bound):
                    return True
                for pat in bn:
                    bound |= _names_of(pat)
            return go(x.body, sh | bound)
        if t is A.Choose:
            if x.set is not None and go(x.set, sh):
                return True
            return go(x.pred, sh | _names_of(x.var))
        if t is A.Let:
            bound = set()
            for d in x.defs:
                if isinstance(d, A.OpDef):
                    if go(d.body, sh | bound | set(d.params)):
                        return True
                    bound.add(d.name)
                elif isinstance(d, A.FnConstrDef):
                    bn = set()
                    for nms, s in d.binders:
                        if s is not None and go(s, sh | bound):
                            return True
                        for pat in nms:
                            bn |= _names_of(pat)
                    if go(d.body, sh | bound | bn | {d.name}):
                        return True
                    bound.add(d.name)
            return go(x.body, sh | bound)
        if t is A.Lambda:
            return go(x.body, sh | set(x.params))
        if t is A.TemporalQuant:
            return go(x.body, sh | set(x.vars))
        for f in getattr(x, "__dataclass_fields__", {}):
            v = getattr(x, f)
            if isinstance(v, A.Node):
                if go(v, sh):
                    return True
            elif isinstance(v, tuple):
                if tup(v, sh):
                    return True
        return False

    return go(e, frozenset())


_CONTAINS_PRIME_CACHE: dict = {}


def contains_prime(e: A.Node) -> bool:
    r = _CONTAINS_PRIME_CACHE.get(id(e))
    if r is not None:
        return r
    if isinstance(e, A.Prime):
        r = True
    else:
        r = False
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, A.Node) and contains_prime(v):
                r = True
                break
            if isinstance(v, tuple) and _tuple_contains_prime(v):
                r = True
                break
    # key on id(): AST nodes are immutable and owned by the loaded module,
    # which outlives any check run; map also keeps e alive via the value
    _CONTAINS_PRIME_CACHE[id(e)] = r
    _CONTAINS_PRIME_KEEPALIVE.append(e)
    return r


_CONTAINS_PRIME_KEEPALIVE: list = []


def _tuple_contains_prime(t) -> bool:
    for x in t:
        if isinstance(x, A.Node) and contains_prime(x):
            return True
        if isinstance(x, tuple) and _tuple_contains_prime(x):
            return True
    return False


_PRIMES_PARAMS_CACHE: dict = {}
_PRIMES_PARAMS_KEEPALIVE: list = []


def primes_params(e: A.Node, params) -> bool:
    """Does e contain p' for any p in params? (Lose(q) assigns q',
    AlternatingBit.tla:55-64 — such bodies need call-by-name expansion.)"""
    ps = set(params)
    if not ps:
        return False
    ck = (id(e), tuple(sorted(ps)))
    hit = _PRIMES_PARAMS_CACHE.get(ck)
    if hit is not None:
        return hit

    def walk(x) -> bool:
        if isinstance(x, A.Prime) and isinstance(x.expr, A.Ident) \
                and x.expr.name in ps:
            return True
        for f in getattr(x, "__dataclass_fields__", {}):
            v = getattr(x, f)
            if isinstance(v, A.Node) and walk(v):
                return True
            if isinstance(v, tuple) and _tuple_walk(v):
                return True
        return False

    def _tuple_walk(t) -> bool:
        for x in t:
            if isinstance(x, A.Node) and walk(x):
                return True
            if isinstance(x, tuple) and _tuple_walk(x):
                return True
        return False

    r = walk(e)
    _PRIMES_PARAMS_CACHE[ck] = r
    _PRIMES_PARAMS_KEEPALIVE.append(e)
    return r


def contains_box(e: A.Node) -> bool:
    if isinstance(e, A.BoxAction):
        return True
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, A.Node) and contains_box(v):
            return True
        if isinstance(v, tuple) and _tuple_contains_box(v):
            return True
    return False


def _tuple_contains_box(t) -> bool:
    for x in t:
        if isinstance(x, A.Node) and contains_box(x):
            return True
        if isinstance(x, tuple) and _tuple_contains_box(x):
            return True
    return False
