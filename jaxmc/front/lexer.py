r"""TLA+ lexer.

Tokenizes the TLA+ subset used by the reference corpus (grammar reference:
/root/reference/examples/SpecifyingSystems/Syntax/TLAPlusGrammar.tla — lexemes
at :17-37, reserved words at :7-15). Emits (kind, text, line, col) tokens; line
and col are 1-based. Column information is load-bearing: the parser uses it for
TLA+'s indentation-sensitive /\ and \/ junction lists.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List


class LexError(Exception):
    def __init__(self, msg: str, line: int, col: int):
        super().__init__(f"{msg} at {line}:{col}")
        self.line = line
        self.col = col


@dataclass(frozen=True)
class Token:
    kind: str  # 'ident' | 'number' | 'string' | 'op' | 'reserved' | 'sep4' | 'end4' | 'eof'
    text: str
    line: int
    col: int

    def __repr__(self):
        return f"Token({self.kind},{self.text!r},{self.line}:{self.col})"


RESERVED = {
    "MODULE", "EXTENDS", "CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES",
    "ASSUME", "ASSUMPTION", "AXIOM", "THEOREM", "LEMMA", "COROLLARY",
    "INSTANCE", "LOCAL", "LET", "IN", "IF", "THEN", "ELSE", "CASE", "OTHER",
    "CHOOSE", "ENABLED", "UNCHANGED", "SUBSET", "UNION", "DOMAIN", "EXCEPT",
    "WITH", "RECURSIVE", "LAMBDA", "TRUE", "FALSE", "BOOLEAN", "STRING",
    "SF_", "WF_", "PROOF", "BY", "OBVIOUS", "OMITTED", "QED",
}

# Multi-char operator lexemes, longest-first so greedy matching works.
_SYMBOLS = [
    "<=>", "|->", "-+->", "...", "::=",
    "==", "=>", "=<", "<=", ">=", "/=", "#", "..", "<<", ">>_", ">>",
    "/\\", "\\/", "@@", ":>", ":=", "||", "->", "<-", "~>", "[]", "<>",
    "]_", "(+)", "(-)", "(.)", "(/)", "(\\X)", "^*", "^+", "^#", "-.",
    "^^", "##", "%%", "&&", "$$",
    "??", "!!", "++", "--", "**", "//", "^", "%", "&", "|", "$",
    "=", "<", ">", "+", "-", "*", "/", "(", ")", "[", "]", "{", "}",
    ",", ":", ";", ".", "!", "@", "'", "~", "_",
]

def _is_ident_char(c: str) -> bool:
    return c.isalnum() or c == "_"


def tokenize(src: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(src)
    line, col = 1, 1

    def adv(k: int = 1):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and src[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = src[i]
        # whitespace
        if c in " \t\r\n\f":
            adv()
            continue
        # line comment
        if src.startswith("\\*", i):
            while i < n and src[i] != "\n":
                adv()
            continue
        # block comment, nested
        if src.startswith("(*", i):
            l0, c0 = line, col
            depth = 1
            adv(2)
            while i < n and depth:
                if src.startswith("(*", i):
                    depth += 1
                    adv(2)
                elif src.startswith("*)", i):
                    depth -= 1
                    adv(2)
                else:
                    adv()
            if depth:
                raise LexError("unterminated block comment", l0, c0)
            continue
        # ---- separators and ==== module end (4 or more)
        if c == "-" and src.startswith("----", i):
            l0, c0 = line, col
            j = i
            while j < n and src[j] == "-":
                j += 1
            adv(j - i)
            toks.append(Token("sep4", "----", l0, c0))
            continue
        if c == "=" and src.startswith("====", i):
            l0, c0 = line, col
            j = i
            while j < n and src[j] == "=":
                j += 1
            adv(j - i)
            toks.append(Token("end4", "====", l0, c0))
            continue
        # string literal
        if c == '"':
            l0, c0 = line, col
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise LexError("unterminated string", l0, c0)
            adv(j + 1 - i)
            toks.append(Token("string", "".join(buf), l0, c0))
            continue
        # number (TLA+ naturals only; '1..2' must lex as 1, '..', 2)
        if c.isdigit():
            l0, c0 = line, col
            j = i
            while j < n and src[j].isdigit():
                j += 1
            text = src[i:j]
            adv(j - i)
            toks.append(Token("number", text, l0, c0))
            continue
        # identifier / reserved word
        if c.isalpha() or c == "_":
            l0, c0 = line, col
            j = i
            while j < n and _is_ident_char(src[j]):
                j += 1
            word = src[i:j]
            # WF_/SF_ prefixes split: WF_vars -> 'WF_' + ident 'vars'
            if word.startswith(("WF_", "SF_")) and len(word) > 3:
                adv(3)
                toks.append(Token("reserved", word[:3], l0, c0))
                continue
            adv(j - i)
            if word == "_":
                toks.append(Token("op", "_", l0, c0))
            elif word in RESERVED:
                toks.append(Token("reserved", word, l0, c0))
            else:
                toks.append(Token("ident", word, l0, c0))
            continue
        # backslash operators  (\in, \cup, \o, \X, \A, \E, ...)
        if c == "\\":
            l0, c0 = line, col
            if i + 1 < n and src[i + 1] == "/":
                adv(2)
                toks.append(Token("op", "\\/", l0, c0))
                continue
            j = i + 1
            while j < n and src[j].isalpha():
                j += 1
            if j == i + 1:
                # lone backslash = set difference
                adv(1)
                toks.append(Token("op", "\\", l0, c0))
                continue
            word = src[i:j]
            adv(j - i)
            toks.append(Token("op", word, l0, c0))
            continue
        # structured-proof step labels: <1>1. / <2>3 / <1>a  (TLAPS syntax,
        # appears in the Paxos proof sketches) — parser skips proof bodies
        if c == "<" and i + 1 < n and src[i + 1].isdigit():
            j = i + 1
            while j < n and src[j].isdigit():
                j += 1
            if j < n and src[j] == ">":
                l0, c0 = line, col
                j += 1
                while j < n and _is_ident_char(src[j]):
                    j += 1
                if j < n and src[j] == ".":
                    j += 1
                text = src[i:j]
                adv(j - i)
                toks.append(Token("prooflabel", text, l0, c0))
                continue
        # symbols (greedy longest match)
        for sym in _SYMBOLS:
            if src.startswith(sym, i):
                # ']_' and '>>_' only when followed by a subscript start:
                # name, number, '<<tuple>>', or parenthesized expression
                if sym in ("]_", ">>_"):
                    nxt = src[i + len(sym):i + len(sym) + 1]
                    if not (nxt.isalpha() or nxt.isdigit()
                            or nxt in ("<", "_", "(")):
                        continue
                l0, c0 = line, col
                adv(len(sym))
                toks.append(Token("op", sym, l0, c0))
                break
        else:
            raise LexError(f"unexpected character {c!r}", line, col)

    toks.append(Token("eof", "", line, col))
    return toks
