r"""TLC .cfg model-configuration parser.

Grammar: the corpus self-specifies the cfg language at
/root/reference/examples/SpecifyingSystems/TLC/ConfigFileGrammar.tla:8-33.
Statements observed in-corpus (SURVEY.md §5): SPECIFICATION, INIT, NEXT,
INVARIANT[S], PROPERTY/PROPERTIES, CONSTRAINT[S], ACTION-CONSTRAINT[S],
SYMMETRY, VIEW, CONSTANT[S] with either
    Ident = <value>          (model value / literal instantiation)
    Ident <- Defn            (substitute a definition)
    Ident <- [Mod] Defn      (instance-scoped substitution, MCPaxos.cfg:9)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class CfgError(Exception):
    pass


@dataclass
class ModelConfig:
    specification: Optional[str] = None
    init: Optional[str] = None
    next: Optional[str] = None
    invariants: List[str] = field(default_factory=list)
    properties: List[str] = field(default_factory=list)
    constraints: List[str] = field(default_factory=list)
    action_constraints: List[str] = field(default_factory=list)
    symmetry: Optional[str] = None
    view: Optional[str] = None
    # name -> parsed constant value (ints, strings, model values, sets of those)
    constants: Dict[str, object] = field(default_factory=dict)
    # name -> substituted definition name;  scoped[(module, name)] for <-[Mod]
    overrides: Dict[str, str] = field(default_factory=dict)
    scoped_overrides: Dict[Tuple[str, str], str] = field(default_factory=dict)
    check_deadlock: bool = True


@dataclass(frozen=True)
class CfgModelValue:
    """A fresh model value introduced by `Ident = Ident` in a cfg."""
    name: str

    def __repr__(self):
        return self.name


_KEYWORDS = {
    "SPECIFICATION", "INIT", "NEXT", "INVARIANT", "INVARIANTS", "PROPERTY",
    "PROPERTIES", "CONSTRAINT", "CONSTRAINTS", "ACTION-CONSTRAINT",
    "ACTION-CONSTRAINTS", "SYMMETRY", "VIEW", "CONSTANT", "CONSTANTS",
    "CHECK_DEADLOCK",
}

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<comment>\\\*[^\n]*|\(\*.*?\*\))
      | (?P<str>"[^"]*")
      | (?P<num>-?\d+)
      | (?P<arrow><-)
      | (?P<punct>[={},\[\]])
      | (?P<word>[A-Za-z0-9_!.\-]+)
    )""",
    re.VERBOSE | re.DOTALL,
)


def _tokenize(text: str) -> List[str]:
    toks = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                break
            raise CfgError(f"bad cfg syntax near {text[pos:pos+30]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        toks.append(m.group(m.lastgroup))
    return toks


def _parse_value(toks: List[str], i: int):
    """Parse a constant value: number, string, model value, or {set, of, them}."""
    if i >= len(toks):
        raise CfgError("constant binding missing its value")
    t = toks[i]
    if t == "{":
        items = []
        i += 1
        while True:
            if i >= len(toks):
                raise CfgError("unterminated set value in cfg")
            if toks[i] == "}":
                break
            v, i = _parse_value(toks, i)
            items.append(v)
            if i < len(toks) and toks[i] == ",":
                i += 1
        return frozenset(items), i + 1
    if t.startswith('"'):
        return t[1:-1], i + 1
    if re.fullmatch(r"-?\d+", t):
        return int(t), i + 1
    if t == "TRUE":
        return True, i + 1
    if t == "FALSE":
        return False, i + 1
    return CfgModelValue(t), i + 1


def parse_cfg(text: str) -> ModelConfig:
    toks = _tokenize(text)
    cfg = ModelConfig()
    i = 0
    n = len(toks)

    def names_until_keyword(i):
        names = []
        while i < n and toks[i] not in _KEYWORDS:
            # stop if this looks like the start of a CONSTANT binding
            if i + 1 < n and toks[i + 1] in ("=", "<-"):
                break
            names.append(toks[i])
            i += 1
        return names, i

    def arg(j):
        if j >= n:
            raise CfgError(f"statement {toks[-1]!r} missing its argument")
        return toks[j]

    while i < n:
        kw = toks[i]
        if kw == "SPECIFICATION":
            cfg.specification = arg(i + 1)
            i += 2
        elif kw == "INIT":
            cfg.init = arg(i + 1)
            i += 2
        elif kw == "NEXT":
            cfg.next = arg(i + 1)
            i += 2
        elif kw in ("INVARIANT", "INVARIANTS"):
            names, i = names_until_keyword(i + 1)
            cfg.invariants.extend(names)
        elif kw in ("PROPERTY", "PROPERTIES"):
            names, i = names_until_keyword(i + 1)
            cfg.properties.extend(names)
        elif kw in ("CONSTRAINT", "CONSTRAINTS"):
            names, i = names_until_keyword(i + 1)
            cfg.constraints.extend(names)
        elif kw in ("ACTION-CONSTRAINT", "ACTION-CONSTRAINTS"):
            names, i = names_until_keyword(i + 1)
            cfg.action_constraints.extend(names)
        elif kw == "SYMMETRY":
            cfg.symmetry = arg(i + 1)
            i += 2
        elif kw == "VIEW":
            cfg.view = arg(i + 1)
            i += 2
        elif kw == "CHECK_DEADLOCK":
            cfg.check_deadlock = arg(i + 1) == "TRUE"
            i += 2
        elif kw in ("CONSTANT", "CONSTANTS"):
            i += 1
            while i < n and toks[i] not in _KEYWORDS:
                name = toks[i]
                if i + 1 >= n or toks[i + 1] not in ("=", "<-"):
                    raise CfgError(f"expected = or <- after constant {name!r}")
                if toks[i + 1] == "=":
                    val, j = _parse_value(toks, i + 2)
                    # `Ident = Ident` introduces a fresh model value; keep the
                    # self-named case as a model value too (NoVal = NoVal)
                    cfg.constants[name] = val
                    i = j
                else:
                    i += 2
                    if arg(i) == "[":
                        mod = arg(i + 1)
                        if arg(i + 2) != "]":
                            raise CfgError("bad scoped substitution")
                        cfg.scoped_overrides[(mod, name)] = arg(i + 3)
                        i += 4
                    else:
                        cfg.overrides[name] = arg(i)
                        i += 1
        else:
            raise CfgError(f"unknown cfg statement {kw!r}")
    return cfg
