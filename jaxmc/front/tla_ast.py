"""AST for the TLA+ subset used by the reference corpus.

Node inventory follows the grammar spec shipped inside the corpus
(/root/reference/examples/SpecifyingSystems/Syntax/TLAPlusGrammar.tla, module
grammar from :70). Expressions are plain dataclasses; the evaluator and the
kernel compiler both walk these.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple


class Node:
    __slots__ = ()


# ---------- expressions ----------

@dataclass(frozen=True)
class Num(Node):
    val: int


@dataclass(frozen=True)
class Str(Node):
    val: str


@dataclass(frozen=True)
class Bool(Node):
    val: bool


@dataclass(frozen=True)
class Ident(Node):
    name: str


@dataclass(frozen=True)
class OpApp(Node):
    """Operator application: user-defined, builtin prefix/infix/postfix (by
    lexeme, e.g. '+', '\\cup'), or instance path application A!B!Op(args).

    path holds instance qualifiers with their own arguments, e.g.
    Inner(mem, ctl, buf)!ISpec  ->  path=(('Inner', (mem, ctl, buf)),),
    name='ISpec'."""
    name: str
    args: Tuple[Node, ...] = ()
    path: Tuple[Tuple[str, Tuple[Node, ...]], ...] = ()


@dataclass(frozen=True)
class FnApp(Node):
    fn: Node
    args: Tuple[Node, ...]


@dataclass(frozen=True)
class Dot(Node):
    expr: Node
    fld: str


@dataclass(frozen=True)
class TupleExpr(Node):
    items: Tuple[Node, ...]


@dataclass(frozen=True)
class SetEnum(Node):
    items: Tuple[Node, ...]


@dataclass(frozen=True)
class SetFilter(Node):
    # {x \in S : P}; var is a name or a tuple-destructuring pattern
    # like <<from, to>> (textbookSnapshotIsolation.tla:411)
    var: Any  # str | Tuple[str, ...]
    set: Node
    pred: Node


@dataclass(frozen=True)
class SetMap(Node):
    # {e : x \in S, y \in T}
    expr: Node
    binders: Tuple[Tuple[Tuple[str, ...], Node], ...]  # ((names), set)


@dataclass(frozen=True)
class FnDef(Node):
    # [x \in S, y \in T |-> e]
    binders: Tuple[Tuple[Tuple[str, ...], Node], ...]
    body: Node


@dataclass(frozen=True)
class FnSet(Node):
    # [S -> T]
    dom: Node
    rng: Node


@dataclass(frozen=True)
class RecordExpr(Node):
    fields: Tuple[Tuple[str, Node], ...]


@dataclass(frozen=True)
class RecordSet(Node):
    fields: Tuple[Tuple[str, Node], ...]


@dataclass(frozen=True)
class Except(Node):
    """[f EXCEPT ![i][j].fld = e, ...].  Each update: (path, rhs) where path
    items are ('idx', (exprs,)) or ('dot', name); rhs may contain At (@)."""
    fn: Node
    updates: Tuple[Tuple[Tuple, Node], ...]


@dataclass(frozen=True)
class At(Node):
    pass


@dataclass(frozen=True)
class If(Node):
    cond: Node
    then: Node
    els: Node


@dataclass(frozen=True)
class Case(Node):
    arms: Tuple[Tuple[Node, Node], ...]
    other: Optional[Node]


@dataclass(frozen=True)
class Let(Node):
    defs: Tuple[Any, ...]  # OpDef / FnConstrDef units
    body: Node


@dataclass(frozen=True)
class Quant(Node):
    kind: str  # 'A' | 'E'
    binders: Tuple[Tuple[Tuple[str, ...], Optional[Node]], ...]
    body: Node


@dataclass(frozen=True)
class TemporalQuant(Node):
    kind: str  # 'AA' | 'EE'  (\AA / \EE variable hiding)
    vars: Tuple[str, ...]
    body: Node


@dataclass(frozen=True)
class Choose(Node):
    var: Any  # str | Tuple[str, ...] destructuring pattern
    set: Optional[Node]
    pred: Node


@dataclass(frozen=True)
class Prime(Node):
    expr: Node


@dataclass(frozen=True)
class BoxAction(Node):
    # [A]_v
    action: Node
    sub: Node


@dataclass(frozen=True)
class AngleAction(Node):
    # <<A>>_v
    action: Node
    sub: Node


@dataclass(frozen=True)
class Fair(Node):
    kind: str  # 'WF' | 'SF'
    sub: Node
    action: Node


@dataclass(frozen=True)
class Unchanged(Node):
    expr: Node


@dataclass(frozen=True)
class Enabled(Node):
    expr: Node


@dataclass(frozen=True)
class Lambda(Node):
    params: Tuple[str, ...]
    body: Node


# ---------- module-level units ----------

@dataclass(frozen=True)
class Constants(Node):
    # (name, arity) — arity > 0 for operator constants like Send(_,_,_,_)
    names: Tuple[Tuple[str, int], ...]


@dataclass(frozen=True)
class Variables(Node):
    names: Tuple[str, ...]


@dataclass(frozen=True)
class OpDef(Node):
    name: str
    params: Tuple[str, ...]
    body: Node
    local: bool = False


@dataclass(frozen=True)
class FnConstrDef(Node):
    # f[x \in S] == e   (possibly recursive function constructor)
    name: str
    binders: Tuple[Tuple[Tuple[str, ...], Node], ...]
    body: Node
    local: bool = False


@dataclass(frozen=True)
class InstanceDef(Node):
    # name(params) == INSTANCE mod WITH a <- e, ...; name None for bare INSTANCE
    name: Optional[str]
    params: Tuple[str, ...]
    module: str
    substs: Tuple[Tuple[str, Node], ...]
    local: bool = False


@dataclass(frozen=True)
class Assume(Node):
    name: Optional[str]
    expr: Node


@dataclass(frozen=True)
class Theorem(Node):
    name: Optional[str]
    expr: Node


@dataclass(frozen=True)
class RecursiveDecl(Node):
    names: Tuple[Tuple[str, int], ...]


@dataclass(frozen=True)
class Module(Node):
    name: str
    extends: Tuple[str, ...]
    units: Tuple[Node, ...] = field(default_factory=tuple)

    def defs(self):
        for u in self.units:
            if isinstance(u, (OpDef, FnConstrDef)):
                yield u
