r"""jaxmc.serve — checking-as-a-service on the resumable session core.

The paper's endgame (ROADMAP item 3): a checker that amortizes its
expensive artifacts — compiled kernels, capacity profiles, explored
state — across MANY checks, instead of a CLI that pays the full
parse -> compile -> ramp bill per invocation.

    python -m jaxmc.serve run --spool DIR [--port N --workers N]
    python -m jaxmc.serve submit SPEC [--cfg F] [--wait] [--spool DIR]
    python -m jaxmc.serve status [--spool DIR]
    python -m jaxmc.serve smoke  [--spool DIR]   # the make serve-check gate

One long-lived daemon (`serve/daemon.py`) over four pillars:

  session core     each job is a jaxmc/session.py CheckSession
                   (parse -> compile -> explore); the daemon keeps
                   completed sessions WARM keyed by job signature, so a
                   repeat submission re-drives the already-built engine
                   (jit caches intact — zero recompiles) instead of
                   rebuilding it;
  durable queue    an on-disk spool (`serve/queue.py`): every job and
                   result is a JSON file, so a daemon restart loses
                   nothing — queued jobs re-queue, interrupted jobs
                   resume from their checkpoints;
  incremental      every job runs with a checkpoint keyed by its
  re-checks        signature and writes a FINAL checkpoint on
                   completion; an identical later job resumes it and
                   replays the stored verdict (window_recompiles == 0
                   on a warm daemon, asserted by tests/test_serve.py);
  graceful drain   SIGTERM requests a cooperative drain (jaxmc/drain.py):
                   in-flight engines checkpoint at their next safe
                   boundary, drained jobs re-queue for the next daemon
                   life, spans close, the watchdog joins — nothing lost,
                   nothing leaked.

Batching: queued jobs with the SAME signature coalesce into one engine
dispatch (the leader runs, followers get the same result, counter
`serve.batched_jobs`); layout-compatible jobs that differ only in
non-layout options share the warm engine serially.  Obs is the fleet
dashboard: the daemon's own Telemetry carries per-job spans and the
queue-depth / warm-hit / batched-jobs gauges, heartbeats come from the
standard watchdog, and per-job metrics artifacts land in the spool for
`python -m jaxmc.obs report|diff`.

Protocol (JSON over HTTP on 127.0.0.1, `serve/protocol.py`): the daemon
trusts its local submitters — spec/cfg are PATHS resolved in the
daemon's filesystem; there is no auth layer.  Front it with a real
proxy before exposing it beyond localhost.
"""

from .queue import JobQueue  # noqa: F401
from .daemon import ServeDaemon  # noqa: F401
