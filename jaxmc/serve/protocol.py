r"""The serve job protocol: JSON over HTTP, plus the tiny stdlib client.

Endpoints (all JSON bodies/responses; the daemon binds 127.0.0.1):

  POST /jobs          {spec, cfg?, options?{...check options...},
                       tenant?}
                      -> 200 {id, sig, status}  |  400 bad job
                      |  429 admission refused (queue full or the
                         tenant's token bucket is dry): Retry-After
                         header + {error, retry_after_s, reason,
                         queue_depth/…gauges} body — the client backs
                         off and resubmits, nothing was enqueued
                      |  503 daemon is draining, or the spool
                         degraded ({degraded: "spool"}) after
                         exhausting write retries
  GET  /jobs          -> {jobs: [job records]}
  GET  /jobs/<id>     -> job record (+ "result" summary once done)
  GET  /jobs/<id>/result
                      -> the job's full jaxmc.metrics/3 artifact
                         (result block carries ok/counts/violation and
                         the rendered counterexample trace), 404 before
                         completion
  GET  /jobs/<id>/events
                      -> {id, events: [...]} — the job's bounded
                         in-memory trace-event ring (JAXMC_TRACE_RING,
                         default 256), readable MID-RUN; falls back to
                         the persisted per-job trace tail after the
                         daemon forgets the ring; 404 when neither
                         exists
  GET  /metrics       -> Prometheus text format 0.0.4 (fleet counters
                         and gauges as jaxmc_serve_*, per-job series
                         labeled {job="<id>"} incl. the live
                         jaxmc_search_progress_est fraction); never
                         blocks job threads
  GET  /status        -> {queue_depth, running, warm_sessions, workers,
                          draining, counters, gauges, progress}
  POST /drain         -> initiate the graceful drain (same path as
                         SIGTERM); 200 {draining: true}

A job record: {id, sig, status: queued|running|done|failed|drained|
quarantined, submitted_at, started_at?, finished_at?, spec, cfg,
options, batch_leader?, error?, tenant?, daemon?, stolen_by?}.
`daemon` names the fleet member that ran (or is running) the job;
`stolen_by` appears after a lease-expiry takeover.  A QUARANTINED job
(its owner died JAXMC_JOB_RETRIES times across the fleet) answers
GET /jobs/<id> with the quarantine record: the named verdict, the
captured fault context, and the trace tail at death.

Job SIGNATURES (`job_signature`) hash the spec/cfg CONTENTS plus every
result-affecting option (session.SessionConfig.job_signature_fields),
so "identical job" means identical model and identical search — the
key under which checkpoints persist, warm sessions are reused, and
queued duplicates batch through one dispatch.  Editing the spec file
changes the signature and invalidates all of that, by construction.

Options accepted in a submission are the check-surface subset below
(`OPTION_FIELDS`); checkpoint/resume/telemetry paths are daemon-owned
and rejected if submitted.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

from ..session import SessionConfig, default_cfg_path, read_text

# the submission-settable option surface (everything else in
# SessionConfig is daemon-owned plumbing)
OPTION_FIELDS = (
    "backend", "platform", "max_states", "workers", "no_deadlock",
    "seq_cap", "grow_cap", "kv_cap", "no_trace", "host_seen", "sample",
    "chunk", "resident", "include", "progress_every", "res_caps",
    "por",
)

JOB_STATUSES = ("queued", "running", "done", "failed", "drained",
                "quarantined")


class BadJob(ValueError):
    """A submission the daemon refuses; the message is the 400 body."""


class Overloaded(RuntimeError):
    """Admission control refused the submission (bounded spool depth or
    a dry per-tenant token bucket).  Carries the machine-readable
    backoff: the HTTP layer renders 429 + Retry-After + the queue/cost
    gauges in `body`, so clients can distinguish 'fleet is full' from
    'you specifically are over budget'."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 body: Optional[Dict[str, Any]] = None):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)
        self.body = dict(body or {})


def build_config(spec: str, cfg: Optional[str],
                 options: Optional[Dict[str, Any]]) -> SessionConfig:
    """Validate a submission into a SessionConfig (checkpoint fields
    left for the daemon to fill).  Raises BadJob with the defect."""
    if not spec or not isinstance(spec, str):
        raise BadJob("job needs a 'spec' path")
    if not os.path.isfile(spec):
        raise BadJob(f"spec not found on the daemon's filesystem: {spec}")
    if cfg is not None and not os.path.isfile(cfg):
        raise BadJob(f"cfg not found on the daemon's filesystem: {cfg}")
    options = dict(options or {})
    unknown = sorted(set(options) - set(OPTION_FIELDS))
    if unknown:
        raise BadJob(f"unknown/forbidden job options: {unknown} "
                     f"(accepted: {sorted(OPTION_FIELDS)})")
    kw: Dict[str, Any] = {}
    for k in OPTION_FIELDS:
        if k in options and options[k] is not None:
            kw[k] = options[k]
    if "sample" in kw:
        kw["sample"] = tuple(kw["sample"])
    if "include" in kw:
        kw["include"] = tuple(kw["include"])
    try:
        return SessionConfig(spec=spec, cfg=cfg, **kw)
    except TypeError as ex:
        raise BadJob(f"bad job options: {ex}")


def job_signature(cfg: SessionConfig) -> str:
    """The warm-reuse / checkpoint / batching key: spec+cfg CONTENT
    hashes plus the result-affecting option surface."""
    effective_cfg = cfg.cfg or default_cfg_path(cfg.spec)
    ident = dict(cfg.job_signature_fields())
    ident["spec_sha"] = hashlib.sha256(
        read_text(cfg.spec).encode()).hexdigest()
    ident["cfg_sha"] = hashlib.sha256(
        read_text(effective_cfg).encode()).hexdigest() \
        if effective_cfg else None
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


# --------------------------------------------------------------- client

class ServeClient:
    """Minimal stdlib HTTP client for the daemon (tests, the submit/
    status subcommands, the make serve-check smoke)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        # response headers of the LAST request (Retry-After on a 429)
        self.last_headers: Dict[str, str] = {}

    @classmethod
    def from_spool(cls, spool: str, timeout: float = 30.0
                   ) -> "ServeClient":
        """Discover a live daemon from its spool's serve.json stamp."""
        with open(os.path.join(spool, "serve.json"),
                  encoding="utf-8") as fh:
            info = json.load(fh)
        return cls(info["host"], info["port"], timeout)

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None):
        import urllib.request
        import urllib.error
        url = f"http://{self.host}:{self.port}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                self.last_headers = dict(resp.headers.items())
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as ex:
            self.last_headers = dict(ex.headers.items()) \
                if ex.headers is not None else {}
            try:
                return ex.code, json.loads(ex.read().decode())
            except Exception:  # noqa: BLE001 — non-JSON error body
                return ex.code, {"error": str(ex)}

    def submit(self, spec: str, cfg: Optional[str] = None,
               options: Optional[Dict[str, Any]] = None,
               tenant: Optional[str] = None):
        body = {"spec": spec, "cfg": cfg, "options": options or {}}
        if tenant is not None:
            body["tenant"] = tenant
        return self._request("POST", "/jobs", body)

    def job(self, jid: str):
        return self._request("GET", f"/jobs/{jid}")

    def result(self, jid: str):
        return self._request("GET", f"/jobs/{jid}/result")

    def status(self):
        return self._request("GET", "/status")

    def drain(self):
        return self._request("POST", "/drain")

    def wait(self, jid: str, timeout: float = 300.0,
             poll_s: float = 0.2) -> Dict[str, Any]:
        """Poll until the job leaves the queue; returns the final job
        record.  Raises TimeoutError with the last-seen status."""
        import time
        deadline = time.time() + timeout
        last = {}
        while time.time() < deadline:
            code, last = self.job(jid)
            if code == 200 and last.get("status") in (
                    "done", "failed", "drained", "quarantined"):
                return last
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {jid} still {last.get('status')!r} after {timeout}s")
