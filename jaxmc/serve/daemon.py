r"""The serve daemon: a bounded worker pool over the durable spool,
warm CheckSessions, and the fleet telemetry dashboard.

Life of a job (see serve/__init__.py for the system view):

  submit   POST /jobs validates the payload (serve/protocol.py), stamps
           the job SIGNATURE, persists the record (serve/queue.py) and
           wakes a worker — 503 once a drain began;
  batch    the worker that pops a job also claims every QUEUED job with
           the same signature: one engine run answers all of them (for
           the resident engine that is literally one batched kernel
           dispatch sequence), counter `serve.batched_jobs`;
  warm     a signature seen before reuses its WARM CheckSession — the
           already-compiled engine — and resumes the signature-keyed
           checkpoint the previous run finalized: the repeat submission
           replays the stored verdict with zero in-window recompiles
           (`serve.warm_hits`); a cold daemon with a spool checkpoint
           from a previous life still resumes it (`serve.ckpt_resumes`)
           and re-pays only the compile, which the persistent compile
           cache + capacity profile make a disk hit;
  drain    SIGTERM / POST /drain: no new jobs, in-flight engines
           checkpoint at their next safe boundary (jaxmc/drain.py),
           their jobs park as `drained` (re-queued by the next daemon
           life's recover()), workers join, spans close, the watchdog
           stops, the fleet metrics artifact is written.

Telemetry: the daemon owns one fleet Telemetry (per-job `job` spans,
queue-depth/warm-hit/batched-jobs gauges, watchdog heartbeats); each
job ALSO records into a private per-thread recorder (obs.use_local) so
its own spans/levels/counters land in `<spool>/results/<id>.json` as a
normal jaxmc.metrics/3 artifact — `python -m jaxmc.obs report/diff`
works on serve results unchanged.  Each job's recorder additionally
writes a per-job trace (`<spool>/results/<id>.trace.jsonl`, trace
context inherited from the daemon so `obs timeline` stitches daemon +
owner + job into one tree), keeps a bounded in-memory event ring
served live at `GET /jobs/<id>/events`, and runs under its OWN
watchdog (a slow tenant cannot mask another job's stall).  `GET
/metrics` renders the whole fleet as Prometheus text without ever
touching a job thread.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import drain, obs
from ..session import CheckSession
from .protocol import BadJob, build_config, job_signature
from .queue import JobQueue


class _ArtifactSeries:
    """Adapts a finished job's metrics ARTIFACT to the /metrics
    done-series surface (metrics_snapshot / prof / progress_est).  The
    device owner is the default device path since ISSUE 19, so the
    job's live recorder finishes in the OWNER process — the daemon
    renders the TTL-retained final series (running 0, prof sites, hbm
    peak) from the summary the owner shipped back instead."""

    progress_est = None

    class _Site:
        __slots__ = ("dispatches", "wall_s")

    class _Prof:
        __slots__ = ("sites", "hbm_peak_bytes")

    def __init__(self, summary: Dict[str, Any]):
        self._counters = dict(summary.get("counters") or {})
        self._gauges = dict(summary.get("gauges") or {})
        self._levels = list(summary.get("levels") or [])
        self.t_start = summary.get("started_at") or time.time()
        self.prof = None
        pb = summary.get("prof")
        if isinstance(pb, dict):
            prof = self._Prof()
            prof.sites = {}
            prof.hbm_peak_bytes = \
                (pb.get("hbm") or {}).get("peak_bytes", 0)
            for name, sd in sorted((pb.get("sites") or {}).items()):
                st = self._Site()
                st.dispatches = sd.get("dispatches", 0)
                st.wall_s = sd.get("wall_s", 0.0)
                prof.sites[name] = st
            self.prof = prof

    def metrics_snapshot(self) -> Dict[str, Any]:
        return {"counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "levels": list(self._levels)}

    def recent_events(self) -> List[Dict[str, Any]]:
        return []


class ServeDaemon:
    def __init__(self, spool: str, host: str = "127.0.0.1",
                 port: int = 0, workers: int = 2,
                 trace: Optional[str] = None,
                 metrics_out: Optional[str] = None,
                 quiet: bool = False,
                 checkpoint_every: float = 60.0):
        # a fresh daemon re-arms the drain flag: an in-process restart
        # (tests, the smoke gate) must not inherit the last life's drain
        drain.clear()
        self.q = JobQueue(spool)
        self.tel = obs.Telemetry(
            trace_path=trace,
            meta={"command": "serve", "spool": self.q.root,
                  "env": obs.environment_meta()})
        # spool writes surface their retry/degrade telemetry here
        self.q.tel = self.tel
        self.log = obs.Logger(self.tel, quiet=quiet)
        # FLEET IDENTITY (ISSUE 19): several daemons may share one
        # spool; each carries a unique id stamped into its heartbeats,
        # leases, and job records so takeovers are attributable
        self.daemon_id = f"d{os.getpid()}-{os.urandom(3).hex()}"

        def _fenv(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, "") or default)
            except ValueError:
                return default

        # lease discipline: a claim is renewed every lease_renew
        # seconds; a peer treats a lease unrenewed for lease_ttl as the
        # owner's death.  Renew at ttl/3 so two missed beats still
        # leave slack before anyone steals.
        self.lease_ttl = max(0.2, _fenv("JAXMC_LEASE_TTL", 10.0))
        self.lease_renew = max(0.05, _fenv("JAXMC_LEASE_RENEW",
                                           self.lease_ttl / 3.0))
        # bsig-affinity head start: a NON-affine thief waits this much
        # past expiry before stealing, so the peer whose warm registry
        # already knows the job's layout class wins ties
        self.affinity_grace = max(0.0, _fenv(
            "JAXMC_LEASE_AFFINITY_GRACE",
            min(2.0, self.lease_ttl / 2.0)))
        # cross-daemon poison budget: a job whose owner dies this many
        # times FLEET-WIDE is quarantined, not retried forever
        self.job_retries = max(1, int(_fenv("JAXMC_JOB_RETRIES", 3)))
        # ADMISSION CONTROL (ISSUE 19): bounded spool depth + per-tenant
        # token buckets priced by the analyze-cost fast lane.  Overload
        # answers 429 + Retry-After, never an unbounded queue.
        self.max_depth = max(1, int(_fenv("JAXMC_SERVE_MAX_DEPTH",
                                          1000)))
        self.tenant_burst = max(1.0, _fenv("JAXMC_SERVE_TENANT_BURST",
                                           256.0))
        self.tenant_rate = max(0.01, _fenv("JAXMC_SERVE_TENANT_RATE",
                                           32.0))
        # tenant -> [tokens, last refill time]; guarded by _cv
        self._buckets: Dict[str, List[float]] = {}
        # jids whose lease the fleet thread discovered LOST (stolen
        # while we still run them): their results must not publish
        self._lost: set = set()
        self._fleet_thread: Optional[threading.Thread] = None
        self._fleet_size = 1
        self.wd = obs.Watchdog(self.tel)
        self.metrics_out = metrics_out
        self.host = host
        self.port = port
        self.n_workers = max(1, int(workers))
        # env override so subprocess daemons (fleetbench, chaos tests)
        # can tighten the checkpoint cadence takeover resumes ride on
        self.checkpoint_every = _fenv("JAXMC_SERVE_CKPT_EVERY",
                                      checkpoint_every)
        # sig -> {"session": CheckSession, "completed": bool} — the warm
        # kernel registry; "completed" gates checkpoint-replay reuse.
        # Mutated ONLY under _cv (status() snapshots under it too), and
        # each signature additionally serializes its RUNS through
        # _sig_lock: a CheckSession's engine is single-flight state, so
        # two same-signature jobs that dodged batching must not drive
        # it concurrently.
        # BOUNDED LRU (ISSUE 10 satellite, ROADMAP item 3): a
        # long-lived fleet daemon otherwise pins one compiled engine
        # per signature forever.  JAXMC_SERVE_WARM_MAX (default a
        # generous 32) caps the registry; the least-recently-used idle
        # signature is evicted (`serve.evictions` + a `serve.evicted`
        # event), and a re-submission after eviction falls back to the
        # FINAL-CHECKPOINT resume path — bit-identical answer, just
        # cold (the spool checkpoint and the persisted capacity
        # profile survive eviction).
        try:
            self.warm_max = max(1, int(os.environ.get(
                "JAXMC_SERVE_WARM_MAX", "32") or 32))
        except ValueError:
            self.warm_max = 32
        self.warm: Dict[str, Dict[str, Any]] = {}
        self._sig_locks: Dict[str, threading.Lock] = {}
        self._cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        # jid -> (sig, claim token): the token identifies WHICH claim
        # registered the job, so a worker whose fallback REQUEUED a
        # claimed job (another worker may re-claim it immediately)
        # never pops the re-claimer's live registration in its finally
        self._running: Dict[str, Tuple[str, object]] = {}
        self._draining = False
        self._drain_reason: Optional[str] = None
        self._workers: List[threading.Thread] = []
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self._jobs_done = 0
        self._jobs_failed = 0
        # CROSS-MODEL VMAPPED BATCHING (ISSUE 13): jobs whose parse-time
        # batch profile (session.batch_profile) puts them in the same
        # layout-compat class (`bsig`) pop TOGETHER and run as ONE
        # vmapped device program (backend/batch.py) — per-job results
        # byte-identical to solo runs, one compile for the cohort.
        # JAXMC_SERVE_BATCH=0 restores exact-signature-only coalescing.
        self.batch_enabled = os.environ.get(
            "JAXMC_SERVE_BATCH", "1").strip().lower() \
            not in ("0", "off", "no", "false")
        try:
            self.batch_max = max(2, int(os.environ.get(
                "JAXMC_SERVE_BATCH_MAX", "8") or 8))
        except ValueError:
            self.batch_max = 8
        # FAST LANE (ROADMAP 1c): analyze's state-space estimate is a
        # pre-scheduling cost oracle — small proven-bounded jobs jump
        # the queue (they finish in milliseconds; parking them behind a
        # multi-minute search is pure latency for free).
        try:
            self.fastlane_bound = int(os.environ.get(
                "JAXMC_SERVE_FASTLANE_BOUND", "50000") or 50000)
        except ValueError:
            self.fastlane_bound = 50000
        # DEVICE-OWNER process — ON BY DEFAULT (ISSUE 19 satellite,
        # ROADMAP 2a): owner death is supervised (requeue + respawn +
        # the cross-daemon retry budget), so device work leaves the
        # daemon process unless JAXMC_SERVE_DEVICE_OWNER=0 opts out.
        # The spawn is lazy: interp-only daemons never pay for it.
        self.owner = None
        if os.environ.get("JAXMC_SERVE_DEVICE_OWNER", "1").strip() \
                .lower() not in ("0", "off", "no", "false"):
            from .owner import DeviceOwner
            self.owner = DeviceOwner(log=self.log)
        self._batch_sigs_seen: set = set()
        # parse-time batch profiles are mtime-cached per (spec, cfg,
        # options): the admission path pays the model load + bounds
        # fixpoint once per content, not once per submission
        self._bprof_cache: Dict[Any, Any] = {}
        # LIVE EXPOSITION (ISSUE 16): jid -> the job's Telemetry while
        # it runs IN THIS PROCESS (GET /metrics per-job series, GET
        # /jobs/<id>/events, /status progress); finished jobs keep
        # their last ring-buffer snapshot in a small bounded LRU.
        # Owner-process jobs have no in-daemon recorder — their events
        # endpoint reads the tail of the job's trace file instead.
        self._job_tels: Dict[str, Any] = {}
        self._done_events: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()
        self._done_events_max = 16
        # /metrics series TTL hygiene (ISSUE 17): completed jobs keep
        # their {job="<id>"} series (jaxmc_job_running 0 + the final
        # gauges) for JAXMC_METRICS_JOB_TTL seconds after completion,
        # then drop at scrape time — a long-lived fleet no longer grows
        # scrape cardinality with every job it ever ran.  Tests drive
        # expiry by monkeypatching _metrics_clock.
        try:
            self._job_ttl = float(os.environ.get(
                "JAXMC_METRICS_JOB_TTL", "600") or 600)
        except ValueError:
            self._job_ttl = 600.0
        self._metrics_clock = time.time
        # jid -> (completion time, the job's final Telemetry)
        self._done_series: \
            "collections.OrderedDict[str, Tuple[float, Any]]" = \
            collections.OrderedDict()

    # ---- lifecycle ----------------------------------------------------
    def start(self) -> "ServeDaemon":
        # recovery is LEASE-AWARE (ISSUE 19): running jobs still leased
        # by a live peer on the same spool stay theirs; expired ones
        # spend the cross-daemon retry budget (quarantine on exhaustion)
        requeued = self.q.recover(self.daemon_id, ttl=self.lease_ttl,
                                  retries=self.job_retries)
        if requeued:
            self.log(f"serve: requeued {requeued} interrupted job"
                     f"{'s' if requeued != 1 else ''} from the spool")
            self.tel.counter("serve.requeued_on_start", requeued)
        with self._cv:
            for job in sorted(self.q.queued(), key=lambda j: j["id"]):
                self._pending.append(job["id"])
        self._start_http()
        self.q.heartbeat(self.daemon_id, host=self.host,
                         port=self.port, pid=os.getpid())
        self.q.stamp(host=self.host, port=self.port, pid=os.getpid(),
                     workers=self.n_workers, status="serving",
                     daemon=self.daemon_id)
        for wi in range(self.n_workers):
            t = threading.Thread(target=self._worker_loop, args=(wi,),
                                 name=f"jaxmc-serve-w{wi}", daemon=True)
            t.start()
            self._workers.append(t)
        self._fleet_thread = threading.Thread(
            target=self._fleet_loop, name="jaxmc-serve-fleet",
            daemon=True)
        self._fleet_thread.start()
        self.wd.start()
        self._update_gauges()
        self.log(f"serve: listening on http://{self.host}:{self.port} "
                 f"(spool {self.q.root}, {self.n_workers} worker"
                 f"{'s' if self.n_workers != 1 else ''})")
        return self

    def _start_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *a):  # quiet the default stderr
                pass

            def _json(self, code: int, obj, headers=None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                from .protocol import Overloaded
                from .queue import SpoolDegraded
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n).decode()) \
                        if n else {}
                except (ValueError, OSError):
                    return self._json(400, {"error": "bad JSON body"})
                if self.path == "/jobs":
                    try:
                        job = daemon.submit(body)
                    except BadJob as ex:
                        return self._json(400, {"error": str(ex)})
                    except Overloaded as ex:
                        # the 429 contract (ISSUE 19): Retry-After in
                        # the header AND machine-readable gauges in
                        # the body, so clients can back off precisely
                        return self._json(
                            429,
                            dict(ex.body, error=str(ex),
                                 retry_after_s=ex.retry_after_s),
                            headers={"Retry-After": str(max(
                                1, int(round(ex.retry_after_s))))})
                    except SpoolDegraded as ex:
                        # hardened spool writes degrade with a NAMED
                        # verdict, never a raw 500
                        return self._json(
                            503, {"error": str(ex),
                                  "degraded": "spool"})
                    except RuntimeError as ex:  # draining
                        return self._json(503, {"error": str(ex)})
                    return self._json(200, job)
                if self.path == "/drain":
                    daemon.initiate_drain("POST /drain")
                    return self._json(200, {"draining": True})
                return self._json(404, {"error": f"no route {self.path}"})

            def do_GET(self):
                if self.path == "/status":
                    return self._json(200, daemon.status())
                if self.path == "/metrics":
                    # Prometheus text exposition; the snapshot copies
                    # are short-critical-section, so a scraper can poll
                    # aggressively without blocking job threads
                    body = daemon.metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/jobs":
                    return self._json(200,
                                      {"jobs": daemon.q.list_jobs()})
                if self.path.startswith("/jobs/"):
                    parts = self.path.split("/")
                    jid = parts[2] if len(parts) > 2 else ""
                    if len(parts) == 4 and parts[3] == "events":
                        evs = daemon.job_events(jid)
                        if evs is None:
                            return self._json(
                                404, {"error": f"no events for {jid}"})
                        return self._json(200, {"job": jid,
                                                "events": evs})
                    if len(parts) == 4 and parts[3] == "result":
                        res = daemon.q.load_result(jid)
                        if res is None:
                            return self._json(
                                404, {"error": f"no result for {jid}"})
                        return self._json(200, res)
                    job = daemon.q.load(jid)
                    if job is None:
                        # quarantined jobs answer with a NAMED verdict
                        # (ISSUE 19): the captured fault context and
                        # trace tail travel with it
                        qrec = daemon.q.load_quarantined(jid)
                        if qrec is not None:
                            return self._json(200, qrec)
                        return self._json(404,
                                          {"error": f"no job {jid}"})
                    if job.get("status") == "done":
                        res = daemon.q.load_result(jid)
                        if res is not None:
                            job = dict(job, result=res.get("result"),
                                       serve=res.get("serve"))
                    return self._json(200, job)
                return self._json(404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="jaxmc-serve-http",
            daemon=True)
        self._http_thread.start()

    def serve_forever(self) -> int:
        """Block until a drain completes; returns the process exit code
        (0 — a drained daemon is a clean daemon)."""
        try:
            while not self._draining:
                time.sleep(0.2)
                self._update_gauges()
        except KeyboardInterrupt:
            self.initiate_drain("KeyboardInterrupt")
        self.shutdown()
        return 0

    def initiate_drain(self, reason: str) -> None:
        """Begin the graceful drain (idempotent): refuse new jobs, ask
        every in-flight engine to checkpoint and stop (jaxmc/drain.py),
        wake idle workers so they exit."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
            self._drain_reason = reason
            self._cv.notify_all()
        drain.request(f"serve drain: {reason}")
        if self.owner is not None:
            # forward to the device-owner process: its engines park at
            # their next safe boundary exactly like in-process ones
            self.owner.drain()
        self.tel.event("serve.drain", reason=reason)
        self.log(f"serve: draining ({reason}) — in-flight jobs will "
                 f"checkpoint and requeue")

    def shutdown(self) -> None:
        """Complete the drain: join workers (their engines return at
        the next safe boundary), stop HTTP, persist the fleet metrics,
        close everything.  No orphan workers, no open spans."""
        if not self._draining:
            self.initiate_drain("shutdown()")
        for t in self._workers:
            t.join(timeout=120.0)
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=10.0)
            self._fleet_thread = None
        alive = [t.name for t in self._workers if t.is_alive()]
        if alive:  # never expected: engines poll drain at every level
            self.log(f"serve: WARNING: workers still alive at shutdown: "
                     f"{alive}")
        self._workers = []
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        if self.owner is not None:
            self.owner.stop()
        self.wd.stop()
        self._update_gauges()
        # leave the fleet cleanly: a stale heartbeat record would make
        # peers defer submissions to a ghost until it aged out
        self.q.remove_daemon(self.daemon_id)
        self.q.stamp(host=self.host, port=self.port, pid=os.getpid(),
                     workers=self.n_workers, status="stopped",
                     drain_reason=self._drain_reason)
        if self.metrics_out:
            self.tel.write_metrics(
                self.metrics_out,
                result={"ok": True, "distinct": 0, "generated": 0,
                        "diameter": 0, "truncated": False,
                        "jobs_done": self._jobs_done,
                        "jobs_failed": self._jobs_failed,
                        "drain_reason": self._drain_reason})
        self.tel.close()
        # re-arm the process-global drain flag: every engine in this
        # daemon has returned, and an in-process successor daemon (the
        # smoke gate, restart tests) must not inherit a stale request
        drain.clear()

    # ---- admission control (ISSUE 19) ---------------------------------
    def _admit(self, tenant: str, charge: float) -> Tuple[bool, float]:
        """Per-tenant token bucket: `charge` tokens (priced by the
        analyze-cost estimate) or a (False, retry-after) rejection.
        Buckets refill continuously at tenant_rate up to tenant_burst."""
        now = time.time()
        with self._cv:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = [self.tenant_burst, now]
            tokens, last = b
            tokens = min(self.tenant_burst,
                         tokens + (now - last) * self.tenant_rate)
            if tokens >= charge:
                b[0], b[1] = tokens - charge, now
                return True, 0.0
            b[0], b[1] = tokens, now
            return False, (charge - tokens) / self.tenant_rate

    def _reject(self, tenant: str, reason: str, retry_after: float,
                **gauges) -> None:
        self.tel.counter("serve.admission_rejected")
        self.tel.event("serve.admission_rejected", tenant=tenant,
                       reason=reason, **gauges)
        from .protocol import Overloaded
        raise Overloaded(
            f"admission refused ({reason}); retry after "
            f"{retry_after:.1f}s",
            retry_after_s=retry_after,
            body=dict(gauges, tenant=tenant, reason=reason))

    # ---- submission ---------------------------------------------------
    def submit(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        if self._draining:
            raise RuntimeError("daemon is draining; resubmit to the "
                               "next daemon life (the spool persists)")
        tenant = str(payload.get("tenant") or "default")
        with self._cv:
            depth = len(self._pending) + len(self._running)
        if depth >= self.max_depth:
            # bounded spool: overload is a FAST, attributable 429 with
            # the queue gauges in the body — never an unbounded queue
            self._reject(tenant, "queue_full",
                         min(60.0, max(1.0, 0.25 * depth)),
                         queue_depth=depth, max_depth=self.max_depth)
        cfg = build_config(payload.get("spec"), payload.get("cfg"),
                           payload.get("options"))
        # submit-time static analysis (ISSUE 9): a statically-broken
        # spec/cfg pair (cfg names an undefined invariant, unassigned
        # CONSTANTs, unparseable inputs — the linter's error-severity
        # classes) is rejected HERE, before it occupies a worker or
        # enters the durable spool; the 400 payload carries the
        # diagnostics.  JAXMC_SERVE_ANALYZE=0 opts out.
        if os.environ.get("JAXMC_SERVE_ANALYZE", "1").strip().lower() \
                not in ("0", "off", "no", "false"):
            from ..analyze.lint import errors, lint_pair
            errs = errors(lint_pair(cfg.spec, cfg.cfg,
                                    tuple(cfg.include or ()),
                                    semantic=False))
            if errs:
                self.tel.counter("serve.jobs_rejected")
                self.tel.event("serve.job_rejected",
                               spec=cfg.spec,
                               codes=[d.code for d in errs])
                raise BadJob(
                    "statically broken job rejected by the analyzer: "
                    + "; ".join(d.render() for d in errs[:5]))
        sig = job_signature(cfg)
        # parse-time batch profile (ISSUE 13): the layout-compat class
        # key + analyze's cost estimate, both computed BEFORE any
        # engine exists; a failure here only means the job schedules
        # solo, exactly as before
        bsig = cost = None
        fast = False
        if self.batch_enabled and cfg.backend != "interp":
            # mtime-keyed cache: the profile costs a model load + the
            # bounds fixpoint — pay it once per (spec, cfg, options)
            # content, not once per submission on the admission path
            try:
                key = (cfg.spec, cfg.cfg,
                       os.path.getmtime(cfg.spec),
                       os.path.getmtime(cfg.cfg) if cfg.cfg else None,
                       json.dumps(cfg.batch_signature_fields(),
                                  sort_keys=True))
            except OSError:
                key = None
            if key is not None and key in self._bprof_cache:
                prof = self._bprof_cache[key]
            else:
                from ..session import batch_profile
                try:
                    prof = batch_profile(cfg)
                except Exception:  # noqa: BLE001 — profiling must
                    prof = None    # never reject a servable job
                if key is not None:
                    if len(self._bprof_cache) >= 256:
                        self._bprof_cache.clear()
                    self._bprof_cache[key] = prof
            if prof is not None:
                bsig, cost = prof.bsig, prof.cost_estimate
                fast = cost is not None and cost <= self.fastlane_bound
        # token-bucket admission, PRICED by the fast-lane cost oracle:
        # proven-small jobs are cheap, estimate-heavy ones cost up to
        # 4 tokens, unpriced jobs cost 1 — so a tenant's burst budget
        # is spent in proportion to the work it schedules
        charge = 1.0
        if cost is not None:
            charge = 0.25 if fast else min(
                4.0, 1.0 + cost / (4.0 * self.fastlane_bound))
        ok, wait_s = self._admit(tenant, charge)
        if not ok:
            self._reject(tenant, "tenant_rate",
                         max(0.1, wait_s), queue_depth=depth,
                         cost_estimate=cost, charge=charge)
        job = self.q.new_job(cfg.spec, cfg.cfg, payload.get("options"),
                             sig, bsig=bsig, cost_estimate=cost,
                             fast_lane=fast or None, tenant=tenant)
        self.tel.counter("serve.jobs_submitted")
        # WARM-HIT ROUTING (ISSUE 19): on a multi-daemon spool, a job
        # whose signature is NOT warm here stays spool-only — a peer
        # whose warm registry knows it adopts it immediately from its
        # fleet scan, everyone else (including us) only after the
        # affinity grace.  Single-daemon spools enqueue locally always.
        with self._cv:
            sig_warm = sig in self.warm
        if not fast and not sig_warm and self._fleet_size > 1:
            self.tel.counter("serve.jobs_deferred")
            with self._cv:
                self._cv.notify()
            self._update_gauges()
            return job
        with self._cv:
            if fast:
                # proven-small jobs jump the queue (fast lane)
                self._pending.appendleft(job["id"])
                self.tel.counter("serve.fastlane_jobs")
            else:
                self._pending.append(job["id"])
            if bsig:
                self._batch_sigs_seen.add(bsig)
                self.tel.gauge("serve.batch_sigs",
                               len(self._batch_sigs_seen))
            self._cv.notify()
        self._update_gauges()
        return job

    # ---- the fleet thread (ISSUE 19) -----------------------------------
    def _fleet_loop(self) -> None:
        """Heartbeat + lease renewal + spool scan, one thread.  The
        `lease_stall` fault site freezes a whole tick (no heartbeat, no
        renewals) so tests can force a live daemon's leases to expire
        and prove the double-claim arbitration."""
        from .. import faults
        interval = max(0.05, min(self.lease_renew, 1.0))
        while not self._draining:
            if faults.fire("lease_stall", daemon=self.daemon_id):
                self.tel.counter("serve.lease_stalls")
                time.sleep(interval)
                continue
            try:
                self._fleet_tick()
            except Exception as ex:  # noqa: BLE001 — the fleet thread
                # must outlive any one bad spool read
                self.tel.event("serve.fleet_tick_error", error=str(ex))
            time.sleep(interval)

    def _fleet_tick(self) -> None:
        self.q.heartbeat(self.daemon_id, host=self.host,
                         port=self.port, pid=os.getpid(),
                         running=len(self._running),
                         warm=len(self.warm))
        self._fleet_size = max(1, len(self.q.daemons(self.lease_ttl)))
        # renew every lease we hold; a failed renewal means a peer
        # stole the job (our stall outlived the TTL) — the run paths
        # check _lost before publishing anything
        with self._cv:
            held = list(self._running)
        for jid in held:
            if self.q.renew(jid, self.daemon_id):
                continue
            with self._cv:
                if jid not in self._running:
                    continue  # finished+released between snapshot/renew
            cur = self.q.lease(jid)
            if cur is None and self.q.try_claim(
                    jid, self.daemon_id, self.lease_ttl):
                continue  # lease file vanished; re-established
            with self._cv:
                if jid in self._lost:
                    continue
                self._lost.add(jid)
            self.tel.counter("serve.lease_lost")
            self.tel.event("serve.lease_lost", id=jid,
                           thief=(cur or {}).get("daemon"))
            self.log(f"serve: lease on {jid} LOST to "
                     f"{(cur or {}).get('daemon')} — its result will "
                     f"be discarded here")
        self._scan_spool()

    def _scan_spool(self) -> None:
        """Adopt spool work this daemon does not know about: queued
        jobs other daemons deferred (bsig-affinity routing) and running
        jobs whose lease expired (crash takeover).  Affine daemons —
        signature warm here, or the layout class already run here —
        move first; everyone else waits out the affinity grace."""
        now = time.time()
        with self._cv:
            known = set(self._pending) | set(self._running)
            warm_sigs = set(self.warm)
            bsigs = set(self._batch_sigs_seen)
        adopted = []
        for job in self.q.list_jobs():
            jid = job["id"]
            if jid in known:
                continue
            status = job.get("status")
            affine = job.get("sig") in warm_sigs or \
                (job.get("bsig") and job.get("bsig") in bsigs) or \
                bool(job.get("fast_lane"))
            if status == "queued":
                age = now - float(job.get("submitted_at") or 0)
                if affine or age > self.affinity_grace or \
                        self._fleet_size <= 1:
                    adopted.append(jid)
                    if affine:
                        self.tel.counter("serve.affinity_adoptions")
            elif status == "running":
                cur = self.q.lease(jid)
                expired = cur is None or cur["age"] > self.lease_ttl
                if not expired:
                    continue
                if not affine and cur is not None and \
                        cur["age"] <= self.lease_ttl + \
                        self.affinity_grace:
                    continue  # give an affine thief the head start
                out = self.q.takeover(jid, self.daemon_id,
                                      self.lease_ttl, self.job_retries)
                if out == "requeued":
                    self.tel.counter("serve.takeovers")
                    self.tel.event("serve.takeover", id=jid,
                                   dead=(cur or {}).get("daemon"))
                    self.log(f"serve: took over {jid} from dead peer "
                             f"{(cur or {}).get('daemon')} (lease "
                             f"expired; resuming from its checkpoint)")
                    adopted.append(jid)
        if adopted:
            with self._cv:
                for jid in adopted:
                    if jid not in self._pending and \
                            jid not in self._running:
                        self._pending.append(jid)
                self._cv.notify_all()
            self.tel.counter("serve.jobs_adopted", len(adopted))
            self._update_gauges()

    def _still_owned(self, jid: str) -> bool:
        """May THIS daemon publish the job's result?  False once the
        fleet thread saw the lease stolen, or the spool says another
        daemon holds it now."""
        with self._cv:
            if jid in self._lost:
                return False
        return self.q.owns(jid, self.daemon_id)

    def _publishable(self, jobs: List[Dict[str, Any]]) -> \
            List[Dict[str, Any]]:
        """Filter a finished claim down to the members whose lease we
        still hold; dropped members were stolen mid-run (the thief's
        re-run is the publication of record — exactly one winner)."""
        out = []
        for j in jobs:
            if self._still_owned(j["id"]):
                out.append(j)
            else:
                self.tel.counter("serve.lease_lost_drops")
                self.tel.event("serve.lease_lost_drop", id=j["id"])
        return out

    # ---- workers ------------------------------------------------------
    def _worker_loop(self, wi: int) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._draining:
                    self._cv.wait(0.5)
                if self._draining:
                    return  # queued jobs persist for the next life
                jid = self._pending.popleft()
                job = self.q.load(jid)
                if job is not None and job.get("status") != "queued":
                    # finished/claimed through the shared spool by a
                    # peer daemon while it sat in our local deque
                    job = None
                if job is not None and not self.q.try_claim(
                        jid, self.daemon_id, self.lease_ttl):
                    job = None  # a peer holds a live lease on it
                followers: List[Dict[str, Any]] = []
                xmembers: List[Dict[str, Any]] = []
                if job is not None:
                    # BATCH: claim every queued job with this signature
                    # (one engine run answers all of them) AND — when
                    # the leader carries a batch profile — every job in
                    # the same LAYOUT-COMPAT class (`bsig`): those run
                    # as one vmapped device program (ISSUE 13).
                    # Claiming happens under the ONE _cv hold that also
                    # registers every claimed id in _running, so a
                    # second worker popping the same signature class
                    # can never pick a claimed follower up again (the
                    # satellite race), and the LRU eviction's busy-set
                    # sees every claimed signature.
                    bsig = job.get("bsig") if self.batch_enabled \
                        else None
                    xsigs = {job["sig"]}
                    rest = []
                    for other in self._pending:
                        oj = self.q.load(other)
                        if oj is None:
                            rest.append(other)
                        elif oj.get("status") != "queued":
                            continue  # a peer already took it; drop
                        elif oj.get("sig") == job["sig"]:
                            if self.q.try_claim(other, self.daemon_id,
                                                self.lease_ttl):
                                followers.append(oj)
                            # claim lost to a peer: drop from our deque
                        elif bsig and oj.get("bsig") == bsig and \
                                (oj.get("sig") in xsigs or
                                 len(xsigs) < self.batch_max) and \
                                (not job.get("fast_lane") or
                                 oj.get("fast_lane")) and \
                                self.q.try_claim(other, self.daemon_id,
                                                 self.lease_ttl):
                            # a fast-lane leader claims only fast-lane
                            # members: stapling a proven-small job to a
                            # multi-minute cohort member would withhold
                            # its result for the whole cohort wall —
                            # the inversion the lane exists to prevent
                            xmembers.append(oj)
                            xsigs.add(oj["sig"])
                        else:
                            rest.append(other)
                    self._pending = collections.deque(rest)
                    tok = object()  # this claim's ownership marker
                    self._running[jid] = (job["sig"], tok)
                    for j in followers + xmembers:
                        self._running[j["id"]] = (j["sig"], tok)
            if job is None:
                continue
            claimed = followers + xmembers
            try:
                if xmembers:
                    self._run_vbatch(job, followers, xmembers)
                elif self.owner is not None and \
                        (job.get("options") or {}).get(
                            "backend", "interp") != "interp":
                    # owner mode: solo DEVICE jobs leave the daemon
                    # process too (interp jobs stay on the thread pool)
                    self._run_owner_solo(job, followers)
                else:
                    self._run_batch(job, followers)
            except Exception as ex:  # noqa: BLE001 — a job failure must
                # never kill the worker; the defect lands on the job —
                # but only on jobs THIS claim still owns (a fallback
                # may have requeued some, and another worker may
                # already be running them)
                with self._cv:
                    own = self._running.get(job["id"])
                    leader_owned = own is not None and own[1] is tok
                    still = [
                        j for j in claimed
                        if (self._running.get(j["id"])
                            or (None, None))[1] is tok]
                err = f"{type(ex).__name__}: {ex}"
                if leader_owned:
                    self._fail_job(job, still, err)
                elif still:
                    # the leader itself was requeued (and possibly
                    # re-claimed elsewhere): fail only the members this
                    # claim still owns
                    self._fail_job(still[0], still[1:], err)
            finally:
                mine = []
                with self._cv:
                    for j in [job] + claimed:
                        cur = self._running.get(j["id"])
                        if cur is not None and cur[1] is tok:
                            self._running.pop(j["id"])
                            mine.append(j["id"])
                    self._lost.difference_update(
                        j["id"] for j in [job] + claimed)
                # drop the leases this claim still holds — requeued
                # members released theirs when they were handed back
                for mj in mine:
                    self.q.release(mj, self.daemon_id)
                self._update_gauges()

    def _fail_job(self, job, followers, error: str) -> None:
        self.tel.counter("serve.jobs_failed", 1 + len(followers))
        self._jobs_failed += 1 + len(followers)
        self.tel.event("serve.job_failed", id=job["id"], error=error)
        self.log(f"serve: job {job['id']} FAILED: {error}")
        for j in [job] + followers:
            self.q.mark(j["id"], "failed", error=error,
                        finished_at=time.time(),
                        batch_leader=job["id"]
                        if j is not job else None)

    def _requeue_or_quarantine(self, members: List[Dict[str, Any]],
                               note: str) -> None:
        """Hand crashed-owner jobs back to the fleet: each spends one
        unit of its CROSS-DAEMON retry budget and requeues; a member
        whose budget is gone is a poison job and quarantines with the
        fault context instead (ISSUE 19 tentpole 3)."""
        with self._cv:
            for j in members:
                attempt = self.q.spend_retry(j["id"], self.job_retries)
                if attempt is None:
                    self._running.pop(j["id"], None)
                    self.q.quarantine(
                        j["id"],
                        f"poison job: owner died {self.job_retries} "
                        f"times across the fleet (cross-daemon retry "
                        f"budget exhausted)",
                        context={"note": note,
                                 "daemon": self.daemon_id})
                    continue
                self.q.mark(j["id"], "queued",
                            requeue_note=f"{note} (attempt {attempt}/"
                                         f"{self.job_retries})")
                self.q.release(j["id"], self.daemon_id)
                self._running.pop(j["id"], None)
                self._pending.append(j["id"])
            self._cv.notify_all()

    def _sig_lock(self, sig: str) -> threading.Lock:
        with self._cv:
            lk = self._sig_locks.get(sig)
            if lk is None:
                lk = self._sig_locks[sig] = threading.Lock()
            return lk

    def _locked_sig(self, sig: str):
        """Per-signature run lock, IMMUNE to the LRU-eviction race
        (ISSUE 13 bugfix): eviction pops a sig's lock from the registry,
        and a worker that FETCHED the lock object before the eviction
        but ACQUIRED it after would no longer serialize against a later
        worker's fresh lock — two jobs could then drive one warm
        session's single-flight engine concurrently.  Re-fetch after
        acquiring and retry until the held object IS the registered
        one."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            while True:
                lk = self._sig_lock(sig)
                lk.acquire()
                with self._cv:
                    if self._sig_locks.get(sig) is lk:
                        break
                lk.release()
            try:
                yield
            finally:
                lk.release()

        return _cm()

    def _touch_warm_locked(self, sig: str) -> None:
        """Move `sig` to the registry's most-recently-used end (dicts
        are insertion-ordered; caller holds _cv)."""
        entry = self.warm.pop(sig, None)
        if entry is not None:
            self.warm[sig] = entry

    def _evict_warm_locked(self) -> None:
        """Evict least-recently-used IDLE signatures past warm_max
        (caller holds _cv).  A signature mid-run (claimed in _running
        or its per-sig lock held) is never evicted — the next-oldest
        idle one goes instead."""
        if len(self.warm) <= self.warm_max:
            return
        busy = {s for s, _t in self._running.values()}
        for sig in list(self.warm):
            if len(self.warm) <= self.warm_max:
                break
            if sig in busy:
                continue
            lk = self._sig_locks.get(sig)
            if lk is not None and lk.locked():
                continue
            del self.warm[sig]
            self._sig_locks.pop(sig, None)
            self.tel.counter("serve.evictions")
            self.tel.event("serve.evicted", sig=sig)
            self.log(f"serve: evicted warm session {sig[:12]} "
                     f"(LRU, warm_max={self.warm_max}; resubmission "
                     f"resumes its final checkpoint cold)")

    def _revalidate_profile(self, sess: CheckSession, job_tel) -> None:
        """Warm-path consistency check: confirm the DURABLE capacity
        profile still matches the warm engine's layout before trusting
        its caps (counts as a profile hit in the job's artifact; a
        missing/stale profile only means the next cold engine re-learns
        — the warm engine's own caps stay valid)."""
        if sess.layout_sig and sess.model is not None:
            from ..compile.cache import load_capacity_profile
            # profiles are namespaced by backend platform (ISSUE 11):
            # ask the warm engine's descriptor for the variant the
            # profile was saved under
            desc = getattr(sess.engine, "backend_desc", None)
            variant = desc.profile_variant() if desc is not None else ""
            load_capacity_profile(sess.model.module.name,
                                  sess.layout_sig, tel=job_tel,
                                  variant=variant)

    def _job_trace_path(self, jid: str) -> str:
        """The job's JSONL trace artifact (next to its result JSON) —
        one lane of the fleet's `obs timeline` view."""
        return os.path.join(self.q.results_dir, f"{jid}.trace.jsonl")

    def _register_job_tel(self, jids: List[str], job_tel) -> None:
        with self._cv:
            for j in jids:
                self._job_tels[j] = job_tel

    def _unregister_job_tel(self, jids: List[str], job_tel) -> None:
        """Drop the live registration; the leader keeps its final ring
        snapshot in the bounded done-LRU so /jobs/<id>/events stays
        answerable briefly after completion."""
        with self._cv:
            now = self._metrics_clock()
            for j in jids:
                if self._job_tels.get(j) is job_tel:
                    del self._job_tels[j]
                # TTL-retained /metrics series (ISSUE 17): scrapes keep
                # rendering the finished job's final series (running 0)
                # until the TTL prunes it at scrape time
                self._done_series[j] = (now, job_tel)
                self._done_series.move_to_end(j)
            if jids:
                self._done_events[jids[0]] = job_tel.recent_events()
                self._done_events.move_to_end(jids[0])
                while len(self._done_events) > self._done_events_max:
                    self._done_events.popitem(last=False)

    def _register_done_artifact(self, jids: List[str],
                                summary: Dict[str, Any]) -> None:
        """TTL-retained /metrics series for owner-run jobs: the live
        recorder finished in the owner process, so render the final
        series from the shipped artifact (same prune window as the
        in-daemon path's _unregister_job_tel)."""
        series = _ArtifactSeries(summary)
        with self._cv:
            now = self._metrics_clock()
            for j in jids:
                self._done_series[j] = (now, series)
                self._done_series.move_to_end(j)

    def _run_batch(self, job: Dict[str, Any],
                   followers: List[Dict[str, Any]]) -> None:
        jid, sig = job["id"], job["sig"]
        cfg = build_config(job["spec"], job.get("cfg"),
                           job.get("options"))
        if cfg.backend == "interp" and not cfg.workers:
            # daemon parallelism comes from the WORKER POOL (several
            # jobs at once), not per-job fork pools: forking from a
            # multithreaded daemon risks classic fork+locks hangs, so
            # interp jobs default to the serial engine unless the
            # submission explicitly asks for a worker count (note both
            # None and 0 mean "auto" on the CLI surface — neither may
            # reach default_workers() here)
            cfg.workers = 1
        ck = self.q.ckpt_path(sig)
        cfg.checkpoint = ck
        cfg.checkpoint_every = self.checkpoint_every
        cfg.final_checkpoint = True
        job_tel = obs.Telemetry(
            trace_path=self._job_trace_path(jid),
            meta={"command": "serve.job", "job": jid, "sig": sig,
                  "backend": cfg.backend, "spec": job["spec"],
                  "cfg": job.get("cfg"), "env": obs.environment_meta()})
        # per-JOB watchdog (ISSUE 16): the stall threshold derives from
        # THIS job's level rhythm — concurrent tenants no longer share
        # one threshold built from their mixed median level wall
        jwd = obs.Watchdog(job_tel)
        jids = [j["id"] for j in [job] + followers]
        self._register_job_tel(jids, job_tel)
        jwd.start()
        try:
            self._run_batch_inner(job, followers, cfg, ck, job_tel)
        finally:
            jwd.stop()
            self._unregister_job_tel(jids, job_tel)

    def _run_batch_inner(self, job: Dict[str, Any],
                         followers: List[Dict[str, Any]],
                         cfg, ck: str, job_tel) -> None:
        jid, sig = job["id"], job["sig"]
        t0 = time.time()
        for j in [job] + followers:
            self.q.mark(j["id"], "running", started_at=t0,
                        daemon=self.daemon_id,
                        batch_leader=jid if j is not job else None)
        if followers:
            self.tel.counter("serve.batched_jobs", len(followers))
        self._update_gauges()
        from .. import faults
        faults.kill_self("daemon_kill", job=jid, kind="solo",
                         spec=os.path.basename(job["spec"]))

        with self._cv:
            warm = self.warm.get(sig)
            if warm is not None:
                self._touch_warm_locked(sig)
        warm_engine = resumed = False
        with self._locked_sig(sig), obs.use_local(job_tel), \
                self.tel.span("job", id=jid, sig=sig, spec=job["spec"],
                              backend=cfg.backend,
                              batched=len(followers)):
            if warm is not None and warm.get("completed") and \
                    os.path.exists(ck):
                # WARM: the already-compiled engine replays the
                # finalized checkpoint — zero recompiles, instant answer
                warm_engine = resumed = True
                self.tel.counter("serve.warm_hits")
                sess = warm["session"]
                # rebind the session's telemetry channel to THIS job's
                # recorder (it was constructed with the cold job's, long
                # closed): the warm artifact must carry its own search
                # span like any other jaxmc.metrics summary
                sess.tel = job_tel
                sess.log = obs.Logger(job_tel, quiet=True)
                self._revalidate_profile(sess, job_tel)
                res = sess.explore(resume_from=ck, checkpoint_path=ck,
                                   final_checkpoint=True)
            else:
                self.tel.counter("serve.cold_runs")
                if os.path.exists(ck):
                    # a previous daemon life checkpointed this signature
                    # (periodic, drain, or final): resume incrementally
                    cfg.resume = ck
                    resumed = True
                    self.tel.counter("serve.ckpt_resumes")
                sess = CheckSession(cfg, tel=job_tel,
                                    log=obs.Logger(job_tel, quiet=True))
                if sess.parse() == "assumes":
                    raise BadJob(
                        "assumes-mode specs (no behavior spec) are not "
                        "servable; run them via `python -m jaxmc check`")
                try:
                    sess.compile()
                    res = sess.explore()
                except (RuntimeError, OSError, MemoryError,
                        ConnectionError) as ex:
                    if cfg.backend == "interp":
                        raise
                    # the CLI's device->CPU fallback, same policy
                    # (session.demote_to_cpu is the shared path)
                    res = sess.demote_to_cpu(ex)
                with self._cv:
                    self.warm[sig] = {"session": sess,
                                      "completed": False}
                    self._evict_warm_locked()

        drained = bool(getattr(res, "drained", False))
        completed = res.ok and not res.truncated and not drained
        with self._cv:
            if sig in self.warm:
                # checkpoint-replay reuse only for COMPLETED searches
                # (the final checkpoint exists exactly then); other
                # outcomes still keep the warm kernels for the next
                # submission
                self.warm[sig]["completed"] = completed or \
                    self.warm[sig].get("completed", False)

        # the job artifact: a normal jaxmc.metrics/2 summary + the
        # serve block (obs/schema.py PR-7 notes)
        window_recompiles = sum(1 for lv in job_tel.levels
                                if lv.get("fresh_compile"))
        wall = time.time() - t0
        result_block: Dict[str, Any] = {
            "ok": res.ok, "distinct": res.distinct,
            "generated": res.generated, "diameter": res.diameter,
            "truncated": bool(res.truncated),
            "wall_s": round(res.wall_s, 6),
            "warnings": list(getattr(res, "warnings", []))}
        if drained:
            result_block["drained"] = True
        if res.violation is not None:
            from ..engine.explore import format_trace
            result_block["violation"] = {"kind": res.violation.kind,
                                         "name": res.violation.name}
            result_block["trace"] = format_trace(res.violation)
        summary = job_tel.summary(result=result_block)
        summary["backend"] = cfg.backend
        summary["spec"] = job["spec"]
        summary["serve"] = {
            "sig": sig, "warm_engine": warm_engine,
            "resumed_from_checkpoint": resumed,
            "window_recompiles": window_recompiles,
            "profile_hits": job_tel.counters.get("profile.hits", 0),
            "persistent_cache_hits": job_tel.counters.get(
                "compile.persistent_cache_hits", 0),
            "batched_with": [f["id"] for f in followers],
            "job_wall_s": round(wall, 6),
        }
        job_tel.close()
        # run ledger (ISSUE 17): one trajectory point per batch (the
        # leader's summary IS every member's summary); never raises
        try:
            from ..obs.ledger import append_summary
            append_summary(summary, source=job["spec"])
        except Exception:  # noqa: BLE001
            pass

        status = "drained" if drained else "done"
        publish = self._publishable([job] + followers)
        if not publish:
            return  # every member was stolen mid-run; the thief answers
        for j in publish:
            self.q.save_result(j["id"], summary)
            self.q.mark(j["id"], status, finished_at=time.time(),
                        ok=res.ok, distinct=res.distinct,
                        generated=res.generated,
                        warm_engine=warm_engine,
                        resumed_from_checkpoint=resumed,
                        window_recompiles=window_recompiles,
                        daemon=self.daemon_id,
                        batch_leader=jid if j is not job else None)
        if drained:
            self.tel.counter("serve.jobs_drained", len(publish))
            self.log(f"serve: job {jid} drained at a safe boundary "
                     f"(checkpointed; will resume next life)")
        else:
            self.tel.counter("serve.jobs_done", len(publish))
            self._jobs_done += len(publish)
            self.log(f"serve: job {jid} done in {wall:.2f}s "
                     f"(ok={res.ok}, {res.distinct} distinct, "
                     f"warm={warm_engine}, resumed={resumed}, "
                     f"batched={len(followers)})")

    def _run_owner_solo(self, job: Dict[str, Any],
                        followers: List[Dict[str, Any]]) -> None:
        """One solo device job (plus exact-sig followers) in the
        device-owner process.  The in-process warm registry does not
        apply — the signature-keyed spool checkpoint still makes
        repeats incremental (the owner resumes it) — and an owner death
        requeues the jobs exactly like a mid-batch death."""
        t0 = time.time()
        jid, sig = job["id"], job["sig"]
        jobs = [job] + followers
        for j in jobs:
            self.q.mark(j["id"], "running", started_at=t0,
                        daemon=self.daemon_id,
                        batch_leader=jid if j is not job else None)
        if followers:
            self.tel.counter("serve.batched_jobs", len(followers))
        self._update_gauges()
        from .. import faults
        faults.kill_self("daemon_kill", job=jid, kind="solo",
                         spec=os.path.basename(job["spec"]))
        md = {"spec": job["spec"], "cfg": job.get("cfg"),
              "options": job.get("options"), "sig": sig,
              "jids": [j["id"] for j in jobs],
              "checkpoint": self.q.ckpt_path(sig),
              "checkpoint_every": self.checkpoint_every,
              "trace": self._job_trace_path(jid)}
        from .owner import OwnerDied
        with self.tel.span("job", id=jid, sig=sig, spec=job["spec"],
                           owner=True, batched=len(followers)):
            try:
                resp = self.owner.request({"kind": "solo",
                                           "member": md})
            except OwnerDied as ex:
                if ex.timed_out:
                    # policy kill: requeueing would livelock (the
                    # re-run hits the same deadline) — the timeout is
                    # the job's verdict
                    self._fail_job(job, followers, str(ex))
                    return
                self.tel.counter("serve.owner_respawns")
                self.tel.event("serve.owner_died", error=str(ex))
                self.log(f"serve: device-owner died mid-job ({ex}); "
                         f"requeued {len(jobs)} job"
                         f"{'s' if len(jobs) != 1 else ''}")
                self._requeue_or_quarantine(
                    jobs, f"requeued after device-owner death: {ex}")
                return
        if resp.get("error"):
            self._fail_job(job, followers, resp["error"])
            return
        summary = resp["summary"]
        sv = summary.setdefault("serve", {})
        sv["cost_estimate"] = job.get("cost_estimate")
        # the owner's own warm registry reports warmth now (ISSUE 19:
        # owner is the default device path, so the warm/cold/resume
        # counters must not go dark when work leaves the daemon)
        warm_engine = bool(sv.get("warm_engine"))
        resumed = bool(sv.get("resumed_from_checkpoint"))
        if warm_engine:
            self.tel.counter("serve.warm_hits")
        else:
            self.tel.counter("serve.cold_runs")
            if resumed:
                self.tel.counter("serve.ckpt_resumes")
        status = "drained" if resp.get("drained") else "done"
        publish = self._publishable(jobs)
        if not publish:
            return  # stolen mid-run; the thief's re-run answers
        for j in publish:
            self.q.save_result(j["id"], summary)
            self.q.mark(j["id"], status, finished_at=time.time(),
                        ok=resp["ok"], distinct=resp["distinct"],
                        generated=resp["generated"],
                        warm_engine=warm_engine, device_owner=True,
                        resumed_from_checkpoint=resumed,
                        daemon=self.daemon_id,
                        batch_leader=jid if j is not job else None)
        self._register_done_artifact([j["id"] for j in publish],
                                     summary)
        if status == "drained":
            self.tel.counter("serve.jobs_drained", len(publish))
            self.log(f"serve: job {jid} drained in the device owner "
                     f"(checkpointed; will resume next life)")
        else:
            self.tel.counter("serve.jobs_done", len(publish))
            self._jobs_done += len(publish)
            self.log(f"serve: job {jid} done in the device owner "
                     f"({time.time() - t0:.2f}s, ok={resp['ok']}, "
                     f"{resp['distinct']} distinct, "
                     f"warm={warm_engine}, resumed={resumed})")

    # ---- cross-model vmapped batches (ISSUE 13) ------------------------
    def _run_vbatch(self, job: Dict[str, Any],
                    followers: List[Dict[str, Any]],
                    xmembers: List[Dict[str, Any]]) -> None:
        """Run one layout-compat cohort — the leader (+ its exact-sig
        followers) and every claimed cross-model member — through ONE
        vmapped device program.  Per-job artifacts and statuses are
        written exactly like solo runs; on any cohort-level failure the
        cross-model members are REQUEUED and the leader falls back to
        the solo path, so batching can delay a job but never lose or
        corrupt one."""
        t0 = time.time()
        jid = job["id"]
        # one member per DISTINCT signature; duplicates share a result
        groups: Dict[str, List[Dict[str, Any]]] = \
            {job["sig"]: [job] + followers}
        order = [job["sig"]]
        for oj in xmembers:
            if oj["sig"] not in groups:
                groups[oj["sig"]] = []
                order.append(oj["sig"])
            groups[oj["sig"]].append(oj)
        # BATCH-SCOPED CHECKPOINTS (ISSUE 19 tentpole 4): each member
        # checkpoints under a bsig-scoped key (the merged batch layout
        # has its own lane plan — the solo `ckpt/<sig>.ck` would refuse
        # to resume it), so a drained or stolen cohort RE-FORMS from
        # per-member checkpoints instead of restarting solo
        bsig = job.get("bsig") or "solo"
        desc = [{"spec": groups[s][0]["spec"],
                 "cfg": groups[s][0].get("cfg"),
                 "options": groups[s][0].get("options"),
                 "sig": s, "bsig": job.get("bsig"),
                 "jids": [j["id"] for j in groups[s]],
                 "checkpoint": self.q.batch_ckpt_path(bsig, s),
                 "checkpoint_every": self.checkpoint_every,
                 "trace": self._job_trace_path(groups[s][0]["id"])}
                for s in order]
        for s in order:
            for j in groups[s]:
                self.q.mark(j["id"], "running", started_at=t0,
                            daemon=self.daemon_id,
                            batch_leader=jid
                            if j["id"] != jid else None,
                            bsig=job.get("bsig"))
        self.tel.counter("serve.vbatch_jobs",
                         sum(len(groups[s]) for s in order))
        self._update_gauges()
        from .. import faults
        faults.kill_self("daemon_kill", job=jid, kind="vbatch",
                         spec=os.path.basename(job["spec"]))

        def _requeue(members: List[Dict[str, Any]], note: str,
                     strip_bsig: bool = False) -> None:
            # strip_bsig: a DETERMINISTIC batch failure (compat refused
            # at build) must not re-form the same failing cohort — the
            # retry runs solo; transient failures (owner death) keep
            # the bsig so the retry can batch again
            with self._cv:
                for j in members:
                    self.q.mark(j["id"], "queued", requeue_note=note,
                                bsig=None if strip_bsig
                                else j.get("bsig"))
                    self.q.release(j["id"], self.daemon_id)
                    self._running.pop(j["id"], None)
                    self._pending.append(j["id"])
                self._cv.notify_all()

        resp = None
        with self.tel.span("vbatch", id=jid, bsig=job.get("bsig"),
                           members=len(order),
                           jobs=sum(len(groups[s]) for s in order)):
            if self.owner is not None:
                from .owner import OwnerDied
                try:
                    resp = self.owner.request(
                        {"kind": "vbatch", "members": desc})
                except OwnerDied as ex:
                    if ex.timed_out:
                        # policy kill, not a death: requeueing would
                        # re-run the identical cohort into the same
                        # deadline forever — fail with the named knob
                        self._fail_job(job, followers + xmembers,
                                       str(ex))
                        return
                    # the owner process died with the cohort in flight:
                    # nothing was written, so every job simply requeues
                    # and the next device job respawns the owner
                    self.tel.counter("serve.owner_respawns")
                    self.tel.event("serve.owner_died", error=str(ex))
                    self.log(f"serve: device-owner died mid-batch "
                             f"({ex}); requeued "
                             f"{sum(len(groups[s]) for s in order)} "
                             f"jobs")
                    # an owner DEATH spends the cross-daemon retry
                    # budget; members keep their bsig so the cohort
                    # re-forms and resumes its batch checkpoints
                    self._requeue_or_quarantine(
                        [j for s in order for j in groups[s]],
                        f"requeued after device-owner death: {ex}")
                    return
            else:
                from .owner import run_vbatch
                resp = run_vbatch(desc)

        if resp.get("error"):
            # owner-side cohort-level failure (not a death — the child
            # answered): deterministic, so requeueing would loop; the
            # REAL error lands on every job
            self._fail_job(job, followers + xmembers, resp["error"])
            return
        if resp.get("incompatible"):
            # parse-time bsig said compatible but the build disagreed
            # (e.g. a lifted constant reached a static-only position):
            # cross-model members requeue solo, the leader group runs
            # the ordinary path
            self.tel.counter("serve.batch_incompatible")
            self.log(f"serve: batch {job.get('bsig')} fell back to "
                     f"solo runs ({resp['incompatible']})")
            _requeue(xmembers, "requeued after batch-compat fallback: "
                               + str(resp["incompatible"]),
                     strip_bsig=True)
            self._run_batch(job, followers)
            return

        occupancy = int(resp.get("occupancy") or 0)
        self.tel.gauge("serve.batch_occupancy", occupancy)
        # MEASURED by the batch engine (1 by construction today; a
        # future in-cohort rebuild would surface here, not be papered
        # over by a constant)
        self.tel.gauge("serve.batch_compiles",
                       int(resp.get("engine_builds") or 1))
        done = failed = drained_n = 0
        for md, mres in zip(desc, resp["members"]):
            jobs = groups[md["sig"]]
            if mres.get("retry_solo"):
                # engine-level abort solo runs recover from (adaptive
                # relayout): requeue WITH BATCHING STRIPPED so the
                # retry cannot re-form the same failing cohort
                self.tel.counter("serve.batch_solo_retries", len(jobs))
                self.log(f"serve: batch member {md['jids'][0]} "
                         f"requeued for solo retry "
                         f"({mres['retry_solo']})")
                with self._cv:
                    for j in jobs:
                        self.q.mark(j["id"], "queued", bsig=None,
                                    requeue_note="solo retry: "
                                    + str(mres["retry_solo"]))
                        self._running.pop(j["id"], None)
                        self._pending.append(j["id"])
                    self._cv.notify_all()
                continue
            if mres.get("error"):
                self.tel.counter("serve.jobs_failed", len(jobs))
                self._jobs_failed += len(jobs)
                self.tel.event("serve.job_failed", id=md["jids"][0],
                               error=mres["error"])
                for j in jobs:
                    self.q.mark(j["id"], "failed", error=mres["error"],
                                finished_at=time.time(),
                                batch_leader=jid
                                if j["id"] != jid else None)
                failed += len(jobs)
                continue
            summary = mres["summary"]
            sv = summary.setdefault("serve", {})
            sv["cost_estimate"] = jobs[0].get("cost_estimate")
            resumed = bool(sv.get("resumed_from_checkpoint"))
            status = "drained" if mres.get("drained") else "done"
            publish = self._publishable(jobs)
            for j in publish:
                self.q.save_result(j["id"], summary)
                self.q.mark(j["id"], status, finished_at=time.time(),
                            ok=mres["ok"], distinct=mres["distinct"],
                            generated=mres["generated"],
                            warm_engine=False,
                            resumed_from_checkpoint=resumed,
                            batch_occupancy=occupancy,
                            daemon=self.daemon_id,
                            batch_leader=jid
                            if j["id"] != jid else None)
            self._register_done_artifact([j["id"] for j in publish],
                                         summary)
            if status == "drained":
                drained_n += len(publish)
            else:
                done += len(publish)
                self._jobs_done += len(publish)
        if drained_n:
            self.tel.counter("serve.jobs_drained", drained_n)
        if done:
            self.tel.counter("serve.jobs_done", done)
        self.log(f"serve: vbatch {jid} done in "
                 f"{time.time() - t0:.2f}s (members={len(order)}, "
                 f"occupancy={occupancy}, done={done}, "
                 f"failed={failed}, drained={drained_n})")

    # ---- introspection ------------------------------------------------
    def _update_gauges(self) -> None:
        with self._cv:
            depth = len(self._pending)
            running = len(self._running)
        self.tel.gauge("serve.queue_depth", depth)
        self.tel.gauge("serve.running", running)
        self.tel.gauge("serve.warm_sessions", len(self.warm))
        self.tel.gauge("serve.workers", self.n_workers)
        self.tel.gauge("serve.draining", self._draining)
        # serve.fleet gauges (ISSUE 19; schema note in obs/schema.py)
        self.tel.gauge("serve.fleet_daemons", self._fleet_size)
        self.tel.gauge("serve.leases_held", running)

    def job_events(self, jid: str) -> Optional[list]:
        """Recent trace events for one job, readable MID-RUN: the live
        ring buffer for in-daemon jobs, the trace-file tail for
        owner-process jobs, the retained ring for recently finished
        ones.  None when nothing is known about the job."""
        with self._cv:
            jt = self._job_tels.get(jid)
            done = self._done_events.get(jid)
        if jt is not None:
            return jt.recent_events()
        if done is not None:
            return list(done)
        try:  # owner-process jobs: their Telemetry streams to the
            # spool trace file, flushed per event — tail it
            with open(self._job_trace_path(jid),
                      encoding="utf-8") as fh:
                lines = fh.readlines()[-256:]
            out = []
            for ln in lines:
                try:
                    out.append(json.loads(ln))
                except ValueError:
                    pass  # torn final line of a live writer
            return out
        except OSError:
            return None

    def metrics_text(self) -> str:
        """The GET /metrics body: Prometheus text exposition 0.0.4 over
        the fleet counters/gauges plus per-running-job series labeled
        {job="<id>"} (name grammar in obs/schema.py).  Built from
        short-critical-section snapshots — never blocks job threads."""
        self._update_gauges()
        fleet = self.tel.metrics_snapshot()
        with self._cv:
            jobs = dict(self._job_tels)
        # family name -> (type, [(label_str, value)])
        fams: Dict[str, Tuple[str, list]] = {}

        def add(name, value, typ="gauge", jid=None, site=None):
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                return
            fam = fams.setdefault(obs.prom_name(name), (typ, []))
            if jid is None:
                lbl = ""
            else:
                pairs = ['job="%s"' % str(jid).replace('"', "'")]
                if site is not None:
                    pairs.append('site="%s"'
                                 % str(site).replace('"', "'"))
                lbl = "{%s}" % ",".join(pairs)
            fam[1].append((lbl, value))

        def add_prof(jid, jt):
            # ISSUE 17: per-dispatch-site gauges plus the HBM model's
            # peak, straight off the job recorder's always-on profiler
            prof = getattr(jt, "prof", None)
            if prof is None:
                return
            for sname, st in sorted(prof.sites.items()):
                add("prof.site_dispatches", st.dispatches,
                    jid=jid, site=sname)
                if st.wall_s:
                    add("prof.site_wall_s", round(st.wall_s, 6),
                        jid=jid, site=sname)
            peak = prof.hbm_peak_bytes
            if peak:
                add("hbm.peak_bytes", peak, jid=jid)

        for name, v in fleet["counters"].items():
            add(name, v, "counter")
        for name, v in fleet["gauges"].items():
            add(name, v, "gauge")
        now = time.time()
        seen_tels = set()
        for jid, jt in sorted(jobs.items()):
            if id(jt) in seen_tels:
                continue  # followers share the leader's recorder
            seen_tels.add(id(jt))
            add("job.running", 1, jid=jid)
            snap = jt.metrics_snapshot()
            for gname, gval in snap["gauges"].items():
                add(gname, gval, jid=jid)
            if snap["levels"]:
                add("job.levels", len(snap["levels"]), jid=jid)
            gen = sum(lv.get("generated") or 0
                      for lv in snap["levels"])
            wall = max(now - jt.t_start, 1e-9)
            if gen:
                add("job.states_per_sec", round(gen / wall, 3),
                    jid=jid)
            pe = jt.progress_est
            if pe is not None:
                ps = pe.snapshot()
                add("job.progress_distinct", ps["distinct"], jid=jid)
                if ps["eta_s"] is not None:
                    add("job.progress_eta_s", ps["eta_s"], jid=jid)
            add_prof(jid, jt)
        # completed jobs linger for JAXMC_METRICS_JOB_TTL seconds so a
        # scraper on a coarse interval still sees the final series of a
        # short job (ISSUE 17 satellite: bounded by TTL, not forever)
        mnow = self._metrics_clock()
        with self._cv:
            for jid in [j for j, (t, _jt) in self._done_series.items()
                        if mnow - t > self._job_ttl]:
                del self._done_series[jid]
            done = [(jid, jt) for jid, (t, jt)
                    in self._done_series.items()
                    if jid not in jobs]
        for jid, jt in done:
            add("job.running", 0, jid=jid)
            snap = jt.metrics_snapshot()
            for gname, gval in snap["gauges"].items():
                add(gname, gval, jid=jid)
            if snap["levels"]:
                add("job.levels", len(snap["levels"]), jid=jid)
            add_prof(jid, jt)
        lines = []
        for name in sorted(fams):
            typ, samples = fams[name]
            lines.append(f"# TYPE {name} {typ}")
            for lbl, value in samples:
                lines.append(f"{name}{lbl} {value}")
        return "\n".join(lines) + "\n"

    def status(self) -> Dict[str, Any]:
        self._update_gauges()
        # ONE snapshot hold for every shared map (ISSUE 19 satellite):
        # the /metrics TTL pruner deletes done-job series under _cv at
        # scrape time, so rendering the per-job progress block must
        # work from copies taken in the same critical section — never
        # iterate a live map the pruner can mutate mid-iteration
        with self._cv:
            pending = list(self._pending)
            running = {jid: s for jid, (s, _t)
                       in self._running.items()}
            warm = {s: w["session"] for s, w in self.warm.items()}
            job_tels = dict(self._job_tels)
            done_series = [(jid, jt) for jid, (_t, jt)
                           in self._done_series.items()]
        # live per-job search progress (ISSUE 16): fraction/ETA from
        # the job's estimator, `unbounded` when analyze offered none —
        # recently-done jobs keep their final snapshot until the TTL
        # prunes them
        progress = {}
        for jid, jt in job_tels.items():
            pe = jt.progress_est
            if pe is not None:
                progress[jid] = pe.snapshot()
        for jid, jt in done_series:
            pe = jt.progress_est
            if jid not in progress and pe is not None:
                progress[jid] = dict(pe.snapshot(), done=True)
        return {
            "progress": progress,
            "spool": self.q.root,
            "queue_depth": len(pending),
            "pending": pending,
            "running": running,
            "fleet": {"daemon_id": self.daemon_id,
                      "daemons": self._fleet_size,
                      "lease_ttl": self.lease_ttl,
                      "lease_renew": self.lease_renew,
                      "job_retries": self.job_retries},
            "quarantined": len(self.q.quarantined()),
            "batch_enabled": self.batch_enabled,
            "device_owner_pid": self.owner.pid
            if self.owner is not None else None,
            "warm_sessions": {
                s: sess.describe() for s, sess in warm.items()},
            "workers": self.n_workers,
            "draining": self._draining,
            "jobs_done": self._jobs_done,
            "jobs_failed": self._jobs_failed,
            "counters": dict(self.tel.counters),
            "gauges": dict(self.tel.gauges),
        }
