r"""The serve daemon: a bounded worker pool over the durable spool,
warm CheckSessions, and the fleet telemetry dashboard.

Life of a job (see serve/__init__.py for the system view):

  submit   POST /jobs validates the payload (serve/protocol.py), stamps
           the job SIGNATURE, persists the record (serve/queue.py) and
           wakes a worker — 503 once a drain began;
  batch    the worker that pops a job also claims every QUEUED job with
           the same signature: one engine run answers all of them (for
           the resident engine that is literally one batched kernel
           dispatch sequence), counter `serve.batched_jobs`;
  warm     a signature seen before reuses its WARM CheckSession — the
           already-compiled engine — and resumes the signature-keyed
           checkpoint the previous run finalized: the repeat submission
           replays the stored verdict with zero in-window recompiles
           (`serve.warm_hits`); a cold daemon with a spool checkpoint
           from a previous life still resumes it (`serve.ckpt_resumes`)
           and re-pays only the compile, which the persistent compile
           cache + capacity profile make a disk hit;
  drain    SIGTERM / POST /drain: no new jobs, in-flight engines
           checkpoint at their next safe boundary (jaxmc/drain.py),
           their jobs park as `drained` (re-queued by the next daemon
           life's recover()), workers join, spans close, the watchdog
           stops, the fleet metrics artifact is written.

Telemetry: the daemon owns one fleet Telemetry (per-job `job` spans,
queue-depth/warm-hit/batched-jobs gauges, watchdog heartbeats); each
job ALSO records into a private per-thread recorder (obs.use_local) so
its own spans/levels/counters land in `<spool>/results/<id>.json` as a
normal jaxmc.metrics/2 artifact — `python -m jaxmc.obs report/diff`
works on serve results unchanged.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import drain, obs
from ..session import CheckSession
from .protocol import BadJob, build_config, job_signature
from .queue import JobQueue


class ServeDaemon:
    def __init__(self, spool: str, host: str = "127.0.0.1",
                 port: int = 0, workers: int = 2,
                 trace: Optional[str] = None,
                 metrics_out: Optional[str] = None,
                 quiet: bool = False,
                 checkpoint_every: float = 60.0):
        # a fresh daemon re-arms the drain flag: an in-process restart
        # (tests, the smoke gate) must not inherit the last life's drain
        drain.clear()
        self.q = JobQueue(spool)
        self.tel = obs.Telemetry(
            trace_path=trace,
            meta={"command": "serve", "spool": self.q.root,
                  "env": obs.environment_meta()})
        self.log = obs.Logger(self.tel, quiet=quiet)
        self.wd = obs.Watchdog(self.tel)
        self.metrics_out = metrics_out
        self.host = host
        self.port = port
        self.n_workers = max(1, int(workers))
        self.checkpoint_every = checkpoint_every
        # sig -> {"session": CheckSession, "completed": bool} — the warm
        # kernel registry; "completed" gates checkpoint-replay reuse.
        # Mutated ONLY under _cv (status() snapshots under it too), and
        # each signature additionally serializes its RUNS through
        # _sig_lock: a CheckSession's engine is single-flight state, so
        # two same-signature jobs that dodged batching must not drive
        # it concurrently.
        # BOUNDED LRU (ISSUE 10 satellite, ROADMAP item 3): a
        # long-lived fleet daemon otherwise pins one compiled engine
        # per signature forever.  JAXMC_SERVE_WARM_MAX (default a
        # generous 32) caps the registry; the least-recently-used idle
        # signature is evicted (`serve.evictions` + a `serve.evicted`
        # event), and a re-submission after eviction falls back to the
        # FINAL-CHECKPOINT resume path — bit-identical answer, just
        # cold (the spool checkpoint and the persisted capacity
        # profile survive eviction).
        try:
            self.warm_max = max(1, int(os.environ.get(
                "JAXMC_SERVE_WARM_MAX", "32") or 32))
        except ValueError:
            self.warm_max = 32
        self.warm: Dict[str, Dict[str, Any]] = {}
        self._sig_locks: Dict[str, threading.Lock] = {}
        self._cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._running: Dict[str, str] = {}  # jid -> sig
        self._draining = False
        self._drain_reason: Optional[str] = None
        self._workers: List[threading.Thread] = []
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self._jobs_done = 0
        self._jobs_failed = 0

    # ---- lifecycle ----------------------------------------------------
    def start(self) -> "ServeDaemon":
        requeued = self.q.recover()
        if requeued:
            self.log(f"serve: requeued {requeued} interrupted job"
                     f"{'s' if requeued != 1 else ''} from the spool")
            self.tel.counter("serve.requeued_on_start", requeued)
        with self._cv:
            for job in sorted(self.q.queued(), key=lambda j: j["id"]):
                self._pending.append(job["id"])
        self._start_http()
        self.q.stamp(host=self.host, port=self.port, pid=os.getpid(),
                     workers=self.n_workers, status="serving")
        for wi in range(self.n_workers):
            t = threading.Thread(target=self._worker_loop, args=(wi,),
                                 name=f"jaxmc-serve-w{wi}", daemon=True)
            t.start()
            self._workers.append(t)
        self.wd.start()
        self._update_gauges()
        self.log(f"serve: listening on http://{self.host}:{self.port} "
                 f"(spool {self.q.root}, {self.n_workers} worker"
                 f"{'s' if self.n_workers != 1 else ''})")
        return self

    def _start_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *a):  # quiet the default stderr
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n).decode()) \
                        if n else {}
                except (ValueError, OSError):
                    return self._json(400, {"error": "bad JSON body"})
                if self.path == "/jobs":
                    try:
                        job = daemon.submit(body)
                    except BadJob as ex:
                        return self._json(400, {"error": str(ex)})
                    except RuntimeError as ex:  # draining
                        return self._json(503, {"error": str(ex)})
                    return self._json(200, job)
                if self.path == "/drain":
                    daemon.initiate_drain("POST /drain")
                    return self._json(200, {"draining": True})
                return self._json(404, {"error": f"no route {self.path}"})

            def do_GET(self):
                if self.path == "/status":
                    return self._json(200, daemon.status())
                if self.path == "/jobs":
                    return self._json(200,
                                      {"jobs": daemon.q.list_jobs()})
                if self.path.startswith("/jobs/"):
                    parts = self.path.split("/")
                    jid = parts[2] if len(parts) > 2 else ""
                    if len(parts) == 4 and parts[3] == "result":
                        res = daemon.q.load_result(jid)
                        if res is None:
                            return self._json(
                                404, {"error": f"no result for {jid}"})
                        return self._json(200, res)
                    job = daemon.q.load(jid)
                    if job is None:
                        return self._json(404,
                                          {"error": f"no job {jid}"})
                    if job.get("status") == "done":
                        res = daemon.q.load_result(jid)
                        if res is not None:
                            job = dict(job, result=res.get("result"),
                                       serve=res.get("serve"))
                    return self._json(200, job)
                return self._json(404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="jaxmc-serve-http",
            daemon=True)
        self._http_thread.start()

    def serve_forever(self) -> int:
        """Block until a drain completes; returns the process exit code
        (0 — a drained daemon is a clean daemon)."""
        try:
            while not self._draining:
                time.sleep(0.2)
                self._update_gauges()
        except KeyboardInterrupt:
            self.initiate_drain("KeyboardInterrupt")
        self.shutdown()
        return 0

    def initiate_drain(self, reason: str) -> None:
        """Begin the graceful drain (idempotent): refuse new jobs, ask
        every in-flight engine to checkpoint and stop (jaxmc/drain.py),
        wake idle workers so they exit."""
        with self._cv:
            if self._draining:
                return
            self._draining = True
            self._drain_reason = reason
            self._cv.notify_all()
        drain.request(f"serve drain: {reason}")
        self.tel.event("serve.drain", reason=reason)
        self.log(f"serve: draining ({reason}) — in-flight jobs will "
                 f"checkpoint and requeue")

    def shutdown(self) -> None:
        """Complete the drain: join workers (their engines return at
        the next safe boundary), stop HTTP, persist the fleet metrics,
        close everything.  No orphan workers, no open spans."""
        if not self._draining:
            self.initiate_drain("shutdown()")
        for t in self._workers:
            t.join(timeout=120.0)
        alive = [t.name for t in self._workers if t.is_alive()]
        if alive:  # never expected: engines poll drain at every level
            self.log(f"serve: WARNING: workers still alive at shutdown: "
                     f"{alive}")
        self._workers = []
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        self.wd.stop()
        self._update_gauges()
        self.q.stamp(host=self.host, port=self.port, pid=os.getpid(),
                     workers=self.n_workers, status="stopped",
                     drain_reason=self._drain_reason)
        if self.metrics_out:
            self.tel.write_metrics(
                self.metrics_out,
                result={"ok": True, "distinct": 0, "generated": 0,
                        "diameter": 0, "truncated": False,
                        "jobs_done": self._jobs_done,
                        "jobs_failed": self._jobs_failed,
                        "drain_reason": self._drain_reason})
        self.tel.close()
        # re-arm the process-global drain flag: every engine in this
        # daemon has returned, and an in-process successor daemon (the
        # smoke gate, restart tests) must not inherit a stale request
        drain.clear()

    # ---- submission ---------------------------------------------------
    def submit(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        if self._draining:
            raise RuntimeError("daemon is draining; resubmit to the "
                               "next daemon life (the spool persists)")
        cfg = build_config(payload.get("spec"), payload.get("cfg"),
                           payload.get("options"))
        # submit-time static analysis (ISSUE 9): a statically-broken
        # spec/cfg pair (cfg names an undefined invariant, unassigned
        # CONSTANTs, unparseable inputs — the linter's error-severity
        # classes) is rejected HERE, before it occupies a worker or
        # enters the durable spool; the 400 payload carries the
        # diagnostics.  JAXMC_SERVE_ANALYZE=0 opts out.
        if os.environ.get("JAXMC_SERVE_ANALYZE", "1").strip().lower() \
                not in ("0", "off", "no", "false"):
            from ..analyze.lint import errors, lint_pair
            errs = errors(lint_pair(cfg.spec, cfg.cfg,
                                    tuple(cfg.include or ()),
                                    semantic=False))
            if errs:
                self.tel.counter("serve.jobs_rejected")
                self.tel.event("serve.job_rejected",
                               spec=cfg.spec,
                               codes=[d.code for d in errs])
                raise BadJob(
                    "statically broken job rejected by the analyzer: "
                    + "; ".join(d.render() for d in errs[:5]))
        sig = job_signature(cfg)
        job = self.q.new_job(cfg.spec, cfg.cfg, payload.get("options"),
                             sig)
        self.tel.counter("serve.jobs_submitted")
        with self._cv:
            self._pending.append(job["id"])
            self._cv.notify()
        self._update_gauges()
        return job

    # ---- workers ------------------------------------------------------
    def _worker_loop(self, wi: int) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._draining:
                    self._cv.wait(0.5)
                if self._draining:
                    return  # queued jobs persist for the next life
                jid = self._pending.popleft()
                job = self.q.load(jid)
                followers: List[Dict[str, Any]] = []
                if job is not None:
                    # BATCH: claim every queued job with this signature
                    # — one engine run answers all of them
                    rest = []
                    for other in self._pending:
                        oj = self.q.load(other)
                        if oj is not None and \
                                oj.get("sig") == job["sig"]:
                            followers.append(oj)
                        else:
                            rest.append(other)
                    self._pending = collections.deque(rest)
                    self._running[jid] = job["sig"]
            if job is None:
                continue
            try:
                self._run_batch(job, followers)
            except Exception as ex:  # noqa: BLE001 — a job failure must
                # never kill the worker; the defect lands on the job
                self._fail_job(job, followers,
                               f"{type(ex).__name__}: {ex}")
            finally:
                with self._cv:
                    self._running.pop(job["id"], None)
                self._update_gauges()

    def _fail_job(self, job, followers, error: str) -> None:
        self.tel.counter("serve.jobs_failed", 1 + len(followers))
        self._jobs_failed += 1 + len(followers)
        self.tel.event("serve.job_failed", id=job["id"], error=error)
        self.log(f"serve: job {job['id']} FAILED: {error}")
        for j in [job] + followers:
            self.q.mark(j["id"], "failed", error=error,
                        finished_at=time.time(),
                        batch_leader=job["id"]
                        if j is not job else None)

    def _sig_lock(self, sig: str) -> threading.Lock:
        with self._cv:
            lk = self._sig_locks.get(sig)
            if lk is None:
                lk = self._sig_locks[sig] = threading.Lock()
            return lk

    def _touch_warm_locked(self, sig: str) -> None:
        """Move `sig` to the registry's most-recently-used end (dicts
        are insertion-ordered; caller holds _cv)."""
        entry = self.warm.pop(sig, None)
        if entry is not None:
            self.warm[sig] = entry

    def _evict_warm_locked(self) -> None:
        """Evict least-recently-used IDLE signatures past warm_max
        (caller holds _cv).  A signature mid-run (claimed in _running
        or its per-sig lock held) is never evicted — the next-oldest
        idle one goes instead."""
        if len(self.warm) <= self.warm_max:
            return
        busy = set(self._running.values())
        for sig in list(self.warm):
            if len(self.warm) <= self.warm_max:
                break
            if sig in busy:
                continue
            lk = self._sig_locks.get(sig)
            if lk is not None and lk.locked():
                continue
            del self.warm[sig]
            self._sig_locks.pop(sig, None)
            self.tel.counter("serve.evictions")
            self.tel.event("serve.evicted", sig=sig)
            self.log(f"serve: evicted warm session {sig[:12]} "
                     f"(LRU, warm_max={self.warm_max}; resubmission "
                     f"resumes its final checkpoint cold)")

    def _revalidate_profile(self, sess: CheckSession, job_tel) -> None:
        """Warm-path consistency check: confirm the DURABLE capacity
        profile still matches the warm engine's layout before trusting
        its caps (counts as a profile hit in the job's artifact; a
        missing/stale profile only means the next cold engine re-learns
        — the warm engine's own caps stay valid)."""
        if sess.layout_sig and sess.model is not None:
            from ..compile.cache import load_capacity_profile
            # profiles are namespaced by backend platform (ISSUE 11):
            # ask the warm engine's descriptor for the variant the
            # profile was saved under
            desc = getattr(sess.engine, "backend_desc", None)
            variant = desc.profile_variant() if desc is not None else ""
            load_capacity_profile(sess.model.module.name,
                                  sess.layout_sig, tel=job_tel,
                                  variant=variant)

    def _run_batch(self, job: Dict[str, Any],
                   followers: List[Dict[str, Any]]) -> None:
        jid, sig = job["id"], job["sig"]
        t0 = time.time()
        cfg = build_config(job["spec"], job.get("cfg"),
                           job.get("options"))
        if cfg.backend == "interp" and not cfg.workers:
            # daemon parallelism comes from the WORKER POOL (several
            # jobs at once), not per-job fork pools: forking from a
            # multithreaded daemon risks classic fork+locks hangs, so
            # interp jobs default to the serial engine unless the
            # submission explicitly asks for a worker count (note both
            # None and 0 mean "auto" on the CLI surface — neither may
            # reach default_workers() here)
            cfg.workers = 1
        ck = self.q.ckpt_path(sig)
        cfg.checkpoint = ck
        cfg.checkpoint_every = self.checkpoint_every
        cfg.final_checkpoint = True
        job_tel = obs.Telemetry(meta={
            "command": "serve.job", "job": jid, "sig": sig,
            "backend": cfg.backend, "spec": job["spec"],
            "cfg": job.get("cfg"), "env": obs.environment_meta()})
        for j in [job] + followers:
            self.q.mark(j["id"], "running", started_at=t0,
                        batch_leader=jid if j is not job else None)
        if followers:
            self.tel.counter("serve.batched_jobs", len(followers))
        self._update_gauges()

        with self._cv:
            warm = self.warm.get(sig)
            if warm is not None:
                self._touch_warm_locked(sig)
        warm_engine = resumed = False
        with self._sig_lock(sig), obs.use_local(job_tel), \
                self.tel.span("job", id=jid, sig=sig, spec=job["spec"],
                              backend=cfg.backend,
                              batched=len(followers)):
            if warm is not None and warm.get("completed") and \
                    os.path.exists(ck):
                # WARM: the already-compiled engine replays the
                # finalized checkpoint — zero recompiles, instant answer
                warm_engine = resumed = True
                self.tel.counter("serve.warm_hits")
                sess = warm["session"]
                # rebind the session's telemetry channel to THIS job's
                # recorder (it was constructed with the cold job's, long
                # closed): the warm artifact must carry its own search
                # span like any other jaxmc.metrics summary
                sess.tel = job_tel
                sess.log = obs.Logger(job_tel, quiet=True)
                self._revalidate_profile(sess, job_tel)
                res = sess.explore(resume_from=ck, checkpoint_path=ck,
                                   final_checkpoint=True)
            else:
                self.tel.counter("serve.cold_runs")
                if os.path.exists(ck):
                    # a previous daemon life checkpointed this signature
                    # (periodic, drain, or final): resume incrementally
                    cfg.resume = ck
                    resumed = True
                    self.tel.counter("serve.ckpt_resumes")
                sess = CheckSession(cfg, tel=job_tel,
                                    log=obs.Logger(job_tel, quiet=True))
                if sess.parse() == "assumes":
                    raise BadJob(
                        "assumes-mode specs (no behavior spec) are not "
                        "servable; run them via `python -m jaxmc check`")
                try:
                    sess.compile()
                    res = sess.explore()
                except (RuntimeError, OSError, MemoryError,
                        ConnectionError) as ex:
                    if cfg.backend == "interp":
                        raise
                    # the CLI's device->CPU fallback, same policy
                    # (session.demote_to_cpu is the shared path)
                    res = sess.demote_to_cpu(ex)
                with self._cv:
                    self.warm[sig] = {"session": sess,
                                      "completed": False}
                    self._evict_warm_locked()

        drained = bool(getattr(res, "drained", False))
        completed = res.ok and not res.truncated and not drained
        with self._cv:
            if sig in self.warm:
                # checkpoint-replay reuse only for COMPLETED searches
                # (the final checkpoint exists exactly then); other
                # outcomes still keep the warm kernels for the next
                # submission
                self.warm[sig]["completed"] = completed or \
                    self.warm[sig].get("completed", False)

        # the job artifact: a normal jaxmc.metrics/2 summary + the
        # serve block (obs/schema.py PR-7 notes)
        window_recompiles = sum(1 for lv in job_tel.levels
                                if lv.get("fresh_compile"))
        wall = time.time() - t0
        result_block: Dict[str, Any] = {
            "ok": res.ok, "distinct": res.distinct,
            "generated": res.generated, "diameter": res.diameter,
            "truncated": bool(res.truncated),
            "wall_s": round(res.wall_s, 6),
            "warnings": list(getattr(res, "warnings", []))}
        if drained:
            result_block["drained"] = True
        if res.violation is not None:
            from ..engine.explore import format_trace
            result_block["violation"] = {"kind": res.violation.kind,
                                         "name": res.violation.name}
            result_block["trace"] = format_trace(res.violation)
        summary = job_tel.summary(result=result_block)
        summary["backend"] = cfg.backend
        summary["spec"] = job["spec"]
        summary["serve"] = {
            "sig": sig, "warm_engine": warm_engine,
            "resumed_from_checkpoint": resumed,
            "window_recompiles": window_recompiles,
            "profile_hits": job_tel.counters.get("profile.hits", 0),
            "persistent_cache_hits": job_tel.counters.get(
                "compile.persistent_cache_hits", 0),
            "batched_with": [f["id"] for f in followers],
            "job_wall_s": round(wall, 6),
        }
        job_tel.close()

        status = "drained" if drained else "done"
        for j in [job] + followers:
            self.q.save_result(j["id"], summary)
            self.q.mark(j["id"], status, finished_at=time.time(),
                        ok=res.ok, distinct=res.distinct,
                        generated=res.generated,
                        warm_engine=warm_engine,
                        resumed_from_checkpoint=resumed,
                        window_recompiles=window_recompiles,
                        batch_leader=jid if j is not job else None)
        if drained:
            self.tel.counter("serve.jobs_drained", 1 + len(followers))
            self.log(f"serve: job {jid} drained at a safe boundary "
                     f"(checkpointed; will resume next life)")
        else:
            self.tel.counter("serve.jobs_done", 1 + len(followers))
            self._jobs_done += 1 + len(followers)
            self.log(f"serve: job {jid} done in {wall:.2f}s "
                     f"(ok={res.ok}, {res.distinct} distinct, "
                     f"warm={warm_engine}, resumed={resumed}, "
                     f"batched={len(followers)})")

    # ---- introspection ------------------------------------------------
    def _update_gauges(self) -> None:
        with self._cv:
            depth = len(self._pending)
            running = len(self._running)
        self.tel.gauge("serve.queue_depth", depth)
        self.tel.gauge("serve.running", running)
        self.tel.gauge("serve.warm_sessions", len(self.warm))
        self.tel.gauge("serve.workers", self.n_workers)
        self.tel.gauge("serve.draining", self._draining)

    def status(self) -> Dict[str, Any]:
        self._update_gauges()
        with self._cv:
            pending = list(self._pending)
            running = dict(self._running)
            warm = {s: w["session"] for s, w in self.warm.items()}
        return {
            "spool": self.q.root,
            "queue_depth": len(pending),
            "pending": pending,
            "running": running,
            "warm_sessions": {
                s: sess.describe() for s, sess in warm.items()},
            "workers": self.n_workers,
            "draining": self._draining,
            "jobs_done": self._jobs_done,
            "jobs_failed": self._jobs_failed,
            "counters": dict(self.tel.counters),
            "gauges": dict(self.tel.gauges),
        }
