r"""The serve daemon's durable on-disk job queue (the spool).

Layout under one root directory:

    <spool>/serve.json           live-daemon stamp {host, port, pid, ...}
    <spool>/jobs/<id>.json       one job record per file (atomic writes)
    <spool>/results/<id>.json    the job's jaxmc.metrics/2 artifact
    <spool>/ckpt/<sig>.ck        checkpoints, keyed by job SIGNATURE so
                                 identical jobs share one resume ladder
                                 (serve/protocol.py defines signatures)

Durability contract: every mutation is a whole-file atomic write
(tmp + os.replace, the obs.write_json_atomic pattern), so a SIGKILLed
daemon leaves a readable spool.  `recover()` runs at daemon start:
jobs stuck in `running` (the daemon died mid-job) and jobs a drain
parked as `drained` go back to `queued` — their signature-keyed
checkpoint (periodic, drain, or final) lets the next run resume
instead of re-exploring.  Job IDs are monotonic per spool
(`<spool>/.seq`, under an O_EXCL-free fcntl lock) so queue order
survives restarts and sorts lexicographically.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ..obs import write_json_atomic


class JobQueue:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        self.results_dir = os.path.join(self.root, "results")
        self.ckpt_dir = os.path.join(self.root, "ckpt")
        for d in (self.jobs_dir, self.results_dir, self.ckpt_dir):
            os.makedirs(d, exist_ok=True)

    # ---- ids ----------------------------------------------------------
    def _next_id(self) -> str:
        """Monotonic job id, crash-safe across daemon restarts: the
        counter file is read-modify-written under an exclusive flock."""
        seq_path = os.path.join(self.root, ".seq")
        fd = os.open(seq_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            try:
                import fcntl
                fcntl.flock(fd, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # single-daemon spools stay correct without it
            raw = os.read(fd, 32)
            n = int(raw) if raw.strip() else 0
            n += 1
            os.lseek(fd, 0, os.SEEK_SET)
            os.ftruncate(fd, 0)
            os.write(fd, str(n).encode())
            return f"j{n:08d}"
        finally:
            os.close(fd)

    # ---- job records --------------------------------------------------
    def job_path(self, jid: str) -> str:
        return os.path.join(self.jobs_dir, f"{jid}.json")

    def result_path(self, jid: str) -> str:
        return os.path.join(self.results_dir, f"{jid}.json")

    def ckpt_path(self, sig: str) -> str:
        return os.path.join(self.ckpt_dir, f"{sig}.ck")

    def new_job(self, spec: str, cfg: Optional[str], options: Dict,
                sig: str, **extra) -> Dict[str, Any]:
        """`extra` carries scheduler metadata (ISSUE 13): `bsig` (the
        layout-compat batch class), `cost_estimate` (analyze's
        state-space estimate) and `fast_lane` — all optional and
        omitted when absent, so old spools read unchanged."""
        job = {
            "id": self._next_id(), "sig": sig, "status": "queued",
            "submitted_at": time.time(), "spec": spec, "cfg": cfg,
            "options": dict(options or {}),
        }
        job.update({k: v for k, v in extra.items() if v is not None})
        self.save(job)
        return job

    def save(self, job: Dict[str, Any]) -> None:
        write_json_atomic(self.job_path(job["id"]), job)

    def load(self, jid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.job_path(jid), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def mark(self, jid: str, status: str, **fields) -> Dict[str, Any]:
        job = self.load(jid) or {"id": jid}
        job["status"] = status
        job.update(fields)
        self.save(job)
        return job

    def list_jobs(self) -> List[Dict[str, Any]]:
        out = []
        try:
            names = sorted(os.listdir(self.jobs_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            j = self.load(name[:-len(".json")])
            if j is not None:
                out.append(j)
        return out

    def queued(self) -> List[Dict[str, Any]]:
        return [j for j in self.list_jobs() if j.get("status") == "queued"]

    # ---- results ------------------------------------------------------
    def save_result(self, jid: str, summary: Dict[str, Any]) -> None:
        write_json_atomic(self.result_path(jid), summary)

    def load_result(self, jid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.result_path(jid), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    # ---- restart recovery ---------------------------------------------
    def recover(self) -> int:
        """Re-queue jobs the previous daemon life left in flight:
        `running` (it died mid-job) and `drained` (it checkpointed and
        parked them on SIGTERM).  Returns the number re-queued.  The
        signature-keyed checkpoint, when one exists, makes the re-run
        incremental rather than from-scratch."""
        n = 0
        for job in self.list_jobs():
            if job.get("status") in ("running", "drained"):
                note = ("requeued after daemon restart"
                        if job["status"] == "running"
                        else "requeued after drain")
                self.mark(job["id"], "queued", requeue_note=note)
                n += 1
        return n

    # ---- the live-daemon stamp ----------------------------------------
    def stamp(self, **info) -> None:
        write_json_atomic(os.path.join(self.root, "serve.json"),
                          dict(info, stamped_at=time.time()))

    def read_stamp(self) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(self.root, "serve.json"),
                      encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None
