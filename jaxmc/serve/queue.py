r"""The serve daemon's durable on-disk job queue (the spool).

Layout under one root directory:

    <spool>/serve.json           live-daemon stamp {host, port, pid, ...}
    <spool>/jobs/<id>.json       one job record per file (atomic writes)
    <spool>/results/<id>.json    the job's jaxmc.metrics/2 artifact
    <spool>/ckpt/<sig>.ck        checkpoints, keyed by job SIGNATURE so
                                 identical jobs share one resume ladder
                                 (serve/protocol.py defines signatures)
    <spool>/daemons/<id>.json    fleet membership: one heartbeat record
                                 per live daemon (ISSUE 19)
    <spool>/leases/<id>.lease    per-job lease: which daemon owns the
                                 job right now, renewed by heartbeat
    <spool>/retries/<id>.r<k>    cross-daemon retry latches (O_EXCL)
    <spool>/quarantine/<id>.json poison jobs parked with fault context

Durability contract: every mutation is a whole-file atomic write
(tmp + os.replace, the obs.write_json_atomic pattern), so a SIGKILLed
daemon leaves a readable spool.  `recover()` runs at daemon start:
jobs stuck in `running` whose lease has EXPIRED (the owning daemon
died mid-job) and jobs a drain parked as `drained` go back to
`queued` — their signature-keyed checkpoint (periodic, drain, or
final) lets the next run resume instead of re-exploring.  Jobs still
leased by a live peer are left alone.  Job IDs are monotonic per
spool (`<spool>/.seq`, under an O_EXCL-free fcntl lock) so queue
order survives restarts and sorts lexicographically.

Fleet contract (ISSUE 19): a job claim is a LEASE, not a mutex — the
lease file carries the owning daemon id and a generation counter, and
its mtime is the renewal clock.  Stealing an expired lease is
arbitrated by an O_EXCL generation latch (`<id>.lease.steal.g<n>`,
the faults.py budget-latch pattern) so exactly one thief wins even
when several peers notice the expiry in the same tick.  Requeues
after an owner death spend a CROSS-DAEMON retry budget (`retries/`
latches); when it is exhausted the job is quarantined instead of
re-poisoning the fleet.

Spool I/O hardening: job/result writes pass through `_write_hard`,
which retries transient failures (and the injected `spool_io_error`
fault site) with exponential backoff, then degrades with a named
`serve.spool_degraded` event + `SpoolDegraded` instead of a raw 500.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from .. import faults
from ..obs import write_json_atomic

#: spool-write retry policy (satellite a): attempts and base backoff
SPOOL_WRITE_TRIES = 3
SPOOL_WRITE_BACKOFF_S = 0.05


class SpoolDegraded(RuntimeError):
    """A spool write failed even after retries — the daemon answers
    with a NAMED 503 (never a raw 500) and keeps serving what it can."""

    def __init__(self, path: str, err: str):
        super().__init__(
            f"spool degraded: cannot write {os.path.basename(path)}: {err}")
        self.path = path
        self.err = err


class JobQueue:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.jobs_dir = os.path.join(self.root, "jobs")
        self.results_dir = os.path.join(self.root, "results")
        self.ckpt_dir = os.path.join(self.root, "ckpt")
        self.daemons_dir = os.path.join(self.root, "daemons")
        self.leases_dir = os.path.join(self.root, "leases")
        self.retries_dir = os.path.join(self.root, "retries")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        for d in (self.jobs_dir, self.results_dir, self.ckpt_dir,
                  self.daemons_dir, self.leases_dir, self.retries_dir,
                  self.quarantine_dir):
            os.makedirs(d, exist_ok=True)
        # optional telemetry hook (the owning daemon sets it) so spool
        # retries/degrades surface as serve.* counters + events
        self.tel = None

    # ---- ids ----------------------------------------------------------
    def _next_id(self) -> str:
        """Monotonic job id, crash-safe across daemon restarts: the
        counter file is read-modify-written under an exclusive flock."""
        seq_path = os.path.join(self.root, ".seq")
        fd = os.open(seq_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            try:
                import fcntl
                fcntl.flock(fd, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # single-daemon spools stay correct without it
            raw = os.read(fd, 32)
            n = int(raw) if raw.strip() else 0
            n += 1
            os.lseek(fd, 0, os.SEEK_SET)
            os.ftruncate(fd, 0)
            os.write(fd, str(n).encode())
            return f"j{n:08d}"
        finally:
            os.close(fd)

    # ---- hardened writes ----------------------------------------------
    def _write_hard(self, path: str, obj: Dict[str, Any]) -> None:
        """Atomic JSON write with transient-failure retries.  The
        `spool_io_error` fault site injects failures here (ctx:
        file=<basename>); real OSErrors take the same path.  After
        SPOOL_WRITE_TRIES the write degrades with a named event."""
        last = None
        for attempt in range(SPOOL_WRITE_TRIES):
            try:
                if faults.fire("spool_io_error",
                               file=os.path.basename(path)):
                    raise OSError("injected spool_io_error")
                write_json_atomic(path, obj)
                if attempt and self.tel is not None:
                    self.tel.counter("serve.spool_retries", attempt)
                return
            except OSError as ex:
                last = ex
                time.sleep(SPOOL_WRITE_BACKOFF_S * (2 ** attempt))
        if self.tel is not None:
            self.tel.counter("serve.spool_degraded")
            self.tel.event("serve.spool_degraded",
                           file=os.path.basename(path), error=str(last))
        raise SpoolDegraded(path, str(last))

    # ---- job records --------------------------------------------------
    def job_path(self, jid: str) -> str:
        return os.path.join(self.jobs_dir, f"{jid}.json")

    def result_path(self, jid: str) -> str:
        return os.path.join(self.results_dir, f"{jid}.json")

    def trace_path(self, jid: str) -> str:
        return os.path.join(self.results_dir, f"{jid}.trace.jsonl")

    def ckpt_path(self, sig: str) -> str:
        return os.path.join(self.ckpt_dir, f"{sig}.ck")

    def batch_ckpt_path(self, bsig: str, sig: str) -> str:
        """Per-member checkpoint of a vbatch cohort.  Keyed by BOTH the
        batch class and the member signature: the merged batch layout
        has a different lane plan than the solo layout, so these can
        never share `ckpt/<sig>.ck` (the resume guard would refuse)."""
        return os.path.join(self.ckpt_dir, f"b{bsig}.{sig}.ck")

    def new_job(self, spec: str, cfg: Optional[str], options: Dict,
                sig: str, **extra) -> Dict[str, Any]:
        """`extra` carries scheduler metadata (ISSUE 13): `bsig` (the
        layout-compat batch class), `cost_estimate` (analyze's
        state-space estimate) and `fast_lane` — all optional and
        omitted when absent, so old spools read unchanged.  ISSUE 19
        adds `tenant` (admission accounting) the same way."""
        job = {
            "id": self._next_id(), "sig": sig, "status": "queued",
            "submitted_at": time.time(), "spec": spec, "cfg": cfg,
            "options": dict(options or {}),
        }
        job.update({k: v for k, v in extra.items() if v is not None})
        self.save(job)
        return job

    def save(self, job: Dict[str, Any]) -> None:
        self._write_hard(self.job_path(job["id"]), job)

    def load(self, jid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.job_path(jid), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def mark(self, jid: str, status: str, **fields) -> Dict[str, Any]:
        job = self.load(jid) or {"id": jid}
        job["status"] = status
        job.update(fields)
        self.save(job)
        return job

    def list_jobs(self) -> List[Dict[str, Any]]:
        out = []
        try:
            names = sorted(os.listdir(self.jobs_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            j = self.load(name[:-len(".json")])
            if j is not None:
                out.append(j)
        return out

    def queued(self) -> List[Dict[str, Any]]:
        return [j for j in self.list_jobs() if j.get("status") == "queued"]

    # ---- results ------------------------------------------------------
    def save_result(self, jid: str, summary: Dict[str, Any]) -> None:
        self._write_hard(self.result_path(jid), summary)

    def load_result(self, jid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.result_path(jid), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    # ---- daemon registry ----------------------------------------------
    def daemon_path(self, daemon_id: str) -> str:
        return os.path.join(self.daemons_dir, f"{daemon_id}.json")

    def heartbeat(self, daemon_id: str, **info) -> None:
        """Refresh this daemon's fleet-membership record.  Peers treat
        a record older than the daemon TTL as a dead node."""
        try:
            write_json_atomic(self.daemon_path(daemon_id),
                              dict(info, id=daemon_id, t=time.time()))
        except OSError:
            pass  # a missed heartbeat is recoverable; the next isn't far

    def remove_daemon(self, daemon_id: str) -> None:
        try:
            os.unlink(self.daemon_path(daemon_id))
        except OSError:
            pass

    def daemons(self, ttl: float) -> List[Dict[str, Any]]:
        """Fleet members with a heartbeat younger than `ttl` seconds.
        Liveness is judged by the record's OWN clock stamp falling
        inside the window — a SIGKILLed daemon simply ages out."""
        out = []
        now = time.time()
        try:
            names = sorted(os.listdir(self.daemons_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.daemons_dir, name),
                          encoding="utf-8") as fh:
                    rec = json.load(fh)
            except (OSError, ValueError):
                continue
            if now - float(rec.get("t", 0)) <= ttl:
                out.append(rec)
        return out

    # ---- leases --------------------------------------------------------
    def lease_path(self, jid: str) -> str:
        return os.path.join(self.leases_dir, f"{jid}.lease")

    def _read_lease(self, jid: str) -> Optional[Dict[str, Any]]:
        path = self.lease_path(jid)
        try:
            age = time.time() - os.stat(path).st_mtime
        except OSError:
            return None
        try:
            with open(path, encoding="utf-8") as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            # mid-write or corrupt: the mtime still dates it, and a
            # generation of 0 makes any steal latch race correctly
            rec = {}
        rec.setdefault("daemon", None)
        rec.setdefault("gen", 0)
        rec["age"] = age
        return rec

    def lease(self, jid: str) -> Optional[Dict[str, Any]]:
        return self._read_lease(jid)

    def try_claim(self, jid: str, daemon_id: str,
                  ttl: float) -> bool:
        """Claim the job's lease.  First claim is an O_EXCL create;
        re-claim by the current holder is a renewal; an EXPIRED lease
        (no renewal for > ttl) may be stolen — the steal of generation
        g is arbitrated by an O_EXCL latch on `<lease>.steal.g<g+1>`,
        so exactly one thief wins no matter how many peers race."""
        path = self.lease_path(jid)
        payload = {"job": jid, "daemon": daemon_id, "gen": 1,
                   "t": time.time()}
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                         0o644)
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            return True
        except FileExistsError:
            pass
        except OSError:
            return False
        cur = self._read_lease(jid)
        if cur is None:
            # vanished between EXCL-fail and read: retry once
            return self.try_claim(jid, daemon_id, ttl)
        if cur["daemon"] == daemon_id:
            return self.renew(jid, daemon_id)
        if cur["age"] <= ttl:
            return False  # held by a live peer
        # expired: race for the generation latch
        gen = int(cur.get("gen", 0)) + 1
        latch = f"{path}.steal.g{gen}"
        try:
            os.close(os.open(latch,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o644))
        except OSError:
            return False  # another thief won this generation
        payload["gen"] = gen
        try:
            write_json_atomic(path, payload)
        except OSError:
            return False
        return True

    def renew(self, jid: str, daemon_id: str) -> bool:
        """Heartbeat-renew a held lease.  Returns False when the lease
        is gone or was stolen — the caller has LOST the job and must
        not publish its result."""
        cur = self._read_lease(jid)
        if cur is None or cur["daemon"] != daemon_id:
            return False
        try:
            os.utime(self.lease_path(jid))
        except OSError:
            return False
        return True

    def owns(self, jid: str, daemon_id: str) -> bool:
        cur = self._read_lease(jid)
        return cur is not None and cur["daemon"] == daemon_id

    def release(self, jid: str, daemon_id: str) -> None:
        """Drop a held lease (job finished or requeued).  Steal latches
        for past generations are cleaned up with it."""
        if not self.owns(jid, daemon_id):
            return
        path = self.lease_path(jid)
        prefix = os.path.basename(path) + ".steal."
        try:
            os.unlink(path)
        except OSError:
            pass
        try:
            for name in os.listdir(self.leases_dir):
                if name.startswith(prefix):
                    os.unlink(os.path.join(self.leases_dir, name))
        except OSError:
            pass

    # ---- cross-daemon retry budget -------------------------------------
    def spend_retry(self, jid: str, budget: int) -> Optional[int]:
        """Spend one unit of the job's fleet-wide retry budget (an
        O_EXCL latch per unit, the faults.py `_claim` pattern — shared
        by every daemon on the spool, unlike a per-process counter).
        Returns the attempt number (1-based) or None when exhausted."""
        for i in range(max(0, int(budget))):
            latch = os.path.join(self.retries_dir, f"{jid}.r{i}")
            try:
                os.close(os.open(latch,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                                 0o644))
                return i + 1
            except OSError:
                continue
        return None

    def retries_spent(self, jid: str) -> int:
        try:
            return sum(1 for n in os.listdir(self.retries_dir)
                       if n.startswith(f"{jid}.r"))
        except OSError:
            return 0

    # ---- poison-job quarantine -----------------------------------------
    def quarantine_path(self, jid: str) -> str:
        return os.path.join(self.quarantine_dir, f"{jid}.json")

    def quarantine(self, jid: str, verdict: str,
                   context: Optional[Dict[str, Any]] = None,
                   trace_tail_lines: int = 40) -> Dict[str, Any]:
        """Park a poison job: capture its record, the fault context,
        and the tail of its per-job trace, then retire it from the
        live queue so no daemon picks it up again."""
        job = self.load(jid) or {"id": jid}
        rec = dict(job)
        rec["status"] = "quarantined"
        rec["quarantined_at"] = time.time()
        rec["verdict"] = verdict
        rec["retries_spent"] = self.retries_spent(jid)
        if context:
            rec["fault_context"] = context
        tail = []
        try:
            with open(self.trace_path(jid), encoding="utf-8") as fh:
                tail = fh.readlines()[-trace_tail_lines:]
        except OSError:
            pass
        if tail:
            rec["trace_tail"] = [ln.rstrip("\n") for ln in tail]
        self._write_hard(self.quarantine_path(jid), rec)
        try:
            os.unlink(self.job_path(jid))
        except OSError:
            pass
        try:
            os.unlink(self.lease_path(jid))
        except OSError:
            pass
        if self.tel is not None:
            self.tel.counter("serve.quarantined")
            self.tel.event("serve.quarantined", id=jid,
                           verdict=verdict)
        return rec

    def load_quarantined(self, jid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self.quarantine_path(jid),
                      encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def quarantined(self) -> List[Dict[str, Any]]:
        out = []
        try:
            names = sorted(os.listdir(self.quarantine_dir))
        except OSError:
            return out
        for name in names:
            if name.endswith(".json"):
                rec = self.load_quarantined(name[:-len(".json")])
                if rec is not None:
                    out.append(rec)
        return out

    # ---- takeover ------------------------------------------------------
    def takeover(self, jid: str, daemon_id: str, ttl: float,
                 retries: int) -> Optional[str]:
        """Steal a dead peer's in-flight job.  Only proceeds when the
        job is `running` and its lease is missing or expired; the lease
        steal latch guarantees a single winner, which then spends one
        cross-daemon retry and requeues — or quarantines the job when
        the budget is gone.  Returns "requeued", "quarantined", or
        None (lost the race / lease still live)."""
        job = self.load(jid)
        if job is None or job.get("status") != "running":
            return None
        cur = self._read_lease(jid)
        if cur is not None and cur["age"] <= ttl:
            return None  # the owner is still renewing
        if not self.try_claim(jid, daemon_id, ttl):
            return None
        attempt = self.spend_retry(jid, retries)
        if attempt is None:
            self.quarantine(
                jid,
                f"poison job: owner died {retries} times across the "
                f"fleet (cross-daemon retry budget exhausted)",
                context={"last_daemon": (cur or {}).get("daemon"),
                         "last_error": job.get("error"),
                         "requeue_note": job.get("requeue_note")})
            return "quarantined"
        self.mark(jid, "queued",
                  requeue_note=f"stolen after lease expiry "
                               f"(attempt {attempt}/{retries})",
                  stolen_by=daemon_id)
        self.release(jid, daemon_id)
        return "requeued"

    # ---- restart recovery ---------------------------------------------
    def recover(self, daemon_id: str = "recover",
                ttl: float = 0.0, retries: int = 0) -> int:
        """Re-queue jobs a previous daemon life left in flight:
        `drained` jobs (it checkpointed and parked them on SIGTERM)
        unconditionally; `running` jobs only when their lease is
        missing or expired — a job still leased by a LIVE peer on the
        same spool belongs to that peer.  Requeues of running jobs
        spend the cross-daemon retry budget when one is configured
        (retries > 0) and quarantine on exhaustion.  Returns the
        number re-queued."""
        n = 0
        for job in self.list_jobs():
            status = job.get("status")
            if status == "drained":
                self.mark(job["id"], "queued",
                          requeue_note="requeued after drain")
                n += 1
            elif status == "running":
                if retries > 0:
                    if self.takeover(job["id"], daemon_id, ttl,
                                     retries) == "requeued":
                        n += 1
                else:
                    cur = self._read_lease(job["id"])
                    if cur is not None and cur["age"] <= ttl:
                        continue  # a live peer owns it
                    self.mark(job["id"], "queued",
                              requeue_note="requeued after daemon "
                                           "restart")
                    n += 1
        return n

    # ---- the live-daemon stamp ----------------------------------------
    def stamp(self, **info) -> None:
        write_json_atomic(os.path.join(self.root, "serve.json"),
                          dict(info, stamped_at=time.time()))

    def read_stamp(self) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(self.root, "serve.json"),
                      encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None
