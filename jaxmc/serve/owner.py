r"""The device-owner worker process (ISSUE 13).

The daemon's workers are THREADS: good for overlapping many jobs'
host-side work, but (a) CPU-bound interp jobs contend with the HTTP
loop for the GIL, and (b) one wedged XLA dispatch would stall every
thread behind the device.  With JAXMC_SERVE_DEVICE_OWNER=1 (or
`serve run --device-owner`) the daemon routes DEVICE work — cross-model
vmapped batches and solo device-backend jobs — to one spawned
child process that owns the accelerator:

  - the daemon process never initializes jax: HTTP + interp jobs keep
    the GIL to themselves;
  - a wedged or crashed dispatch kills (at worst) the owner process;
    the daemon detects the death, REQUEUES the in-flight jobs (their
    spool records simply go back to `queued` — no result was written,
    so nothing is lost) and respawns the owner lazily on the next
    device job;
  - SIGTERM-drain forwards to the child, whose engines park at their
    next safe boundary exactly like in-process engines do.

The owner speaks a tiny pickled request/response protocol over a
multiprocessing Pipe (spawn context — never fork a jax-initialized
daemon).  `run_vbatch` is the one batch runner, used by the owner child
AND by the daemon in-process when the owner is disabled, so the two
paths cannot drift.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import obs


def _member_summary(res, jt, backend: str, spec: str,
                    serve_block: Dict[str, Any]) -> Dict[str, Any]:
    """ONE result-summary builder for every owner-run job (vbatch
    member or solo): the jaxmc.metrics result block, the rendered
    violation trace, and the serve block — shared so the two paths
    cannot drift.  Closes `jt`."""
    drained = bool(getattr(res, "drained", False))
    result_block: Dict[str, Any] = {
        "ok": res.ok, "distinct": res.distinct,
        "generated": res.generated, "diameter": res.diameter,
        "truncated": bool(res.truncated),
        "wall_s": round(res.wall_s, 6),
        "warnings": list(getattr(res, "warnings", []))}
    if drained:
        result_block["drained"] = True
    if res.violation is not None:
        from ..engine.explore import format_trace
        result_block["violation"] = {"kind": res.violation.kind,
                                     "name": res.violation.name}
        result_block["trace"] = format_trace(res.violation)
    summary = jt.summary(result=result_block)
    summary["backend"] = backend
    summary["spec"] = spec
    summary["serve"] = dict(
        serve_block,
        window_recompiles=sum(1 for lv in jt.levels
                              if lv.get("fresh_compile")),
        profile_hits=jt.counters.get("profile.hits", 0),
        persistent_cache_hits=jt.counters.get(
            "compile.persistent_cache_hits", 0))
    jt.close()
    return {"summary": summary, "ok": res.ok, "distinct": res.distinct,
            "generated": res.generated, "drained": drained}


def run_vbatch(members_desc: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Run one cross-model vmapped batch.  `members_desc` is one entry
    per DISTINCT job signature: {spec, cfg, options, jids: [job ids]}.
    Returns {"members": [...]} with per-member result/summary dicts, or
    {"incompatible": reason} when the cohort cannot share a program
    (the caller falls back to solo runs)."""
    from ..backend.batch import BatchCheckEngine, BatchIncompatible
    from .protocol import build_config
    t0 = time.time()
    cfgs, tels = [], []
    for md in members_desc:
        cfg = build_config(md["spec"], md.get("cfg"), md.get("options"))
        if md.get("checkpoint"):
            # batch-scoped per-member checkpoints (ISSUE 19): a drained
            # or stolen cohort re-forms and resumes each member from
            # its own bsig-scoped checkpoint; the batch engine clears
            # any resume whose lane plan no longer matches (fresh run,
            # never a refused job)
            cfg.checkpoint = md["checkpoint"]
            cfg.checkpoint_every = float(
                md.get("checkpoint_every", 60.0))
            cfg.final_checkpoint = True
            if os.path.exists(md["checkpoint"]):
                cfg.resume = md["checkpoint"]
        cfgs.append(cfg)
        tels.append(obs.Telemetry(trace_path=md.get("trace"), meta={
            "command": "serve.job", "job": md["jids"][0],
            "sig": md.get("sig"), "bsig": md.get("bsig"),
            "backend": cfg.backend, "spec": md["spec"],
            "cfg": md.get("cfg"), "env": obs.environment_meta()}))
    try:
        be = BatchCheckEngine(
            cfgs, tels=tels, tags=[md["jids"][0] for md in members_desc]
        ).build()
    except BatchIncompatible as ex:
        for jt in tels:
            jt.close()
        return {"incompatible": str(ex)}
    members = be.run()
    disp = be.dispatcher
    wall = time.time() - t0
    out: List[Dict[str, Any]] = []
    for md, cfg, mem, jt in zip(members_desc, cfgs, members, tels):
        if mem.error is not None:
            jt.close()
            out.append({"error":
                        f"{type(mem.error).__name__}: {mem.error}"})
            continue
        res = mem.result
        if not res.ok and res.violation is not None and \
                res.violation.kind == "error":
            # an engine-level abort (OV_PACK profile gap, capacity
            # overflow) is NOT this job's verdict: a SOLO run recovers
            # via adaptive relayout, which the shared batch program
            # cannot do — hand the member back for a solo retry
            jt.close()
            why = res.violation.message or res.violation.name
            out.append({"retry_solo":
                        f"batch member aborted ({why}); solo relayout "
                        f"recovery applies"})
            continue
        out.append(_member_summary(mem.result, jt, cfg.backend,
                                   md["spec"], {
            "sig": md.get("sig"), "bsig": md.get("bsig"),
            "warm_engine": False,
            "resumed_from_checkpoint": bool(
                getattr(mem, "resumed", False)),
            "batched_with": [j for m2 in members_desc
                             for j in m2["jids"]
                             if j not in md["jids"]],
            "batch_occupancy": disp.max_width,
            "batch_dispatches": disp.dispatches,
            "lifted_consts": list(be.lift_names),
            "job_wall_s": round(wall, 6),
        }))
    return {"members": out, "occupancy": disp.max_width,
            "dispatches": disp.dispatches,
            "lift": list(be.lift_names),
            "engine_builds": be.engine_builds,
            "build_wall_s": round(be.build_wall_s, 6),
            "wall_s": round(wall, 6)}


# sig -> {"session": CheckSession, "completed": bool} — the OWNER'S
# warm registry (ISSUE 19): with the owner process on by default, the
# already-compiled engine must live WHERE THE DEVICE IS.  The same
# bounded-LRU discipline as the daemon's in-process registry
# (JAXMC_SERVE_WARM_MAX), the same checkpoint-replay reuse gate.  The
# owner serves one request at a time (the daemon serializes on the
# pipe), so no locking is needed here.
_WARM: "collections.OrderedDict[str, Dict[str, Any]]" = \
    collections.OrderedDict()


def _warm_max() -> int:
    try:
        return max(1, int(os.environ.get(
            "JAXMC_SERVE_WARM_MAX", "32") or 32))
    except ValueError:
        return 32


def _revalidate_profile(sess, job_tel) -> None:
    """Confirm the durable capacity profile still matches the warm
    engine's layout (counts as a profile hit in the job's artifact) —
    the daemon-side warm path's check, mirrored for the owner."""
    if sess.layout_sig and sess.model is not None:
        from ..compile.cache import load_capacity_profile
        desc = getattr(sess.engine, "backend_desc", None)
        variant = desc.profile_variant() if desc is not None else ""
        load_capacity_profile(sess.model.module.name,
                              sess.layout_sig, tel=job_tel,
                              variant=variant)


def run_solo(md: Dict[str, Any]) -> Dict[str, Any]:
    """Run one solo device job in the owner process: the same
    CheckSession flow the daemon's _run_batch drives, including a warm
    registry of its own — a repeat signature replays the finalized
    checkpoint on the already-compiled engine with zero in-window
    recompiles.  Returns {"summary", "ok", ...} or {"error"}."""
    from ..session import CheckSession
    from .protocol import build_config
    t0 = time.time()
    cfg = build_config(md["spec"], md.get("cfg"), md.get("options"))
    ck = md.get("checkpoint")
    if ck:
        cfg.checkpoint = ck
        cfg.checkpoint_every = float(md.get("checkpoint_every", 60.0))
        cfg.final_checkpoint = True
        if os.path.exists(ck):
            cfg.resume = ck
    jt = obs.Telemetry(trace_path=md.get("trace"), meta={
        "command": "serve.job", "job": md["jids"][0],
        "sig": md.get("sig"), "backend": cfg.backend,
        "spec": md["spec"], "cfg": md.get("cfg"),
        "env": obs.environment_meta()})
    sig = md.get("sig")
    entry = _WARM.get(sig) if sig else None
    warm_engine = bool(entry is not None and entry.get("completed")
                       and ck and os.path.exists(ck))
    resumed = bool(cfg.resume)
    # per-JOB watchdog (ISSUE 16): the stall threshold derives from
    # this job's own level rhythm, never a neighbour's
    wd = obs.Watchdog(jt).start()
    try:
        with obs.use_local(jt):
            if warm_engine:
                # WARM: replay the finalized checkpoint on the
                # already-compiled engine; rebind its telemetry to
                # THIS job's recorder first (the cold job's closed)
                resumed = True
                _WARM.move_to_end(sig)
                sess = entry["session"]
                sess.tel = jt
                sess.log = obs.Logger(jt, quiet=True)
                _revalidate_profile(sess, jt)
                res = sess.explore(resume_from=ck, checkpoint_path=ck,
                                   final_checkpoint=True)
            else:
                sess = CheckSession(cfg, tel=jt,
                                    log=obs.Logger(jt, quiet=True))
                sess.parse()
                try:
                    sess.compile()
                    res = sess.explore()
                except (RuntimeError, OSError, MemoryError,
                        ConnectionError) as ex:
                    res = sess.demote_to_cpu(ex)
                if sig:
                    drained = bool(getattr(res, "drained", False))
                    _WARM[sig] = {"session": sess,
                                  "completed": res.ok and
                                  not res.truncated and not drained}
                    _WARM.move_to_end(sig)
                    while len(_WARM) > _warm_max():
                        _WARM.popitem(last=False)
    except Exception as ex:  # noqa: BLE001 — the job's failure is its
        # verdict; the owner loop must survive to serve the next one
        jt.close()
        return {"error": f"{type(ex).__name__}: {ex}"}
    finally:
        wd.stop()
    return _member_summary(res, jt, cfg.backend, md["spec"], {
        "sig": sig, "warm_engine": warm_engine,
        "resumed_from_checkpoint": resumed,
        "device_owner": True,
        "batched_with": [],
        "job_wall_s": round(time.time() - t0, 6),
    })


def _owner_main(conn) -> None:
    """The owner child's request loop (spawn target — keep this
    module-level and import-light)."""
    import signal
    from .. import drain
    drain.clear()
    signal.signal(signal.SIGTERM,
                  lambda *_: drain.request("device-owner SIGTERM"))
    while True:
        try:
            req = conn.recv()
        except (EOFError, OSError):
            return
        kind = req.get("kind")
        if kind == "stop":
            conn.send({"stopped": True})
            return
        if kind == "ping":
            conn.send({"pong": True, "pid": os.getpid()})
            continue
        try:
            if kind == "vbatch":
                resp = run_vbatch(req["members"])
            elif kind == "solo":
                resp = run_solo(req["member"])
            else:
                resp = {"error": f"unknown request kind {kind!r}"}
        except BaseException as ex:  # noqa: BLE001 — report, don't die
            resp = {"error": f"{type(ex).__name__}: {ex}"}
        try:
            conn.send(resp)
        except (BrokenPipeError, OSError):
            return


class OwnerDied(Exception):
    """The owner process died (or timed out) with a request in flight.
    `timed_out` distinguishes a POLICY kill (the request exceeded
    JAXMC_SERVE_OWNER_TIMEOUT — requeueing would livelock: the re-run
    hits the same deadline) from a genuine death (requeue + respawn is
    the right recovery)."""

    def __init__(self, msg: str, timed_out: bool = False):
        super().__init__(msg)
        self.timed_out = timed_out


class DeviceOwner:
    """Parent-side handle: lazy spawn, serialized requests, death
    detection, respawn accounting."""

    def __init__(self, log=None, timeout: Optional[float] = None):
        import multiprocessing as mp
        self._mp = mp.get_context("spawn")
        self._proc = None
        self._conn = None
        self._lock = threading.Lock()
        self.log = log or (lambda *_: None)
        self.timeout = timeout if timeout is not None else float(
            os.environ.get("JAXMC_SERVE_OWNER_TIMEOUT", "3600"))
        self.spawns = 0

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def alive(self) -> bool:
        return self._proc is not None and self._proc.is_alive()

    def _spawn_locked(self) -> None:
        parent, child = self._mp.Pipe()
        self._proc = self._mp.Process(target=_owner_main, args=(child,),
                                      name="jaxmc-device-owner",
                                      daemon=True)
        # the spawn context snapshots os.environ at start(): export the
        # trace header for that window so the owner (and every job it
        # runs) joins the daemon's trace — a respawned owner re-reads
        # the SAME header, keeping the original trace_id
        with obs.context.exported():
            self._proc.start()
        child.close()
        self._conn = parent
        self.spawns += 1
        self.log(f"serve: device-owner process spawned "
                 f"(pid {self._proc.pid})")

    def request(self, req: Dict[str, Any],
                timeout: Optional[float] = None) -> Dict[str, Any]:
        """Send one request; block for the response.  Raises OwnerDied
        if the child dies or the deadline passes — the owner is then
        torn down so the next request respawns a fresh one."""
        with self._lock:
            # the deadline starts when THIS request is actually sent:
            # time spent waiting behind another worker's long job must
            # not count against it (a healthy owner would be killed)
            deadline = time.time() + (timeout if timeout is not None
                                      else self.timeout)
            if not self.alive():
                self._spawn_locked()
            try:
                self._conn.send(req)
            except (BrokenPipeError, OSError):
                # a broken pipe makes the child unusable even if it is
                # still alive: kill it so the next request respawns
                self._kill_locked()
                raise OwnerDied("owner pipe closed on send")
            while True:
                try:
                    if self._conn.poll(0.2):
                        return self._conn.recv()
                except (EOFError, OSError):
                    self._kill_locked()
                    raise OwnerDied("owner pipe closed mid-request")
                if not self._proc.is_alive():
                    self._reap_locked()
                    raise OwnerDied(
                        f"owner process died (exitcode "
                        f"{self._proc.exitcode if self._proc else '?'})")
                if time.time() > deadline:
                    self._kill_locked()
                    raise OwnerDied(
                        "owner request exceeded "
                        "JAXMC_SERVE_OWNER_TIMEOUT "
                        f"({self.timeout:.0f}s); raise it for "
                        "longer-running cohorts", timed_out=True)

    def _reap_locked(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        self._conn = None

    def _kill_locked(self) -> None:
        self._reap_locked()
        if self._proc is not None and self._proc.is_alive():
            self._proc.kill()
            self._proc.join(timeout=5.0)
        self._proc = None

    def drain(self) -> None:
        """Forward the daemon's drain: SIGTERM the child so its engines
        park at their next safe boundary."""
        if self.alive():
            self._proc.terminate()

    def stop(self, timeout: float = 30.0) -> None:
        with self._lock:
            if not self.alive():
                self._kill_locked()
                return
            try:
                self._conn.send({"kind": "stop"})
                t0 = time.time()
                while self._proc.is_alive() and \
                        time.time() - t0 < timeout:
                    time.sleep(0.05)
            except (BrokenPipeError, OSError):
                pass
            self._kill_locked()
