r"""`python -m jaxmc.serve` — run the daemon, talk to it, or smoke it.

    run     (default) start the daemon on a spool directory
    submit  POST a job to a live daemon (discovered via the spool stamp)
    status  print a live daemon's /status JSON
    smoke   the `make serve-check` gate: fresh spool, in-process daemon,
            two identical jobs — the second MUST be a warm
            checkpoint-resume with zero in-window recompiles and a
            capacity-profile hit, and the warm artifact must pass
            `python -m jaxmc.obs diff --fail-on-regress` against the
            cold one.  Exit 0 only when every assertion holds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import List, Optional


def cmd_run(args) -> int:
    from .. import drain
    from .daemon import ServeDaemon
    # flag -> env so the policy has ONE read site (the daemon's), and
    # subprocess daemon tests can set it the same way
    if getattr(args, "no_device_owner", False):
        os.environ["JAXMC_SERVE_DEVICE_OWNER"] = "0"
    elif getattr(args, "device_owner", False):
        os.environ["JAXMC_SERVE_DEVICE_OWNER"] = "1"
    daemon = ServeDaemon(args.spool, host=args.host, port=args.port,
                         workers=args.workers, trace=args.trace,
                         metrics_out=args.metrics_out, quiet=args.quiet,
                         checkpoint_every=args.checkpoint_every)
    daemon.start()
    # SIGTERM/SIGINT -> cooperative drain: in-flight jobs checkpoint and
    # park, queued jobs persist in the spool, exit 0 (a drained daemon
    # is a clean daemon); a second signal hard-exits 143 (drain.py)
    import signal
    drain.install(signals=(signal.SIGTERM, signal.SIGINT),
                  on_request=lambda name: daemon.initiate_drain(
                      f"signal {name}"))
    return daemon.serve_forever()


def cmd_submit(args) -> int:
    from .protocol import ServeClient
    client = ServeClient.from_spool(args.spool)
    options = json.loads(args.options) if args.options else {}
    for flag in ("backend", "platform"):
        v = getattr(args, flag)
        if v is not None:
            options[flag] = v
    if args.resident:
        options["resident"] = True
        options.setdefault("no_trace", True)
    code, job = client.submit(os.path.abspath(args.spec),
                              os.path.abspath(args.cfg)
                              if args.cfg else None, options,
                              tenant=args.tenant)
    if code == 429:
        print(f"error: admission refused (429): {job.get('error')} "
              f"[Retry-After: "
              f"{client.last_headers.get('Retry-After')}s]",
              file=sys.stderr)
        return 2
    if code != 200:
        print(f"error: submit failed ({code}): {job.get('error')}",
              file=sys.stderr)
        return 2
    if not args.wait:
        print(json.dumps(job, indent=1))
        return 0
    job = client.wait(job["id"], timeout=args.timeout)
    print(json.dumps(job, indent=1))
    if job.get("status") != "done":
        return 2
    return 0 if job.get("ok") else 1


def cmd_status(args) -> int:
    from .protocol import ServeClient
    client = ServeClient.from_spool(args.spool)
    code, st = client.status()
    print(json.dumps(st, indent=1))
    return 0 if code == 200 else 2


def cmd_smoke(args) -> int:
    """The serve-check gate (Makefile): prove the warm-reuse contract
    end to end on a repo-local spec, in one process, in seconds."""
    from .daemon import ServeDaemon
    from .protocol import ServeClient

    spool = args.spool or tempfile.mkdtemp(prefix="jaxmc_serve_smoke_")
    # hermetic durable artifacts: the capacity-profile store lives in
    # the spool so the smoke's profile hits are its own, not a previous
    # run's (the compile cache stays off — the guarded enable's health
    # probe costs more than this whole smoke)
    os.environ.setdefault("JAXMC_PROFILE_STORE",
                          os.path.join(spool, "profiles"))
    spec = os.path.abspath(args.spec)
    options = {"backend": "jax", "platform": "cpu", "resident": True,
               "no_trace": True}

    daemon = ServeDaemon(spool, workers=1, quiet=False).start()
    try:
        client = ServeClient("127.0.0.1", daemon.port)

        def run_one(tag: str):
            code, job = client.submit(spec, None, options)
            assert code == 200, f"{tag}: submit failed ({code}): {job}"
            done = client.wait(job["id"], timeout=args.timeout)
            assert done["status"] == "done", \
                f"{tag}: job {done['id']} ended {done['status']!r}: " \
                f"{done.get('error')}"
            code, res = client.result(done["id"])
            assert code == 200, f"{tag}: no result artifact"
            return done, res

        cold_job, cold = run_one("cold")
        warm_job, warm = run_one("warm")

        failures: List[str] = []
        sv = warm.get("serve", {})
        if not sv.get("resumed_from_checkpoint"):
            failures.append("warm job did not resume the cold job's "
                            "checkpoint")
        if not sv.get("warm_engine"):
            failures.append("warm job did not reuse the warm session")
        if sv.get("window_recompiles") != 0:
            failures.append(f"warm job recompiled in-window "
                            f"({sv.get('window_recompiles')} times)")
        if not sv.get("profile_hits"):
            failures.append("warm job recorded no capacity-profile hit")
        cr, wr = cold.get("result", {}), warm.get("result", {})
        if (wr.get("generated"), wr.get("distinct")) != \
                (cr.get("generated"), cr.get("distinct")):
            failures.append(
                f"warm counts {wr.get('generated')}/{wr.get('distinct')}"
                f" != cold {cr.get('generated')}/{cr.get('distinct')}")
        # the regression gate: the warm artifact vs the cold one
        from ..obs.report import main as obs_main
        cold_path = daemon.q.result_path(cold_job["id"])
        warm_path = daemon.q.result_path(warm_job["id"])
        rc = obs_main(["diff", "--fail-on-regress", cold_path,
                       warm_path])
        if rc != 0:
            failures.append("obs diff flagged a cold->warm regression")
        for f in failures:
            print(f"serve-check: FAIL: {f}", file=sys.stderr)
        if not failures:
            print(f"serve-check: PASS — warm submission resumed the "
                  f"checkpoint with 0 in-window recompiles "
                  f"(profile_hits={sv.get('profile_hits')}, "
                  f"artifacts: {cold_path} {warm_path})")
        return 1 if failures else 0
    finally:
        daemon.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # bare `python -m jaxmc.serve [--flags]` runs the daemon
    if not argv or argv[0].startswith("-"):
        argv = ["run"] + argv
    ap = argparse.ArgumentParser(prog="python -m jaxmc.serve")
    sub = ap.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="start the checking daemon")
    r.add_argument("--spool", default="/tmp/jaxmc_serve",
                   help="durable job-queue directory (survives restarts)")
    r.add_argument("--host", default="127.0.0.1")
    r.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral; the bound port lands in "
                        "<spool>/serve.json")
    r.add_argument("--workers", type=int, default=2,
                   help="worker threads (bounded pool)")
    r.add_argument("--trace", default=None, metavar="FILE",
                   help="fleet telemetry JSONL (job spans, queue gauges, "
                        "watchdog heartbeats)")
    r.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="fleet metrics artifact written at drain")
    r.add_argument("--quiet", action="store_true")
    r.add_argument("--checkpoint-every", type=float, default=60.0,
                   metavar="S",
                   help="periodic job-checkpoint cadence; the spool "
                        "checkpoint is what a lease-expiry takeover "
                        "resumes from (env: JAXMC_SERVE_CKPT_EVERY)")
    r.add_argument("--device-owner", action="store_true",
                   help="route device work (vmapped batches, solo "
                        "device jobs) through a spawned owner process "
                        "(ISSUE 13): the daemon never initializes jax, "
                        "a wedged/crashed dispatch kills at worst the "
                        "owner (jobs requeue, owner respawns). THE "
                        "DEFAULT since owner death became supervised; "
                        "equiv: JAXMC_SERVE_DEVICE_OWNER=1")
    r.add_argument("--no-device-owner", action="store_true",
                   help="run device work in-process (the pre-fleet "
                        "layout). Equiv: JAXMC_SERVE_DEVICE_OWNER=0")
    r.set_defaults(fn=cmd_run)

    s = sub.add_parser("submit", help="submit a job to a live daemon")
    s.add_argument("spec")
    s.add_argument("--cfg", default=None)
    s.add_argument("--spool", default="/tmp/jaxmc_serve")
    s.add_argument("--backend", choices=("interp", "jax"), default=None)
    s.add_argument("--platform", default=None)
    s.add_argument("--resident", action="store_true")
    s.add_argument("--options", default=None,
                   help="extra job options as a JSON object")
    s.add_argument("--tenant", default=None,
                   help="admission-control accounting principal "
                        "(per-tenant token bucket); default 'default'")
    s.add_argument("--wait", action="store_true",
                   help="poll until the job finishes; exit 0/1 like "
                        "`jaxmc check`")
    s.add_argument("--timeout", type=float, default=600.0)
    s.set_defaults(fn=cmd_submit)

    t = sub.add_parser("status", help="print a live daemon's status")
    t.add_argument("--spool", default="/tmp/jaxmc_serve")
    t.set_defaults(fn=cmd_status)

    k = sub.add_parser("smoke",
                       help="the make serve-check gate: cold+warm "
                            "submission pair, warm-reuse assertions, "
                            "obs diff regression gate")
    k.add_argument("--spool", default=None,
                   help="default: a fresh temp dir")
    k.add_argument("--spec", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "specs", "constoy.tla"))
    k.add_argument("--timeout", type=float, default=300.0)
    k.set_defaults(fn=cmd_smoke)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except AssertionError as e:
        print(f"serve: FAIL: {e}", file=sys.stderr)
        return 1
    except (OSError, ValueError, TimeoutError) as e:
        print(f"serve: error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
