r"""`make backend-check` (ISSUE 11): oracle smoke + per-backend gate.

Two legs, one parseable line each:

  1. ORACLE — the preflight oracle (jaxmc/backend/oracle.py) must find
     at least one live platform inside its deadline (the --smoke
     contract: a broken probe harness fails here, in seconds).
  2. per-platform BASELINE — for every LIVE platform, one small
     jax-backend check leg pinned to it (`python -m jaxmc check
     --backend <plat>`), its jaxmc.metrics artifact gated against that
     platform's OWN saved baseline via `python -m jaxmc.obs diff
     --fail-on-regress` (first run snapshots it — how a new platform's
     baseline is seeded, BASELINE.md "Per-backend baselines").  Dead
     platforms emit `BACKEND-CHECK SKIP <plat>: <reason>` — parseable,
     never a failure — so the same target is green on a cpu-only
     builder box and on a TPU pod.

All live platforms must also agree on the leg's reachable-state counts
(the cross-backend exactness pin; counts differing across XLA targets
would mean the engine layer is NOT backend-portable).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the gate leg: small, repo-local, resident jax engine — big enough to
#: exercise compile + the resident loop, small enough for seconds/leg
_LEG_SPEC = "specs/viewtoy_scaled.tla"
_LEG_MAX_STATES = "4000"


def _run_leg(plat: str, out_dir: str, timeout_s: float) -> dict:
    metrics = os.path.join(out_dir, f"jaxmc_backend_{plat}.json")
    cmd = [sys.executable, "-m", "jaxmc", "check",
           os.path.join(_REPO, _LEG_SPEC),
           "--backend", plat, "--resident", "--no-trace", "--quiet",
           "--max-states", _LEG_MAX_STATES,
           "--metrics-out", metrics]
    env = dict(os.environ, PYTHONPATH=_REPO)
    # the child pins its own platform; a parent-level JAX_PLATFORMS=cpu
    # (tier-1 convention) would override the pin on accelerators
    env.pop("JAX_PLATFORMS", None)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=_REPO, env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False,
                "error": f"leg timed out after {timeout_s:.0f}s"}
    if p.returncode != 0:
        tail = ((p.stderr or "") + (p.stdout or "")).strip() \
            .splitlines()[-2:] or ["no output"]
        return {"ok": False,
                "error": f"rc={p.returncode}: "
                         + " | ".join(t[:160] for t in tail)}
    try:
        with open(metrics, encoding="utf-8") as fh:
            summary = json.load(fh)
    except (OSError, ValueError) as ex:
        return {"ok": False, "error": f"no metrics artifact ({ex})"}
    res = summary.get("result") or {}
    return {"ok": bool(res.get("ok")), "metrics": metrics,
            "distinct": res.get("distinct"),
            "generated": res.get("generated"),
            "wall_s": round(time.time() - t0, 3)}


#: one-shot cold-start walls excluded from the per-backend phase gate:
#: they time XLA compiles and plugin init, which swing with box load in
#: a way the measured search window does not (the meshbench legs avoid
#: the problem by gating a WARM timed window; this leg is deliberately
#: cold end-to-end, so it gates states/sec + search instead)
_COLD_PHASES = ("device_init", "engine_build", "layout_sample",
                "compile_arm", "preflight_oracle")


def _gate(metrics_path: str) -> int:
    # per-PLATFORM saved baseline (the artifact name carries the
    # platform): first run snapshots, later runs gate — shared logic
    # with the meshbench legs
    from ..meshbench import _gate as gate
    return gate(metrics_path, log=print, ignore_phases=_COLD_PHASES)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m jaxmc.backend.check",
        description="oracle smoke + per-backend baseline gate")
    ap.add_argument("--out-dir", default=os.environ.get(
        "JAXMC_PROBE_DIR", "/tmp"))
    ap.add_argument("--deadline", type=float, default=float(
        os.environ.get("JAXMC_ORACLE_DEADLINE", "10")))
    ap.add_argument("--leg-timeout", type=float, default=float(
        os.environ.get("JAXMC_BACKEND_CHECK_TIMEOUT", "300")))
    args = ap.parse_args(argv)

    from .oracle import preflight
    v = preflight(deadline_s=args.deadline, use_cache=False)
    for plat, pr in v["probes"].items():
        if pr.get("live"):
            print(f"BACKEND-CHECK oracle {plat} live "
                  f"devices={pr.get('devices')} "
                  f"dispatch={pr.get('dispatch_s')}s")
    if v["platform"] is None:
        print("BACKEND-CHECK FAIL oracle: no live platform "
              f"({v['reason']})", file=sys.stderr)
        return 1
    if v["wall_s"] > args.deadline:
        print(f"BACKEND-CHECK FAIL oracle: preflight took "
              f"{v['wall_s']}s > {args.deadline}s", file=sys.stderr)
        return 1
    print(f"BACKEND-CHECK oracle verdict {v['platform']} "
          f"wall={v['wall_s']}s")

    failures = 0
    counts = {}
    for plat, pr in v["probes"].items():
        if not pr.get("live"):
            print(f"BACKEND-CHECK SKIP {plat}: {pr.get('error')}")
            continue
        r = _run_leg(plat, args.out_dir, args.leg_timeout)
        if not r.get("ok"):
            print(f"BACKEND-CHECK FAIL {plat}: {r.get('error', r)}")
            failures += 1
            continue
        counts[plat] = (r["generated"], r["distinct"])
        print(f"BACKEND-CHECK ok {plat}: {r['generated']} gen / "
              f"{r['distinct']} distinct ({r['wall_s']}s)")
        if _gate(r["metrics"]):
            failures += 1
    if len(set(counts.values())) > 1:
        print(f"BACKEND-CHECK FAIL: live platforms disagree on counts "
              f"{counts}", file=sys.stderr)
        failures += 1
    print(f"backend-check: {'FAIL' if failures else 'ok'} "
          f"({failures} failing legs, "
          f"{len(counts)} live platform(s))")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
