r"""Cross-model vmapped batching (ISSUE 13): one dispatch serves many
layout-compatible jobs.

The model-checking analogue of continuous batching in LLM serving
(Orca, OSDI '22).  The serve fleet's old batching coalesced IDENTICAL
jobs only; here B *different but layout-compatible* models share one
compiled device program:

  compat   two models are batch-compatible when they differ only in
           LIFTABLE constant values (analyze/bounds.liftable_constants:
           ints used purely in value positions) — everything that shapes
           the layout, the arm structure, or the dedup key basis is
           equal.  session.batch_signature proves this at PARSE time,
           before any engine exists.
  compile  ONE donor engine builds the layout (lane plan over the union
           of every member's sampled states; proven bounds interval-
           merged across members) and the kernels, with the lifted
           constants as traced inputs (kernel2 const_lanes).  Followers
           clone the donor (TpuExplorer(donor=...)): zero sampling,
           zero kernel builds.
  dispatch every member runs the UNCHANGED host_seen BFS loop — its own
           init states, native fingerprint store, trace bookkeeping,
           verdicts — but its per-chunk device call routes through the
           shared BatchDispatcher, which waits until every ACTIVE
           member has a pending chunk and then runs ONE
           jit(vmap(hstep_core)) over [B, CH, PW] frontiers + [B]
           counts + [B, n_lift] constant vectors.
  ragged   per-member frontier occupancy is handled by the step's own
           validity masks (fcount per lane); a member that finishes —
           exhaustion, violation, truncation, drain — DEREGISTERS and
           its lane goes idle-masked: membership changes between
           supersteps without recompiling (the continuous-batching
           move).

Because each member's host loop IS the solo engine's loop and
vmap(f)(stack(xs))[i] == f(xs[i]) exactly over integer kernels, per-job
counts, traces, and verdicts are byte-identical to solo runs — batching
is a throughput optimization, never a semantics change.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import obs
from ..compile.vspec import Bounds, CompileError, ModeError
from ..engine.simulate import sample_states
from .bfs import SENTINEL, TpuExplorer, _pow2_at_least


class BatchIncompatible(Exception):
    """The cohort cannot share one program; the message names why.  The
    caller (serve daemon, batchbench) falls back to solo runs."""


@dataclass
class _MergedBounds:
    """Shim BoundsReport for the donor build: the interval-UNION of
    every member's converged proof, sound for all of them."""
    merged: Dict[str, Tuple[int, int]]
    merged_eb: Dict[str, Any] = field(default_factory=dict)
    converged: bool = True

    def lane_bounds(self) -> Dict[str, Tuple[int, int]]:
        return self.merged

    def element_bounds(self) -> Dict[str, Any]:
        # structural merge (ISSUE 18): per-element trees where every
        # member proved one, backed by the lane interval for variables
        # whose structured merge collapsed — the donor plan never packs
        # wider than the worst solo member would
        from ..analyze.bounds import EB
        out: Dict[str, Any] = dict(self.merged_eb)
        for v, iv in self.merged.items():
            if v not in out:
                out[v] = EB(all=iv)
        return out


class BatchDispatcher:
    """The superstep barrier: collects one pending device chunk per
    ACTIVE member, runs ONE vmapped dispatch, hands each member its
    slice.  The thread that completes the barrier executes the dispatch
    inline (every other member is blocked waiting on its slice)."""

    def __init__(self, donor: TpuExplorer, cvecs: np.ndarray,
                 tel=None):
        self.CH = _pow2_at_least(donor.chunk, lo=64)
        self.B = len(cvecs)
        self.PW = donor.PW
        self._core = donor._hstep_core(self.CH)
        self._vstep = obs.prof_wrap("batch.vstep",
                                    jax.jit(jax.vmap(self._core)))
        self._cvecs = jnp.asarray(np.ascontiguousarray(cvecs, np.int32))
        self.tel = tel
        self._cv = threading.Condition()
        self._active: set = set(range(self.B))
        self._pending: Dict[int, Tuple[np.ndarray, int]] = {}
        self._results: Dict[int, Dict[str, Any]] = {}
        self._gen = 0            # dispatch generation (wakeup marker)
        self.dispatches = 0
        self.max_width = 0
        self.widths: List[int] = []

    def reset(self) -> None:
        """Re-arm for another cohort run (bench warm re-runs): all
        lanes active again, superstep state and PER-RUN STATS cleared
        (the artifact's dispatch count must describe one run, not the
        lifetime).  The compiled vmapped program is untouched — that is
        the warm artifact."""
        with self._cv:
            self._active = set(range(self.B))
            self._pending.clear()
            self._results.clear()
            self.dispatches = 0
            self.max_width = 0
            self.widths = []

    # ---- member surface ------------------------------------------------
    def hstep_factory(self, slot: int):
        """The _hstep_override for member `slot`: returns a callable
        with the solo hstep's signature whose device work goes through
        the shared vmapped program."""
        def factory(CH: int):
            if CH != self.CH:
                raise ModeError(
                    f"batch member chunk capacity {CH} != shared "
                    f"dispatcher capacity {self.CH}")

            def hstep(frontier_p, fcount):
                return self._step(slot, frontier_p, int(fcount))

            return hstep

        return factory

    def deregister(self, slot: int) -> None:
        """Membership change between supersteps: the member is done (or
        failed); remaining members' barrier no longer waits for it."""
        with self._cv:
            self._active.discard(slot)
            self._pending.pop(slot, None)
            if self._active and \
                    set(self._pending) >= self._active:
                self._fire_locked()
            self._cv.notify_all()

    # ---- the superstep -------------------------------------------------
    def _step(self, slot: int, frontier_p, fcount: int
              ) -> Dict[str, Any]:
        with self._cv:
            self._pending[slot] = (np.asarray(frontier_p, np.int32),
                                   fcount)
            if set(self._pending) >= self._active:
                self._fire_locked()
            while slot not in self._results:
                self._cv.wait(0.5)
            res = self._results.pop(slot)
            if isinstance(res, BaseException):
                # the shared dispatch failed: EVERY waiter gets the
                # error (not just the thread that fired) — each member
                # fails its own run and deregisters, so the cohort
                # never deadlocks on a lane that cannot re-fire
                raise RuntimeError(
                    f"vmapped batch dispatch failed: "
                    f"{type(res).__name__}: {res}") from res
            return res

    def _fire_locked(self) -> None:
        """One vmapped dispatch over every pending member lane (caller
        holds the condition).  A dispatch failure is distributed to
        every pending slot as its result — see _step."""
        slots = sorted(self._pending)
        width = len(slots)
        fr = np.full((self.B, self.CH, self.PW), SENTINEL, np.int32)
        fc = np.zeros(self.B, np.int32)
        for s in slots:
            bf, c = self._pending[s]
            fr[s] = bf
            fc[s] = c
        self._pending.clear()
        try:
            out = self._vstep(jnp.asarray(fr), jnp.asarray(fc),
                              self._cvecs)
            out_np = {k: np.asarray(v) for k, v in out.items()}
        except Exception as ex:  # noqa: BLE001 — XLA runtime/OOM/
            # compile failures land on every waiting member
            for s in slots:
                self._results[s] = ex
            self._cv.notify_all()
            return
        for s in slots:
            self._results[s] = {k: v[s] for k, v in out_np.items()}
        self.dispatches += 1
        self.max_width = max(self.max_width, width)
        self.widths.append(width)
        if self.tel is not None:
            self.tel.gauge("batch.width", width)
            self.tel.counter("batch.dispatches")
        self._cv.notify_all()


@dataclass
class BatchMember:
    """One job in the cohort: its model, engine, telemetry channel and
    (after run) result or error."""
    model: Any
    engine: Optional[TpuExplorer] = None
    tel: Any = None
    result: Any = None
    error: Optional[BaseException] = None
    tag: Optional[str] = None  # caller's handle (job id)
    warnings: List[str] = field(default_factory=list)
    resumed: bool = False      # engine restored from its checkpoint


# engine-relevant option surface every member must share (per-model
# differences ride the lifted constant lanes, nothing else)
_SHARED_FIELDS = ("include", "no_deadlock", "max_states", "seq_cap",
                  "grow_cap", "kv_cap", "no_trace", "sample", "chunk")


class BatchCheckEngine:
    """B layout-compatible CheckSession configs -> one donor engine +
    B-1 follower clones -> one vmapped dispatch sequence -> B solo-
    identical CheckResults."""

    def __init__(self, cfgs: List[Any], tels: Optional[List[Any]] = None,
                 tags: Optional[List[str]] = None, log=None, tel=None):
        if len(cfgs) < 1:
            raise ValueError("empty batch")
        self.cfgs = cfgs
        self.tel = tel if tel is not None else obs.current()
        self.log = log if log is not None else obs.Logger(self.tel,
                                                          quiet=True)
        self.members: List[BatchMember] = []
        self.dispatcher: Optional[BatchDispatcher] = None
        self.lift_names: Tuple[str, ...] = ()
        self._tels = tels or [None] * len(cfgs)
        self._tags = tags or [None] * len(cfgs)
        self.build_wall_s = 0.0

    # ---- compat proof + build -----------------------------------------
    def build(self) -> "BatchCheckEngine":
        from ..analyze.bounds import (infer_state_bounds,
                                      liftable_constants,
                                      merge_element_bounds,
                                      merge_lane_bounds)
        from ..session import load_model
        t0 = time.time()
        c0 = self.cfgs[0]
        for c in self.cfgs[1:]:
            for f in _SHARED_FIELDS:
                if getattr(c, f) != getattr(c0, f):
                    raise BatchIncompatible(
                        f"member option {f!r} differs "
                        f"({getattr(c, f)!r} vs {getattr(c0, f)!r})")
        models = []
        for c, jt in zip(self.cfgs, self._tels):
            with (jt or self.tel).span("load", spec=c.spec):
                models.append(load_model(c.spec, c.cfg, c.no_deadlock,
                                         c.include))
        m0 = models[0]
        lift = liftable_constants(m0)
        for m in models[1:]:
            if m.module.name != m0.module.name:
                raise BatchIncompatible(
                    f"module {m.module.name!r} != {m0.module.name!r}")
            if tuple(m.vars) != tuple(m0.vars):
                raise BatchIncompatible("state variables differ")
            if liftable_constants(m) != lift:
                raise BatchIncompatible("liftable-constant sets differ")
            if set(m.cfg.constants) != set(m0.cfg.constants):
                raise BatchIncompatible("cfg CONSTANT names differ")
            for n in m.cfg.constants:
                if n not in lift and \
                        m.defs.get(n) != m0.defs.get(n):
                    raise BatchIncompatible(
                        f"non-liftable constant {n} differs "
                        f"({m.defs.get(n)!r} vs {m0.defs.get(n)!r}) — "
                        f"it shapes the layout, so the models are not "
                        f"layout-compatible")
        self.lift_names = lift
        self.members = [BatchMember(model=m, tel=t, tag=g)
                        for m, t, g in zip(models, self._tels,
                                           self._tags)]

        # ONE layout over the union of every member's sampled states,
        # with the proven bounds interval-merged so no member's values
        # can trip another's proof
        bfs_n, walks, depth = tuple(c0.sample)
        extra: List[Dict[str, Any]] = []
        reports = []
        with self.tel.span("batch_sample", members=len(models)):
            for m in models:
                reports.append(infer_state_bounds(m))
                if m is not m0:
                    extra.extend(sample_states(m, bfs_states=bfs_n,
                                               n_walks=walks,
                                               walk_depth=depth))
        merged = merge_lane_bounds(
            [r.lane_bounds() if r is not None and r.converged else None
             for r in reports])
        merged_eb = merge_element_bounds(
            [r.element_bounds() if r is not None and r.converged
             else None for r in reports])
        m0._bounds_report = _MergedBounds(merged=merged,
                                          merged_eb=merged_eb)

        bounds = Bounds(seq_cap=c0.seq_cap, grow_cap=c0.grow_cap,
                        kv_cap=c0.kv_cap)
        with self.tel.span("engine_build", batch=len(models)):
            try:
                donor = TpuExplorer(
                    m0, log=self.log, bounds=bounds,
                    store_trace=not c0.no_trace,
                    progress_every=c0.progress_every,
                    host_seen=True, chunk=c0.chunk,
                    sample_cfg=tuple(c0.sample),
                    extra_samples=extra,
                    max_states=c0.max_states,
                    relayouts_left=0,
                    checkpoint_path=c0.checkpoint,
                    checkpoint_every=c0.checkpoint_every,
                    resume_from=c0.resume,
                    final_checkpoint=c0.final_checkpoint,
                    lift_consts=lift)
            except (CompileError, ModeError) as ex:
                raise BatchIncompatible(
                    f"lifted-constant compile failed: {ex}")
        reason = donor.batch_block_reason()
        if reason is not None:
            raise BatchIncompatible(f"donor engine not batchable: "
                                    f"{reason}")
        self.members[0].engine = donor
        for mem, c in zip(self.members[1:], self.cfgs[1:]):
            mem.engine = TpuExplorer(
                mem.model, donor=donor, log=self.log,
                max_states=c0.max_states,
                store_trace=not c0.no_trace,
                progress_every=c0.progress_every,
                checkpoint_path=c.checkpoint,
                checkpoint_every=c.checkpoint_every,
                resume_from=c.resume,
                final_checkpoint=c.final_checkpoint)
        self._validate_resumes()
        cvecs = np.stack([mem.engine._cvec for mem in self.members]) \
            if lift else np.zeros((len(self.members), 0), np.int32)
        self.dispatcher = BatchDispatcher(donor, cvecs, tel=self.tel)
        # MEASURED engine-build count for the cohort (the "one compile"
        # gauge must be derived, not asserted): the donor build above
        # is the only build path — follower clones and the vmapped jit
        # reuse it; any future path that rebuilds must increment this
        self.engine_builds = 1
        self.build_wall_s = time.time() - t0
        self.tel.gauge("batch.members", len(self.members))
        self.tel.gauge("batch.lifted_consts", list(lift))
        self.tel.gauge("batch.plan", donor.plan.batch_descriptor())
        return self

    def _validate_resumes(self) -> None:
        """Batch-scoped resume guard (ISSUE 19): a member whose
        checkpoint cannot seed THIS cohort's merged layout (a solo
        checkpoint, a different cohort's packing, a torn file) runs
        FRESH instead of failing — lease takeover feeds possibly-stale
        paths by design, so refusal is a downgrade, never an error."""
        from ..engine.ckpt import CkptError, load_checkpoint
        for mem in self.members:
            eng = mem.engine
            path = getattr(eng, "resume_from", None)
            if not path:
                continue
            why = None
            try:
                _, ck = load_checkpoint(path, kind="device")
                if ck.get("module") != mem.model.module.name or \
                        ck.get("vars") != list(mem.model.vars):
                    why = "checkpoint is for a different model"
                elif ck.get("mode") != "host_seen":
                    why = (f"checkpoint was written by the "
                           f"{ck.get('mode')!r} device mode")
                elif ck.get("layout_sig") != eng._layout_sig():
                    why = ("lane layout differs from the checkpoint's "
                           "(solo or different-cohort checkpoint)")
            except (CkptError, OSError, ValueError) as ex:
                why = str(ex)
            if why is None:
                mem.resumed = True
                continue
            eng.resume_from = None
            self.tel.counter("batch.resume_refused")
            self.log(f"batch member {mem.tag or '?'}: refusing "
                     f"checkpoint {path} ({why}); running fresh")

    # ---- run -----------------------------------------------------------
    def run(self) -> List[BatchMember]:
        """Drive every member's UNCHANGED host_seen loop, one thread per
        member, device work through the shared dispatcher.  Returns the
        members with .result (or .error) filled."""
        assert self.dispatcher is not None, "build() first"
        disp = self.dispatcher
        disp.reset()
        for mem in self.members:
            mem.result = mem.error = None
        # serial init prep: tiny, and it primes the shared _host_keys
        # jit buckets so member threads race on dispatch only
        import contextlib
        for mem in self.members:
            eng = mem.engine
            with obs.use_local(mem.tel) if mem.tel is not None \
                    else contextlib.nullcontext():
                eng._prepare_init(time.time(), [])

        def drive(slot: int, mem: BatchMember) -> None:
            eng = mem.engine
            eng._hstep_override = disp.hstep_factory(slot)
            try:
                if mem.tel is not None:
                    with obs.use_local(mem.tel), \
                            mem.tel.span("search", batch_slot=slot):
                        mem.result = eng.run()
                else:
                    mem.result = eng.run()
            except BaseException as ex:  # noqa: BLE001 — the member's
                # failure is ITS verdict; the cohort keeps running
                mem.error = ex
            finally:
                disp.deregister(slot)

        threads = [threading.Thread(
            target=drive, args=(i, mem),
            name=f"jaxmc-batch-m{i}", daemon=True)
            for i, mem in enumerate(self.members)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.tel.gauge("batch.occupancy", disp.max_width)
        self.tel.gauge("batch.dispatch_count", disp.dispatches)
        return self.members
